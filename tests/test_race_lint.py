"""Race & lock-discipline analysis (spark_tpu/analysis/race_lint.py) and
its runtime half (spark_tpu/utils/lockwatch.py, utils/counters.py).

Contract under test: the static model flags spawn-reachable mutations of
process-global state with no common lock, opposite-order lock nestings,
bare context-losing thread spawns in obs-scoped code, and worker-global
state without a re-init path — while `# guarded-by:` annotations,
`# race-lint: ignore[rule]` pragmas, locked-counter state, and the
sanctioned scoped_submit/par_map wrappers all stay clean; the repo
itself is clean against the checked-in baseline; lockwatch records
acquisition orders and held sets when enabled and is STRUCTURALLY
zero-overhead when idle (raw locks in every slot, maybe_wrap a
pass-through); and the locked counters lose no updates under racing
threads while validating their own guard under watching.
"""

import json
import os
import subprocess
import sys
import threading

from spark_tpu.analysis import race_lint
from spark_tpu.utils import lockwatch
from spark_tpu.utils.counters import LockedCounter, LockedCounterMap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# paths chosen to land in the rule-scoped directory sets
EXEC = "spark_tpu/exec/fx_mod.py"        # obs-scoped AND worker-shipped
API = "spark_tpu/api/fx_api.py"          # neither

_RAW_LOCK_TYPE = type(threading.Lock())


def _rules(sources):
    return [(v.rule, v.path, v.line) for v in race_lint.lint_sources(sources)]


def _only(sources, rule):
    return [(p, ln) for r, p, ln in _rules(sources) if r == rule]


# ---------------------------------------------------------------------------
# shared-mutation
# ---------------------------------------------------------------------------

UNGUARDED = (
    "import threading\n"
    "STATS = {}\n"
    "def work():\n"
    "    STATS['n'] = STATS.get('n', 0) + 1\n"
    "def start():\n"
    "    threading.Thread(target=work, daemon=True).start()\n"
)


def test_spawn_reachable_unguarded_mutation_flagged():
    hits = _only({EXEC: UNGUARDED}, "shared-mutation")
    assert hits == [(EXEC, 4)]


def test_unreachable_mutation_not_flagged():
    """No spawn site reaches the mutating function → single-threaded by
    the model, no finding."""
    src = ("STATS = {}\n"
           "def work():\n"
           "    STATS['n'] = 1\n")
    assert _only({EXEC: src}, "shared-mutation") == []


def test_common_lock_clears_shared_mutation():
    src = ("import threading\n"
           "LOCK = threading.Lock()\n"
           "STATS = {}\n"
           "def work():\n"
           "    with LOCK:\n"
           "        STATS['n'] = STATS.get('n', 0) + 1\n"
           "def start():\n"
           "    threading.Thread(target=work, daemon=True).start()\n")
    assert _only({EXEC: src}, "shared-mutation") == []


def test_guard_must_be_common_across_all_sites():
    """Two mutation sites under DIFFERENT locks: the intersection is
    empty, so both spawn-reachable sites are flagged."""
    src = ("import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "STATS = {}\n"
           "def work():\n"
           "    with A:\n"
           "        STATS['n'] = 1\n"
           "def other():\n"
           "    with B:\n"
           "        STATS['m'] = 2\n"
           "def start():\n"
           "    threading.Thread(target=work, daemon=True).start()\n"
           "    threading.Thread(target=other, daemon=True).start()\n")
    assert len(_only({EXEC: src}, "shared-mutation")) == 2


def test_guarded_by_annotation_trusted_and_exported():
    src = ("import threading\n"
           "LOCK = threading.Lock()\n"
           "STATS = {}\n"
           "def work():\n"
           "    STATS['n'] = 1  # guarded-by: LOCK\n"
           "def start():\n"
           "    threading.Thread(target=work, daemon=True).start()\n")
    model = race_lint.build_model_from_sources({EXEC: src})
    assert [v for v in model.violations if v.rule == "shared-mutation"] == []
    assert any(a["lock"].endswith("LOCK") for a in model.annotations)


def test_locked_counter_state_is_exempt():
    src = ("import threading\n"
           "from spark_tpu.utils.counters import LockedCounter\n"
           "N = LockedCounter('fx.N')\n"
           "def work():\n"
           "    N.bump()\n"
           "def start():\n"
           "    threading.Thread(target=work, daemon=True).start()\n")
    assert _only({EXEC: src}, "shared-mutation") == []


def test_pragma_suppresses_shared_mutation():
    src = UNGUARDED.replace(
        "    STATS['n'] = STATS.get('n', 0) + 1\n",
        "    # race-lint: ignore[shared-mutation] — test justification\n"
        "    STATS['n'] = STATS.get('n', 0) + 1\n")
    assert _only({EXEC: src}, "shared-mutation") == []


def test_comment_pragma_reaches_through_justification_block():
    """A comment-only pragma covers its continuation comment lines AND
    the next code line — multi-line written justifications work."""
    src = UNGUARDED.replace(
        "    STATS['n'] = STATS.get('n', 0) + 1\n",
        "    # race-lint: ignore[shared-mutation] — a justification that\n"
        "    # spans several comment lines before the flagged statement\n"
        "    STATS['n'] = STATS.get('n', 0) + 1\n")
    assert _only({EXEC: src}, "shared-mutation") == []


def test_pragma_for_wrong_rule_does_not_suppress():
    src = UNGUARDED.replace(
        "    STATS['n'] = STATS.get('n', 0) + 1\n",
        "    STATS['n'] = STATS.get('n', 0) + 1"
        "  # race-lint: ignore[lock-order]\n")
    assert len(_only({EXEC: src}, "shared-mutation")) == 1


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

INVERTED = (
    "import threading\n"
    "A = threading.Lock()\n"
    "B = threading.Lock()\n"
    "def f():\n"
    "    with A:\n"
    "        with B:\n"
    "            pass\n"
    "def g():\n"
    "    with B:\n"
    "        with A:\n"
    "            pass\n"
)


def test_opposite_nesting_orders_flagged():
    assert len(_only({EXEC: INVERTED}, "lock-order")) >= 1


def test_consistent_nesting_order_clean():
    src = INVERTED.replace(
        "def g():\n    with B:\n        with A:\n",
        "def g():\n    with A:\n        with B:\n")
    assert _only({EXEC: src}, "lock-order") == []


def test_transitive_acquire_through_calls_flagged():
    """f holds A and CALLS g which takes B; h nests B→A directly — the
    cycle only exists through the call graph."""
    src = ("import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def g():\n"
           "    with B:\n"
           "        pass\n"
           "def f():\n"
           "    with A:\n"
           "        g()\n"
           "def h():\n"
           "    with B:\n"
           "        with A:\n"
           "            pass\n")
    assert len(_only({EXEC: src}, "lock-order")) >= 1


def test_lock_order_pragma_removes_edge_from_model():
    src = INVERTED.replace(
        "    with B:\n        with A:\n",
        "    with B:\n"
        "        # race-lint: ignore[lock-order] — test justification\n"
        "        with A:\n")
    model = race_lint.build_model_from_sources({EXEC: src})
    assert [v for v in model.violations if v.rule == "lock-order"] == []
    # the suppressed nesting is an assertion it cannot happen: the
    # exported edge set (what the --race gate unions with runtime
    # observations) must not contain the pragma'd B→A edge
    assert all(not (a.endswith(".B") and b.endswith(".A"))
               for a, b in model.lock_edges)


# ---------------------------------------------------------------------------
# bare-submit
# ---------------------------------------------------------------------------

def test_bare_thread_flagged_in_obs_scoped_dirs_only():
    src = ("import threading\n"
           "def go(fn):\n"
           "    threading.Thread(target=fn, daemon=True).start()\n")
    assert len(_only({EXEC: src}, "bare-submit")) == 1
    assert _only({API: src}, "bare-submit") == []


def test_bare_submit_of_known_function_flagged():
    # the rdd._parallel shape before its conversion to scoped_submit
    src = ("def work(s):\n"
           "    return s\n"
           "def run(pool, splits):\n"
           "    return [pool.submit(work, s) for s in splits]\n")
    assert len(_only({EXEC: src}, "bare-submit")) == 1


def test_scoped_submit_and_par_map_are_sanctioned():
    src = ("from spark_tpu.obs.metrics import scoped_submit\n"
           "def work(s):\n"
           "    return s\n"
           "def run(pool, splits):\n"
           "    return [scoped_submit(pool, work, s) for s in splits]\n"
           "def run2(splits):\n"
           "    return par_map(work, splits)\n")
    assert _only({EXEC: src}, "bare-submit") == []


def test_bare_submit_inside_scoped_submit_definition_exempt():
    """The wrapper itself must call the raw pool — the exemption is what
    makes the sanctioned wrapper expressible at all."""
    src = ("def scoped_submit(pool, fn, *a):\n"
           "    return pool.submit(fn, *a)\n")
    assert _only({EXEC: src}, "bare-submit") == []


def test_bare_submit_pragma_with_justification():
    src = ("import threading\n"
           "def go(fn):\n"
           "    # race-lint: ignore[bare-submit] — process-lifetime\n"
           "    # service thread, must not inherit a query scope\n"
           "    threading.Thread(target=fn, daemon=True).start()\n")
    assert _only({EXEC: src}, "bare-submit") == []


# ---------------------------------------------------------------------------
# worker-reinit
# ---------------------------------------------------------------------------

def test_worker_global_without_reinit_path_flagged():
    src = ("CACHE = {}\n"
           "def add(k, v):\n"
           "    CACHE[k] = v\n")
    assert len(_only({EXEC: src}, "worker-reinit")) == 1
    # outside worker-shipped dirs the rule does not apply
    assert _only({API: src}, "worker-reinit") == []


def test_reinit_path_clears_worker_reinit():
    src = ("CACHE = {}\n"
           "def add(k, v):\n"
           "    CACHE[k] = v\n"
           "def reset_cache():\n"
           "    CACHE.clear()\n")
    assert _only({EXEC: src}, "worker-reinit") == []


def test_locked_counter_has_builtin_reinit_path():
    """LockedCounter.reset() IS the re-init path — exempt by kind."""
    src = ("from spark_tpu.utils.counters import LockedCounter\n"
           "N = LockedCounter('fx.N')\n"
           "def add():\n"
           "    N.bump()\n")
    assert _only({EXEC: src}, "worker-reinit") == []


# ---------------------------------------------------------------------------
# baseline semantics + the CI gate: repo clean vs checked-in baseline
# ---------------------------------------------------------------------------

def test_baseline_blocks_only_new_violations(tmp_path):
    v1 = race_lint.lint_sources({EXEC: UNGUARDED})
    path = tmp_path / "base.json"
    race_lint.write_baseline(str(path), v1)
    baseline = race_lint.load_baseline(str(path))
    assert race_lint.new_violations(v1, baseline) == []
    v2 = race_lint.lint_sources({EXEC: UNGUARDED.replace(
        "def start():",
        "def mutate2():\n    STATS['m'] = 2\ndef start():\n"
        "    threading.Thread(target=mutate2, daemon=True).start()")})
    extra = race_lint.new_violations(v2, baseline)
    # the second spawn-reachable mutation site is NEW; so is the second
    # bare Thread spawn (EXEC is obs-scoped) — both beyond the baseline
    assert any(v.rule == "shared-mutation" for v in extra)
    assert race_lint.new_violations(v1, baseline) == []


def test_repo_clean_against_checked_in_baseline():
    violations = race_lint.lint_paths([os.path.join(REPO, "spark_tpu")],
                                      repo_root=REPO)
    baseline = race_lint.load_baseline(
        os.path.join(REPO, "dev", "race_baseline.json"))
    offending = race_lint.new_violations(violations, baseline)
    msg = "\n".join(str(v) for v in offending[:20])
    assert not offending, (
        f"race_lint found NEW violations beyond dev/race_baseline.json "
        f"(fix them, suppress with '# race-lint: ignore[rule]' plus a "
        f"written justification, or regenerate via "
        f"`python dev/racecheck.py --write-baseline`):\n{msg}")


def test_repo_baseline_is_empty():
    """The concurrency debt is fully paid: the committed baseline grants
    no allowance at all, so ANY finding is a hard failure."""
    baseline = race_lint.load_baseline(
        os.path.join(REPO, "dev", "race_baseline.json"))
    assert baseline == {}


def test_static_lock_graph_is_acyclic():
    model = race_lint.build_model([os.path.join(REPO, "spark_tpu")],
                                  repo_root=REPO)
    cyc = lockwatch.find_cycle(model.lock_edges)
    assert cyc is None, f"static lock-order cycle: {cyc}"


def test_cli_runs_clean_and_fails_on_new(tmp_path):
    cli = os.path.join(REPO, "dev", "racecheck.py")
    r = subprocess.run(
        [sys.executable, cli, os.path.join(REPO, "spark_tpu"),
         "--baseline", os.path.join(REPO, "dev", "race_baseline.json")],
        capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    bad = tmp_path / "spark_tpu" / "exec" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(UNGUARDED)
    r = subprocess.run(
        [sys.executable, cli, str(tmp_path / "spark_tpu"),
         "--format", "json"],
        capture_output=True, text=True, timeout=180)
    assert r.returncode == 1
    data = json.loads(r.stdout)
    assert data["total"] >= 1
    assert data["new"][0]["rule"] in race_lint.RULES


# ---------------------------------------------------------------------------
# lockwatch: order recording, held sets, guard checks, idle overhead
# ---------------------------------------------------------------------------

class _Box:
    pass


def _two_watched(prefix):
    box = _Box()
    box.a = threading.Lock()
    box.b = threading.Lock()
    lockwatch.register(f"{prefix}.A", box, "a")
    lockwatch.register(f"{prefix}.B", box, "b")
    return box


def test_lockwatch_records_order_and_held_sets():
    box = _two_watched("t_order")
    lockwatch.enable()
    lockwatch.reset_observations()
    try:
        with box.a:
            assert lockwatch.held_locks() == ("t_order.A",)
            with box.b:
                assert lockwatch.held_locks() == ("t_order.A", "t_order.B")
        assert lockwatch.held_locks() == ()
        edges = lockwatch.order_edges()
        assert edges.get(("t_order.A", "t_order.B")) == 1
        assert ("t_order.B", "t_order.A") not in edges
        assert lockwatch.acquire_counts()["t_order.A"] == 1
    finally:
        lockwatch.disable()
        lockwatch.reset_observations()


def test_lockwatch_observed_inversion_closes_cycle():
    box = _two_watched("t_cyc")
    lockwatch.enable()
    lockwatch.reset_observations()
    try:
        with box.a:
            with box.b:
                pass
        with box.b:
            with box.a:
                pass
        cyc = lockwatch.find_cycle(lockwatch.order_edges())
        assert cyc is not None and cyc[0] == cyc[-1]
    finally:
        lockwatch.disable()
        lockwatch.reset_observations()


def test_check_guard_held_vs_missing():
    box = _two_watched("t_guard")
    lockwatch.enable()
    lockwatch.reset_observations()
    try:
        with box.a:
            assert lockwatch.check_guard("site1", "t_guard.A")
        assert not lockwatch.check_guard("site1", "t_guard.A")
        assert lockwatch.guard_checks() == {("site1", "t_guard.A"): 1}
        v = lockwatch.violations()
        assert len(v) == 1 and v[0]["site"] == "site1"
    finally:
        lockwatch.disable()
        lockwatch.reset_observations()


def test_idle_is_structurally_zero_overhead():
    """Off means OFF: raw lock objects in every registered slot, no
    proxy frame on acquire, maybe_wrap a pass-through, and the counters'
    guard self-check never reached (fast-path bool)."""
    assert not lockwatch.ENABLED
    box = _two_watched("t_idle")
    assert isinstance(box.a, _RAW_LOCK_TYPE)
    raw = threading.Lock()
    assert lockwatch.maybe_wrap("t_idle.X", raw) is raw
    before = dict(lockwatch.guard_checks())
    c = LockedCounter("t_idle.N")
    assert isinstance(c._lock, _RAW_LOCK_TYPE)
    c.bump()
    assert lockwatch.guard_checks() == before
    # enable swaps proxies in, disable restores the SAME raw locks
    lockwatch.enable()
    try:
        assert isinstance(box.a, lockwatch.WatchedLock)
        assert isinstance(c._lock, lockwatch.WatchedLock)
        assert isinstance(lockwatch.maybe_wrap("t_idle.X", raw),
                          lockwatch.WatchedLock)
    finally:
        lockwatch.disable()
        lockwatch.reset_observations()
    assert isinstance(box.a, _RAW_LOCK_TYPE)
    assert isinstance(c._lock, _RAW_LOCK_TYPE)


def test_find_cycle_ignores_self_loops():
    assert lockwatch.find_cycle([("A", "A")]) is None
    assert lockwatch.find_cycle([("A", "B"), ("B", "C")]) is None
    cyc = lockwatch.find_cycle([("A", "B"), ("B", "C"), ("C", "A")])
    assert cyc is not None and cyc[0] == cyc[-1]


# ---------------------------------------------------------------------------
# locked counters: no lost updates under racing threads, guard self-check
# ---------------------------------------------------------------------------

def _hammer(fn, threads=8, each=400):
    barrier = threading.Barrier(threads)

    def run():
        barrier.wait()
        for _ in range(each):
            fn()

    ts = [threading.Thread(target=run) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return threads * each


def test_locked_counter_loses_no_updates():
    c = LockedCounter("t_race.N")
    expect = _hammer(c.bump)
    assert c.value == expect
    c.reset()
    assert c.value == 0


def test_locked_counter_map_loses_no_updates():
    m = LockedCounterMap("t_race.M", ("a", "b"))
    expect = _hammer(lambda: m.bump("a"))
    assert m["a"] == expect and m["b"] == 0
    assert m.snapshot() == {"a": expect, "b": 0}


def test_retry_stats_regression_racing_threads():
    """The PR's satellite fix: net/transport.RETRY_STATS was a bare
    dict += (lost updates under the retry loop + par_map lanes); the
    locked replacement must count exactly under contention."""
    from spark_tpu.net.transport import RETRY_STATS
    before = RETRY_STATS["absorbed"]
    added = _hammer(lambda: RETRY_STATS.bump("absorbed"))
    assert RETRY_STATS["absorbed"] - before == added


def test_flush_overflows_regression_racing_threads():
    from spark_tpu.exec import worker_main as wm
    before = wm.FLUSH_OVERFLOWS.value
    added = _hammer(wm.FLUSH_OVERFLOWS.bump)
    assert wm.FLUSH_OVERFLOWS.value - before == added


def test_counter_bump_validates_own_guard_when_watched():
    c = LockedCounter("t_race.G")
    lockwatch.enable()
    lockwatch.reset_observations()
    try:
        c.bump()
        assert lockwatch.guard_checks() == {
            ("t_race.G", "counter.t_race.G"): 1}
        assert lockwatch.violations() == []
    finally:
        lockwatch.disable()
        lockwatch.reset_observations()


# ---------------------------------------------------------------------------
# integration: a real concurrent serve load under lockwatch
# ---------------------------------------------------------------------------

def test_concurrent_serve_load_under_lockwatch():
    """The gate's serve leg in miniature: cloned sessions collecting
    concurrently with every registered lock watched — zero guard
    violations, observed acquisition orders union the static nesting
    graph acyclic, and attribution untouched by the proxies."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.serve import QueryService
    from spark_tpu.serve.loadgen import run_serve_load

    lockwatch.enable()
    lockwatch.reset_observations()
    session = TpuSession("race-lint-it", {
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.batch.capacity": 1 << 11,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.serve.maxConcurrent": 2,
    })
    try:
        rng = np.random.default_rng(3)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 8, 1500).astype(np.int64),
            "v": rng.integers(-20, 60, 1500).astype(np.int64),
        })).createOrReplaceTempView("rl_t")
        service = QueryService(session)
        report = run_serve_load(
            service, ["select k, sum(v) s from rl_t group by k"],
            sessions=3, reps=1)
        assert not report["errors"], report["errors"]
        assert lockwatch.violations() == []
        model = race_lint.build_model([os.path.join(REPO, "spark_tpu")],
                                      repo_root=REPO)
        merged = set(lockwatch.order_edges()) \
            | {tuple(e) for e in model.lock_edges}
        assert lockwatch.find_cycle(merged) is None
        # watching was actually live during the load
        assert lockwatch.acquire_counts()
    finally:
        session.stop()
        lockwatch.disable()
        lockwatch.reset_observations()
