"""Resource observability (spark_tpu/obs/resources.py): HBM ledger,
kernel cost capture, memory budgets, and plan_lint's memory model.

Hard constraints under test: the ledger and cost capture add ZERO kernel
launches (same guard as the rest of obs/), watermarks reconcile with
batch shape/dtype metadata exactly, the memory budget pre-flights BEFORE
any dispatch, and the analyzer's predicted peak HBM bounds the measured
watermark on a real multi-operator plan."""

import time

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.obs.resources import (GLOBAL_LEDGER, DeviceLedger,
                                     MemoryBudgetExceeded)
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(31)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 11, n),
        "v": rng.integers(-40, 90, n),
    })).createOrReplaceTempView("res_t")
    return spark


Q_AGG = "select k, sum(v) sv, count(*) c from res_t where v > 0 group by k"


def _launch_delta(spark, sql):
    spark.sql(sql).toArrow()  # warm: compiles + caches + memos
    before = dict(KC.launches_by_kind)
    spark.sql(sql).toArrow()
    after = dict(KC.launches_by_kind)
    return {k: v - before.get(k, 0) for k, v in after.items()
            if v != before.get(k, 0)}


# ---------------------------------------------------------------------------
# overhead guard: the ledger adds ZERO kernel launches, fusion on and off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
def test_ledger_zero_launch_overhead(data, fusion):
    from spark_tpu.obs import resources

    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", fusion)
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.conf.set("spark.tpu.memory.ledger", "true")
        spark.conf.set("spark.tpu.metrics.kernelCost", "true")
        resources.configure(spark.conf)
        with_ledger = _launch_delta(spark, Q_AGG)
        spark.conf.set("spark.tpu.memory.ledger", "false")
        spark.conf.set("spark.tpu.metrics.kernelCost", "false")
        resources.configure(spark.conf)
        without = _launch_delta(spark, Q_AGG)
        assert with_ledger == without, (
            f"resource ledger changed kernel dispatches: {with_ledger} "
            f"vs {without}")
    finally:
        for k in ("spark.tpu.fusion.enabled", "spark.tpu.fusion.minRows",
                  "spark.tpu.memory.ledger", "spark.tpu.metrics.kernelCost"):
            spark.conf.unset(k)
        resources.configure(spark.conf)


# ---------------------------------------------------------------------------
# ledger unit semantics: exact bytes, identity refcount, release on GC
# ---------------------------------------------------------------------------

class _Col:
    def __init__(self, data, validity=None):
        self.data = data
        self.validity = validity


class _Batch:
    def __init__(self, columns, row_mask):
        self.columns = columns
        self.row_mask = row_mask


def test_ledger_watermark_exact_vs_known_nbytes():
    """Charge = column data nbytes + 1 B/row validity planes + 1 B/row
    row mask, attributed to the active query/operator scope; shared
    arrays charge once; the charge releases when the LAST owner dies."""
    from spark_tpu.obs.metrics import pop_op, push_op
    from spark_tpu.obs.tracing import pop_query, push_query

    led = DeviceLedger()
    n = 1024
    dat = np.zeros(n, dtype=np.int64)          # 8192 B
    val = np.ones(n, dtype=bool)               # 1024 B
    mask = np.ones(n, dtype=bool)              # 1024 B
    expected = dat.nbytes + val.nbytes + mask.nbytes

    qtok = push_query("resq-unit")
    otok = push_op({}, "UnitExec")
    try:
        b1 = _Batch([_Col(dat, val)], mask)
        led.register_batch(b1)
    finally:
        pop_op(otok)
        pop_query(qtok)
    assert led.bytes == expected
    assert led.peak == expected
    rec = led.query_record("resq-unit")
    assert rec["bytes"] == rec["peak"] == expected
    assert rec["ops"]["UnitExec"]["peak"] == expected

    # a second wrapper over the SAME planes must not double-charge
    b2 = _Batch([_Col(dat, val)], mask)
    led.register_batch(b2)
    assert led.bytes == expected
    assert led.verify() == []

    # first owner dies: refcounts hold the charge for the survivor
    del b1
    assert led.bytes == expected
    del b2
    assert led.bytes == 0
    assert led.peak == expected               # watermark survives release
    rec = led.query_record("resq-unit")
    assert rec["bytes"] == 0 and rec["peak"] == expected
    assert rec["registered"] == rec["released"] == expected
    assert led.verify() == []


def test_query_watermark_covers_executed_batches(data):
    """Integration: executing under a query scope charges at least the
    surviving output tiles' metadata bytes to that query, and the global
    ledger stays internally consistent."""
    from spark_tpu.obs.tracing import pop_query, push_query

    spark = data
    df = spark.sql(Q_AGG)
    qid = "resq-exec-watermark"
    tok = push_query(qid)
    try:
        parts = df.query_execution.execute()
    finally:
        pop_query(tok)
    seen, live_bytes = set(), 0
    for batch in [b for p in parts for b in (p if isinstance(p, list)
                                             else [p])]:
        planes = [batch.row_mask] + [c.data for c in batch.columns] \
            + [c.validity for c in batch.columns]
        for a in planes:
            if a is None or not hasattr(a, "dtype") or id(a) in seen:
                continue
            seen.add(id(a))
            live_bytes += int(a.size) * a.dtype.itemsize
    rec = GLOBAL_LEDGER.query_record(qid)
    assert rec is not None
    assert rec["peak"] >= rec["bytes"] > 0
    # first execution of a fresh view: every surviving output plane was
    # created (and charged) under this query's scope, so the still-held
    # balance must cover the parts' metadata bytes
    assert rec["bytes"] >= live_bytes > 0
    assert GLOBAL_LEDGER.verify() == []


# ---------------------------------------------------------------------------
# kernel cost capture
# ---------------------------------------------------------------------------

def test_kernel_cost_table_and_operator_attribution(data):
    """Every launch multiplies its captured per-launch cost onto the
    process counters, the per-kind cost table, and the executing
    operator's record (flops/bytes/gbps in EXPLAIN ANALYZE nodes)."""
    spark = data
    spark.sql(Q_AGG).toArrow()  # ensure at least one costed kernel ran
    assert KC.cost_by_kind, "cost table empty after a real query"
    assert KC.bytes_total > 0
    counters = KC.counters()
    assert counters["kernel_cache.bytes_accessed"] > 0
    for kind, ent in KC.cost_by_kind.items():
        assert ent["kernels"] >= 1 and ent["launches"] >= 1, kind
        assert ent["bytes"] >= 0.0 and ent["flops"] >= 0.0

    report = spark.sql(Q_AGG).query_execution.analyzed_report()
    costed = [nd for nd in report.nodes if nd.get("bytes")]
    assert costed, "no operator carries captured bytes accessed"
    assert any(nd.get("gbps") for nd in costed), \
        "bytes present but achieved-GB/s never derived"
    text = report.render()
    assert "bytes=" in text


def test_batch_cost_scope_scales_operator_record():
    """Per-batch live-row scaling unit semantics: inside the scope the
    per-identity constant cost multiplies by rows/capacity onto the
    operator record; outside (or with an unknown live count) it lands
    unscaled."""
    from spark_tpu.obs import metrics as M

    class _B:
        def __init__(self, rows, cap):
            self._num_rows = rows
            self.capacity = cap

    cost = {"flops": 100.0, "bytes": 4096.0}
    rec = M.new_op_record()
    tok = M.push_op(rec, "X")
    try:
        with M.batch_cost_scope(_B(1024, 4096)):
            M.record_kernel_launch("pipeline", cost)
        with M.batch_cost_scope(_B(None, 4096)):  # unknown live count
            M.record_kernel_launch("pipeline", cost)
        M.record_kernel_launch("pipeline", cost)  # no scope
    finally:
        M.pop_op(tok)
    assert rec["bytes"] == 4096.0 * 0.25 + 4096.0 + 4096.0
    assert rec["flops"] == 100.0 * 0.25 + 100.0 + 100.0
    assert rec["launch_total"] == 3  # launches never scale


def test_sparse_batch_cost_scaled_on_operator_record(spark):
    """PR 7 follow-on: a batch whose live rows underfill its capacity
    bucket attributes SCALED bytes to the dispatching operator — EXPLAIN
    ANALYZE's achieved GB/s stops overstating sparse batches. The
    process-global cost counters stay unscaled (they mirror the cost
    model's per-launch bytes)."""
    n = 2560  # bucket_capacity(2560) = 4096 → live fraction 0.625
    spark.createDataFrame(pa.table({
        "a": np.arange(n, dtype=np.int64),
        "b": np.arange(n, dtype=np.int64) * 3,
    })).createOrReplaceTempView("sparse_t")

    def q():
        return spark.sql("select a + b as c from sparse_t")

    q().toArrow()  # warm: compile + capture the kernel cost
    ent = KC.cost_by_kind.get("pipeline")
    if ent is None or ent["bytes"] <= 0:
        pytest.skip("kernel cost capture unavailable on this backend")
    before = dict(ent)
    df = q()
    df.toArrow()
    after = KC.cost_by_kind["pipeline"]
    launches = after["launches"] - before["launches"]
    unscaled = after["bytes"] - before["bytes"]
    assert launches == 1 and unscaled > 0
    node = next(nd for nd in df.query_execution.plan_graph()
                if nd["op"] == "ComputeExec")
    frac = n / 4096
    assert node["bytes"] == pytest.approx(unscaled * frac, rel=1e-6), \
        (node["bytes"], unscaled, frac)


# ---------------------------------------------------------------------------
# memory budget pre-flight (admission control)
# ---------------------------------------------------------------------------

def test_budget_preflight_raises_before_dispatch(data):
    spark = data
    spark.conf.set("spark.tpu.memory.budget", "1024")
    try:
        before = KC.launches
        with pytest.raises(MemoryBudgetExceeded) as ei:
            spark.sql(Q_AGG).toArrow()
        msg = str(ei.value)
        assert "largest stage" in msg and "Exec" in msg, msg
        assert "spark.tpu.memory.budget" in msg
        assert KC.launches == before, \
            "an over-budget query dispatched kernels before failing"
    finally:
        spark.conf.unset("spark.tpu.memory.budget")
    # same query admits fine once the budget is lifted
    assert spark.sql(Q_AGG).toArrow().num_rows > 0


def test_budget_admits_within_budget_plan(data):
    spark = data
    spark.conf.set("spark.tpu.memory.budget", str(1 << 34))
    try:
        assert spark.sql(Q_AGG).toArrow().num_rows > 0
    finally:
        spark.conf.unset("spark.tpu.memory.budget")


# ---------------------------------------------------------------------------
# plan_lint memory model vs measured watermark (TPC-DS mini q3)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
def test_predicted_peak_bounds_measured_watermark_q3(spark, fusion):
    """EXPLAIN ANALYZE on TPC-DS mini q3: a per-stage predicted peak-HBM
    line reconciled against the ledger's measured watermark — the model
    is an upper bound on engine-held tiles, so measured must stay within
    it (plus slack for rounding), with zero unexplained drift."""
    from tests.test_plan_analysis import Q3
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.fusion.enabled", fusion)
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        report = spark.sql(Q3).query_execution.analyzed_report()
        assert not report.has_unexplained_drift, report.render()
        mem = report.memory
        assert mem.get("predicted_peak"), "memory model produced no peak"
        assert mem.get("measured_peak") is not None
        assert mem["measured_peak"] > 0
        assert mem["measured_peak"] <= mem["predicted_peak"] * 1.25, (
            f"measured watermark {mem['measured_peak']} blew through the "
            f"model's predicted peak {mem['predicted_peak']}")
        assert mem.get("per_stage"), "no per-stage predicted-HBM rows"
        assert any(st.get("measured") for st in mem["per_stage"]), \
            "no stage carries a measured per-operator watermark"
        text = report.render()
        assert "memory (HBM" in text and "query peak" in text
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


def test_analysis_report_renders_predicted_hbm(data):
    rep = data.sql(Q_AGG).query_execution.analysis_report()
    assert rep.predicted_peak_hbm and rep.predicted_peak_hbm > 0
    assert any(s.get("hbm_bytes") for s in rep.stages)
    assert "predicted peak HBM" in rep.render()
    assert rep.to_dict()["predicted_peak_hbm"] == rep.predicted_peak_hbm


# ---------------------------------------------------------------------------
# heartbeat flush budget (satellite: wide-executor payload cap)
# ---------------------------------------------------------------------------

def test_flush_budget_trims_carries_and_counts_overflow():
    """With the per-beat byte budget exhausted, later tasks ship
    counter-only deltas: no op-record breakdown, no spans — but their
    closed spans stay in the carry buffer (never dropped) and ship once
    the budget allows; every trim increments the overflow counter."""
    from spark_tpu.config import SQLConf
    from spark_tpu.exec import worker_main as wm
    from spark_tpu.obs.metrics import get_or_create_op_record

    conf = SQLConf()
    conf.set("spark.tpu.heartbeat.flushBudget", "1")   # starve every beat
    states = [wm.begin_stage_obs(conf, query_id="fbq", stage_id=f"s{i}",
                                 task_id=i) for i in range(2)]
    try:
        assert all(s is not None for s in states)
        for s in states:
            ent = get_or_create_op_record(s["rec"], f"op{s['task_id']}")
            ent["rows"] += 100
            ent["batches"] += 1
            with s["tracer"].span(f"work{s['task_id']}"):
                pass
        base = wm.FLUSH_OVERFLOWS.value
        out = wm.collect_live_obs()
        mine = [d for d in out if d["query"] == "fbq"]
        assert len(mine) == 2
        trimmed = [d for d in mine if d["op_records"] is None]
        fat = [d for d in mine if d["op_records"] is not None]
        assert trimmed and fat, "budget=1 B should trim all but the first"
        # counter totals survive the trim
        assert all(d["rows"] == 100 and d["batches"] == 1 for d in mine)
        assert all(not d["spans_closed"] for d in trimmed)
        assert wm.FLUSH_OVERFLOWS.value > base
        wm.ack_live_obs()
        # the trimmed task's spans were carried, not dropped: lift the
        # budget and they ship on the next beat
        for s in states:
            s["flush_budget"] = 0
        out2 = wm.collect_live_obs()
        by_task = {d["task"]: d for d in out2 if d["query"] == "fbq"}
        carried = [sp for d in by_task.values()
                   for sp in d["spans_closed"]]
        assert any(sp.get("name", "").startswith("work")
                   for sp in carried), \
            "trimmed spans never shipped after the budget was lifted"
        wm.ack_live_obs()
    finally:
        for s in states:
            wm.finish_stage_obs(s)


def test_overflow_counter_surfaces_in_live_status():
    from spark_tpu.obs.live import LiveObs

    live = LiveObs()
    live.on_heartbeat("w-1", [], hbm={"bytes": 4096, "peak": 8192},
                      overflows=3)
    live.on_heartbeat("w-2", [], hbm={"bytes": 100, "peak": 200})
    snap = live.snapshot()
    assert snap["flush_overflows"] == 3
    ex = snap["executors"]
    assert ex["w-1"]["hbm_bytes"] == 4096
    assert ex["w-1"]["hbm_peak"] == 8192
    assert ex["w-1"]["overflows"] == 3
    assert ex["w-2"]["overflows"] == 0


# ---------------------------------------------------------------------------
# console reporter: per-executor utilization rows
# ---------------------------------------------------------------------------

def test_console_reporter_renders_executor_rows():
    from spark_tpu.obs.live import ConsoleProgressReporter, LiveObs

    live = LiveObs()
    live.on_heartbeat("exec-9", [{
        "query": "cq", "stage": "s0", "task": 0, "seq": 1,
        "rows": 500, "rows_exact": True, "batches": 2, "launches": 4,
        "compile_ms": 0.0, "kernel_kinds": {"pipeline": 4},
        "op_records": {}, "spans_closed": [], "open_spans": [],
    }], hbm={"bytes": 3 << 20, "peak": 4 << 20}, overflows=2)
    rep = ConsoleProgressReporter(live, stream=None, interval=99)
    line = rep.render_line()
    assert "exec-9" in line
    assert "hbm=3.0MiB" in line
    assert "obs-trims=2" in line
    assert "1 task" in line


# ---------------------------------------------------------------------------
# cluster round-trip: executor watermarks over the heartbeat path
# ---------------------------------------------------------------------------

def _cluster_table():
    rng = np.random.default_rng(47)
    n = 6000
    return pa.table({"k": rng.integers(0, 7, n),
                     "v": rng.integers(-30, 70, n)})


@pytest.fixture(scope="module")
def cluster_spark():
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("resource-cluster", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.heartbeat.interval": "0.1",
    })
    cluster = LocalCluster(num_workers=2,
                           heartbeat_interval=0.1)
    s.attachSqlCluster(cluster)
    s.createDataFrame(_cluster_table()).createOrReplaceTempView("cres_t")
    yield s
    s.stop()


def _cluster_query(s):
    import spark_tpu.api.functions as F

    return (s.table("cres_t").filter(F.col("v") > 0).repartition(2)
            .groupBy("k").agg(F.sum("v").alias("sv")))


def test_cluster_heartbeat_ships_executor_hbm(cluster_spark):
    """Worker processes report their device-ledger occupancy on every
    heartbeat; the driver's LiveObs shows HBM per executor."""
    s = cluster_spark
    _cluster_query(s).toArrow()
    deadline = time.time() + 5.0
    workers = {}
    while time.time() < deadline:
        workers = {eid: e for eid, e in s.live_obs.executors.items()
                   if eid != "driver" and e.get("hbm_bytes") is not None}
        if workers:
            break
        time.sleep(0.1)
    assert workers, "no worker heartbeat carried an HBM snapshot"
    for eid, e in workers.items():
        assert e["hbm_bytes"] >= 0
        assert e["hbm_peak"] >= e["hbm_bytes"]
    util = s.live_obs.executor_utilization()
    assert any(eid in util for eid in workers)


def test_cluster_explain_analyze_merges_remote_hbm(cluster_spark):
    """Map tasks ship their worker-process HBM record with the task
    result; EXPLAIN ANALYZE's memory section reports per-executor remote
    peaks next to the driver watermark — and stays drift-free."""
    s = cluster_spark
    report = _cluster_query(s).query_execution.analyzed_report()
    assert not report.has_unexplained_drift, report.render()
    mem = report.memory
    assert mem.get("remote"), \
        "no worker HBM record reached the memory section"
    for eid, rec in mem["remote"].items():
        assert rec.get("peak", 0) > 0, (eid, rec)
    assert "workers={" in report.render()
    assert GLOBAL_LEDGER.verify() == []
