"""explode(split(...)) — the wordcount capability (reference:
sql/core GenerateExec + Explode generator)."""

import pyarrow as pa


def _lines_df(spark):
    t = pa.table({"id": [1, 2, 3],
                  "line": ["the quick brown fox", "the lazy dog", "the"]})
    return spark.createDataFrame(t)


def test_sql_wordcount(spark):
    _lines_df(spark).createOrReplaceTempView("lines")
    out = spark.sql("""
        SELECT word, count(*) AS n
        FROM (SELECT explode(split(line, ' ')) AS word FROM lines)
        GROUP BY word ORDER BY n DESC, word
    """).collect()
    assert tuple(out[0].values()) == ("the", 3)
    counts = {r["word"]: r["n"] for r in out}
    assert counts == {"the": 3, "quick": 1, "brown": 1, "fox": 1,
                      "lazy": 1, "dog": 1}


def test_explode_keeps_other_columns(spark):
    from spark_tpu.api import functions as F

    df = _lines_df(spark)
    out = df.select(df["id"], F.explode(F.split(df["line"], " ")).alias("w")) \
            .collect()
    rows = [tuple(r.values()) for r in out]
    assert rows.count((1, "the")) == 1
    assert rows.count((3, "the")) == 1
    assert len(rows) == 4 + 3 + 1


def test_explode_with_nulls_and_filter(spark):
    t = pa.table({"line": ["a b", None, "c"]})
    df = spark.createDataFrame(t)
    df.createOrReplaceTempView("nl")
    out = spark.sql(
        "SELECT explode(split(line, ' ')) AS w FROM nl").collect()
    assert sorted(x["w"] for x in out) == ["a", "b", "c"]
    out2 = spark.sql(
        "SELECT w FROM (SELECT explode(split(line, ' ')) AS w FROM nl) "
        "WHERE w <> 'b'").collect()
    assert sorted(x["w"] for x in out2) == ["a", "c"]


def test_split_regex_delimiter(spark):
    t = pa.table({"s": ["a,b;c", "x"]})
    spark.createDataFrame(t).createOrReplaceTempView("rx")
    out = spark.sql(
        "SELECT explode(split(s, '[,;]')) AS p FROM rx").collect()
    assert sorted(x["p"] for x in out) == ["a", "b", "c", "x"]


def test_explode_array_column(spark):
    spark.createDataFrame(pa.table({
        "k": ["a", "a", "b"], "v": [1, 2, 3]})) \
        .createOrReplaceTempView("cl")
    out = spark.sql("""
        SELECT k, explode(l) AS e FROM
          (SELECT k, collect_list(v) AS l FROM cl GROUP BY k)
        ORDER BY k, e""").collect()
    assert [tuple(r.values()) for r in out] == \
        [("a", 1), ("a", 2), ("b", 3)]
