"""gRPC transport tests (role of the reference's network-common suites:
TransportClientFactorySuite, auth via SaslIntegrationSuite) and the
join-by-address cluster path (two process-groups on one machine standing
in for two hosts — the standalone-worker deployment model)."""

import os
import pickle
import subprocess
import sys
import threading
import time

import pytest

from spark_tpu.net.transport import (
    RemoteRpcError, RpcClient, RpcServer, RpcUnavailableError,
)


@pytest.fixture()
def server():
    s = RpcServer("tok")
    s.register("echo", lambda p: p)
    s.register("boom", lambda p: 1 / 0)
    s.register_stream("chunks", lambda p: iter([b"a" * 10, b"b" * 10, b"c"]))
    s.start()
    yield s
    s.stop()


def test_unary_roundtrip(server):
    with RpcClient(server.address, "tok") as c:
        assert c.call("echo", b"hello") == b"hello"
        assert c.call("echo", b"") == b""


def test_large_payload(server):
    big = os.urandom(8 << 20)
    with RpcClient(server.address, "tok") as c:
        assert c.call("echo", big) == big


def test_handler_error_propagates(server):
    with RpcClient(server.address, "tok") as c:
        with pytest.raises(RemoteRpcError, match="ZeroDivisionError"):
            c.call("boom", b"")


def test_stream(server):
    with RpcClient(server.address, "tok") as c:
        assert b"".join(c.stream("chunks", b"")) == b"a" * 10 + b"b" * 10 + b"c"


def test_bad_token_rejected(server):
    # auth failure is deterministic, NOT executor death — it must not
    # map to RpcUnavailableError or the cluster would kill the worker
    with RpcClient(server.address, "wrong") as c:
        with pytest.raises(RemoteRpcError, match="UNAUTHENTICATED"):
            c.call("echo", b"x")


def test_unknown_method(server):
    with RpcClient(server.address, "tok") as c:
        with pytest.raises(RemoteRpcError):
            c.call("nope", b"x")


def test_oversized_payload_is_deterministic_error(server):
    # a payload over the transport cap must surface as RemoteRpcError
    # (deterministic) so the task layer fails the job instead of
    # tearing down healthy executors one by one
    big = b"x" * (257 << 20)
    with RpcClient(server.address, "tok") as c:
        with pytest.raises(RemoteRpcError, match="RESOURCE_EXHAUSTED"):
            c.call("echo", big)


def test_dead_peer_fails_fast(server):
    addr = server.address
    server.stop()
    with RpcClient(addr, "tok") as c:
        t0 = time.monotonic()
        with pytest.raises(RpcUnavailableError):
            c.call("echo", b"x", timeout=10)
        assert time.monotonic() - t0 < 10


def test_concurrent_calls(server):
    results = []
    with RpcClient(server.address, "tok") as c:
        def worker(i):
            results.append(c.call("echo", str(i).encode()))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert sorted(results) == sorted(str(i).encode() for i in range(16))


# ---------------------------------------------------------------------------
# join-by-address: a second "host" process-group joins a running cluster
# ---------------------------------------------------------------------------

def test_external_worker_joins_by_address():
    from spark_tpu.exec.cluster import LocalCluster, worker_env

    c = LocalCluster(num_workers=1)
    try:
        # boot an EXTERNAL worker exactly as a remote host would: only the
        # driver address + cluster secret, no shared in-process state
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_tpu.exec.worker_main"],
            env=worker_env(c.driver_addr, c.token, host_label="hostB"))
        try:
            deadline = time.monotonic() + 30
            while c.num_alive() < 2 and time.monotonic() < deadline:
                time.sleep(0.2)
            assert c.num_alive() == 2
            hosts = {e.host for e in c.registry.alive()}
            assert hosts == {"localhost", "hostB"}
            # tasks round-robin across both "hosts"
            pids = set(c.map(lambda _: __import__("os").getpid(), range(4)))
            assert len(pids) == 2 and proc.pid in pids
        finally:
            proc.terminate()
            proc.wait(timeout=10)
    finally:
        c.stop()


def test_two_host_sql_query():
    """Distributed SQL across two process-groups ('hosts'): map stages on
    either group, shuffle blocks fetched across the group boundary."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster, worker_env

    s = TpuSession("twohost", {"spark.sql.shuffle.partitions": "4"})
    c = LocalCluster(num_workers=1)
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_tpu.exec.worker_main"],
        env=worker_env(c.driver_addr, c.token, host_label="hostB"))
    try:
        deadline = time.monotonic() + 30
        while c.num_alive() < 2 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert c.num_alive() == 2
        s.attachSqlCluster(c)
        n = 10000
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 25, n)
        s.createDataFrame(pa.table({"k": keys, "v": np.ones(n)})) \
            .createOrReplaceTempView("thfact")
        df = s.table("thfact").repartition(4).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        import collections

        assert got == dict(collections.Counter(keys.tolist()))
        assert s._metrics.snapshot()["counters"].get(
            "scheduler.stages_remote", 0) >= 1
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        s.stop()
