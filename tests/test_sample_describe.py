"""Sampling, EXTRACT syntax, and describe() tests."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F


def test_sample_deterministic_fraction(spark):
    df = spark.range(0, 10_000, 1, 4)
    s1 = df.sample(0.1, seed=7).count()
    s2 = df.sample(0.1, seed=7).count()
    assert s1 == s2
    assert 800 < s1 < 1200


def test_sample_composes(spark):
    df = spark.range(0, 1000, 1, 2).sample(0.5, seed=1)
    out = df.agg(F.count("*").alias("c")).toArrow().to_pydict()
    assert 350 < out["c"][0] < 650


def test_extract_syntax(spark):
    out = spark.sql(
        "SELECT EXTRACT(year FROM DATE '2021-07-04') AS y, "
        "EXTRACT(month FROM DATE '2021-07-04') AS m, "
        "EXTRACT(hour FROM TIMESTAMP '2021-07-04 09:30:00') AS h"
    ).toArrow().to_pydict()
    assert out["y"] == [2021]
    assert out["m"] == [7]
    assert out["h"] == [9]


def test_describe(spark):
    df = spark.createDataFrame(pa.table({
        "v": [1.0, 2.0, 3.0, 4.0], "name": ["a", "b", "c", "d"]}))
    out = df.describe().toArrow().to_pydict()
    assert out["summary"] == ["count", "mean", "stddev", "min", "max"]
    assert out["v"][0] == "4"
    assert float(out["v"][1]) == 2.5
    assert "name" not in out  # non-numeric excluded


def test_stat_functions(spark):
    import numpy as np

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, 400)
    y = 3 * x + rng.normal(0, 0.1, 400)
    cat = ["a" if v > 0 else "b" for v in x]
    grp = ["hi" if v > 1 else "lo" for v in y]
    df = spark.createDataFrame(pa.table({"x": x, "y": y, "cat": cat,
                                         "grp": grp}))

    assert abs(df.stat.corr("x", "y") - np.corrcoef(x, y)[0, 1]) < 1e-6
    assert abs(df.stat.cov("x", "y") - np.cov(x, y, ddof=1)[0, 1]) < 1e-6

    qs = df.stat.approxQuantile("x", [0.0, 0.5, 1.0])
    assert qs[0] == x.min() and qs[2] == x.max()
    assert abs(qs[1] - np.median(x)) < 0.2

    fi = df.stat.freqItems(["cat"], support=0.3)
    assert set(fi["cat_freqItems"]) == {"a", "b"}

    ct = df.stat.crosstab("cat", "grp").toArrow().to_pydict()
    assert ct["cat_grp"] == ["a", "b"]
    assert sum(ct["hi"]) + sum(ct["lo"]) == 400

    sb = df.stat.sampleBy("cat", {"a": 1.0, "b": 0.0}, seed=1)
    got = sb.toArrow().to_pydict()["cat"]
    assert set(got) == {"a"}


def test_df_rdd_bridge(spark):
    df = spark.createDataFrame(pa.table({"x": [1, 2, 3], "s": ["a", "b", "c"]}))
    r = df.rdd
    rows = r.collect()
    assert [row.x for row in rows] == [1, 2, 3]
    assert r.map(lambda row: row.x * 10).sum() == 60


def test_tablesample(spark):
    df = spark.createDataFrame(pa.table({"x": list(range(1000))}))
    df.createOrReplaceTempView("ts_t")
    n = spark.sql(
        "SELECT count(*) AS c FROM ts_t TABLESAMPLE (10 PERCENT)"
    ).toArrow().to_pydict()["c"][0]
    assert 40 < n < 200
    n2 = spark.sql(
        "SELECT count(*) AS c FROM ts_t TABLESAMPLE (50 ROWS)"
    ).toArrow().to_pydict()["c"][0]
    assert n2 == 50
