"""Memory discipline: device budget → external sort / grace join, and
host shuffle-buffer spill (roles of UnifiedMemoryManager.scala,
UnsafeExternalSorter.java, and the grace-hash fallback of
HashedRelation; see spark_tpu/exec/memory.py)."""

import glob
import os
import tempfile

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu import TpuSession


def _session(extra=None):
    conf = {"spark.sql.shuffle.partitions": 1,
            "spark.tpu.batch.capacity": 1 << 12}
    conf.update(extra or {})
    return TpuSession("mem-tests", conf)


@pytest.fixture()
def tiny_budget_session():
    # budget small enough that >~35k-row partitions take the external path
    # (tile_rows floors at 1<<14)
    s = _session({"spark.tpu.memory.deviceBudgetBytes": 1 << 19})
    yield s
    s.stop()


def _ext_passes(s):
    return s._metrics.snapshot()["counters"].get("sort.external.passes", 0)


def test_external_sort_ints(tiny_budget_session):
    s = tiny_budget_session
    rng = np.random.default_rng(0)
    n = 100_000
    vals = rng.integers(-1_000_000, 1_000_000, n)
    df = s.createDataFrame(pa.table({"k": vals}))
    before = _ext_passes(s)
    out = df.orderBy("k").toArrow().column("k").to_numpy()
    assert _ext_passes(s) > before, "external sort path did not run"
    np.testing.assert_array_equal(out, np.sort(vals))


def test_external_sort_desc_with_nulls(tiny_budget_session):
    s = tiny_budget_session
    rng = np.random.default_rng(1)
    n = 80_000
    vals = rng.integers(0, 10_000, n).astype(object)
    null_at = rng.random(n) < 0.05
    vals[null_at] = None
    df = s.createDataFrame(pa.table({"k": pa.array(list(vals),
                                                   pa.int64())}))
    out = df.orderBy(F.col("k").desc_nulls_last()).toArrow()
    got = out.column("k").to_pylist()
    nn = sorted([v for v in vals if v is not None], reverse=True)
    assert got[:len(nn)] == nn
    assert all(v is None for v in got[len(nn):])
    assert len(got) == n


def test_external_sort_multikey_ties_across_buckets(tiny_budget_session):
    # leading key has only 7 distinct values → every bucket boundary is a
    # tie; secondary ordering must still hold globally
    s = tiny_budget_session
    rng = np.random.default_rng(2)
    n = 60_000
    k1 = rng.integers(0, 7, n)
    k2 = rng.integers(0, 1_000_000, n)
    df = s.createDataFrame(pa.table({"a": k1, "b": k2}))
    out = df.orderBy("a", F.col("b").desc()).toArrow()
    ga, gb = out.column("a").to_numpy(), out.column("b").to_numpy()
    order = np.lexsort((-k2, k1))
    np.testing.assert_array_equal(ga, k1[order])
    np.testing.assert_array_equal(gb, k2[order])


def test_external_sort_strings(tiny_budget_session):
    s = tiny_budget_session
    rng = np.random.default_rng(3)
    n = 50_000
    pool = [f"s{i:06d}" for i in range(5_000)]
    vals = [pool[i] for i in rng.integers(0, len(pool), n)]
    df = s.createDataFrame(pa.table({"k": vals}))
    out = df.orderBy("k").toArrow().column("k").to_pylist()
    assert out == sorted(vals)


def test_grace_join_inner_and_outer(tiny_budget_session):
    s = tiny_budget_session
    rng = np.random.default_rng(4)
    n_left, n_right = 30_000, 60_000
    lk = rng.integers(0, 50_000, n_left)
    rk = rng.integers(0, 50_000, n_right)
    left = s.createDataFrame(pa.table({"k": lk, "lv": np.arange(n_left)}))
    right = s.createDataFrame(pa.table({"k": rk, "rv": np.arange(n_right)}))
    before = s._metrics.snapshot()["counters"].get("join.grace.fragments", 0)
    out = (left.join(right, "k")
           .groupBy().agg(F.count("*").alias("n"),
                          F.sum("lv").alias("sl"),
                          F.sum("rv").alias("sr"))).toArrow().to_pydict()
    after = s._metrics.snapshot()["counters"].get("join.grace.fragments", 0)
    assert after > before, "grace join path did not run"

    # oracle
    from collections import defaultdict

    rmap = defaultdict(list)
    for i, k in enumerate(rk):
        rmap[int(k)].append(i)
    n = sl = sr = 0
    for i, k in enumerate(lk):
        for j in rmap.get(int(k), ()):
            n += 1
            sl += i
            sr += j
    assert out["n"] == [n]
    assert out["sl"] == [sl]
    assert out["sr"] == [sr]


def test_grace_resplit_not_degenerate():
    """Re-hashing an already-hash-partitioned partition must spread rows
    across fragments: the grace split uses a different seed than the
    exchange, otherwise h % nfrag is constant within a partition whenever
    nfrag divides the exchange partition count."""
    from spark_tpu.columnar.batch import ColumnarBatch
    from spark_tpu.exec.context import ExecContext
    from spark_tpu.exec.shuffle import shuffle_hash
    from spark_tpu.types import StructField, StructType, int64

    rng = np.random.default_rng(7)
    schema = StructType([StructField("k", int64)])
    batch = ColumnarBatch.from_numpy(
        schema, [rng.integers(0, 1 << 40, 8192).astype(np.int64)])
    ctx = ExecContext()
    parts = shuffle_hash([[batch]], [0], 8, schema, ctx)  # default seed
    # take one exchange output partition and grace-split it 4 ways
    part = max(parts, key=lambda p: sum(b.num_rows() for b in p))
    frags = shuffle_hash([part], [0], 4, schema, ctx, seed=0x9E3779B9)
    filled = [sum(b.num_rows() for b in f) for f in frags]
    assert sum(1 for n in filled if n > 0) >= 3, filled
    assert max(filled) < sum(filled), "all rows landed in one fragment"


def test_grace_join_left_anti(tiny_budget_session):
    s = tiny_budget_session
    rng = np.random.default_rng(5)
    lk = rng.integers(0, 40_000, 20_000)
    rk = rng.integers(0, 40_000, 60_000)
    left = s.createDataFrame(pa.table({"k": lk}))
    right = s.createDataFrame(pa.table({"k": rk, "rv": np.arange(60_000)}))
    out = left.join(right, "k", "left_anti").toArrow().column("k").to_numpy()
    expected = lk[~np.isin(lk, rk)]
    np.testing.assert_array_equal(np.sort(out), np.sort(expected))


def test_shuffle_spill_bounded_and_correct():
    spill_dir = tempfile.mkdtemp(prefix="sparktpu-spill-test-")
    s = _session({
        "spark.sql.shuffle.partitions": 4,
        "spark.tpu.mesh.enabled": "false",  # force the host shuffle path
        "spark.tpu.shuffle.spillBytes": 1 << 12,  # 4 KiB → spill a lot
        "spark.local.dir": spill_dir,
        "spark.tpu.batch.capacity": 1 << 10,
    })
    try:
        rng = np.random.default_rng(6)
        n = 50_000
        k = rng.integers(0, 1_000_000, n)
        df = s.createDataFrame(pa.table({"k": k}))
        out = (df.repartition(4, "k").orderBy("k")
               .toArrow().column("k").to_numpy())
        counters = s._metrics.snapshot()["counters"]
        assert counters.get("shuffle.spill.files", 0) > 0, \
            "spill never triggered"
        np.testing.assert_array_equal(out, np.sort(k))
        # spill files are consumed and unlinked by build()
        leftovers = glob.glob(os.path.join(spill_dir, "*.npz"))
        assert leftovers == []
    finally:
        s.stop()


def test_budget_resolution_explicit_and_floor():
    from spark_tpu.config import SQLConf
    from spark_tpu.exec.memory import MemoryManager, schema_row_bytes
    from spark_tpu.types import StructType, StructField, int64

    conf = SQLConf()
    conf.set("spark.tpu.memory.deviceBudgetBytes", str(1 << 30))
    m = MemoryManager(conf)
    schema = StructType([StructField("a", int64), StructField("b", int64)])
    rows = m.tile_rows(schema, amplification=3)
    assert rows == (1 << 30) // (schema_row_bytes(schema) * 3)
    conf.set("spark.tpu.memory.deviceBudgetBytes", "1")
    # explicit caps may push below the auto floor, but never below 1<<10
    assert MemoryManager(conf).tile_rows(schema) == 1 << 10


@pytest.mark.slow
def test_tpcds_queries_under_capped_budget():
    """TPC-DS q3/q19 produce identical results with the device budget
    capped low enough to force every blocking operator through its
    multi-pass path (external sort, grace join, blockwise agg)."""
    from tests.tpcds.datagen import gen_tpcds_full
    from tests.tpcds.oracle import strip_trailing_limit

    here = os.path.dirname(os.path.abspath(__file__))
    tables = gen_tpcds_full(scale=0.1)
    results = {}
    for budget in (0, 1 << 16):  # auto vs ~64 KiB cap
        s = _session({
            "spark.sql.shuffle.partitions": 4,
            "spark.tpu.batch.capacity": 1 << 12,
            "spark.tpu.memory.deviceBudgetBytes": budget,
        })
        try:
            for name, tab in tables.items():
                s.createDataFrame(tab).createOrReplaceTempView(name)
            for q in ("q3", "q19"):
                sql = strip_trailing_limit(
                    open(os.path.join(here, "tpcds", "queries",
                                      f"{q}.sql")).read())
                t = s.sql(sql).toArrow()
                results.setdefault(q, []).append(
                    sorted(tuple(r.values()) for r in t.to_pylist()))
        finally:
            s.stop()
    for q, (auto_r, capped_r) in results.items():
        assert auto_r == capped_r, f"{q}: capped-budget results differ"
