"""Changelog state store: O(delta) commits, snapshot compaction, replay
(RocksDBStateStoreProvider + StateStoreChangelog roles)."""

import os
import tempfile

import pyarrow as pa

from spark_tpu.streaming.state import StateStore


def _mk_state(n, start=0):
    return pa.table({"k": list(range(start, start + n)),
                     "v": [i * 10 for i in range(start, start + n)]})


def test_changelog_commit_is_o_delta_and_replays():
    d = tempfile.mkdtemp(prefix="sparktpu-state-")
    s = StateStore(d, snapshot_interval=5)

    # v1: initial snapshot of 1000 keys
    t = _mk_state(1000)
    s.commit(1, t)
    snap_size = os.path.getsize(os.path.join(s.dir, "1.parquet"))

    # v2..v5: each touches 10 keys (5 updates + 5 inserts), state grows
    delta_sizes = []
    for v in range(2, 6):
        n = 1000 + (v - 1) * 5
        t = _mk_state(n)
        touched = set((k,) for k in range(5)) | \
            set((k,) for k in range(n - 5, n))
        s.commit(v, t, upsert_keys=touched, key_names=["k"])
        p = os.path.join(s.dir, f"{v}.delta.arrow")
        assert os.path.exists(p), f"v{v} should be a changelog commit"
        delta_sizes.append(os.path.getsize(p))
    # a 10-row delta must be far smaller than the 1000-row snapshot
    assert max(delta_sizes) < snap_size / 2
    # commit cost flat: delta size does not grow with state size
    assert max(delta_sizes) < 2 * min(delta_sizes) + 1024

    # v6: compaction interval reached → full snapshot again
    t = _mk_state(1030)
    s.commit(6, t, upsert_keys={(0,)}, key_names=["k"])
    assert os.path.exists(os.path.join(s.dir, "6.parquet"))

    # recovery mid-interval: replay snapshot v1 + deltas v2..v5
    r = StateStore(d, snapshot_interval=5)
    r.load(5)
    want = _mk_state(1020)
    got = dict(zip(r.table.column("k").to_pylist(),
                   r.table.column("v").to_pylist()))
    expect = dict(zip(want.column("k").to_pylist(),
                      want.column("v").to_pylist()))
    assert got == expect


def test_changelog_deletes_replay():
    d = tempfile.mkdtemp(prefix="sparktpu-state-")
    s = StateStore(d, snapshot_interval=10)
    s.commit(1, _mk_state(100))
    # v2: update key 0, delete keys 90..99
    t = pa.table({"k": list(range(90)), "v": [0] + [i * 10
                                                    for i in range(1, 90)]})
    s.commit(2, t, upsert_keys={(0,)},
             delete_keys=[(k,) for k in range(90, 100)], key_names=["k"])
    r = StateStore(d)
    r.load(2)
    ks = sorted(r.table.column("k").to_pylist())
    assert ks == list(range(90))
    got = dict(zip(r.table.column("k").to_pylist(),
                   r.table.column("v").to_pylist()))
    assert got[0] == 0 and got[1] == 10


def test_gc_retains_two_snapshots():
    d = tempfile.mkdtemp(prefix="sparktpu-state-")
    s = StateStore(d, snapshot_interval=2)
    for v in range(1, 10):
        s.commit(v, _mk_state(10 + v), upsert_keys={(0,)}, key_names=["k"])
    snaps = sorted(int(f.split(".")[0]) for f in os.listdir(s.dir)
                   if f.endswith(".parquet"))
    assert len(snaps) == 2
    # everything older than the older retained snapshot is gone
    vs = [int(f.split(".")[0]) for f in os.listdir(s.dir)]
    assert min(vs) >= snaps[0]
    # and recovery from the latest version still works
    r = StateStore(d)
    r.load(9)
    assert r.table.num_rows == 19


def test_composite_key_python_path():
    d = tempfile.mkdtemp(prefix="sparktpu-state-")
    s = StateStore(d, snapshot_interval=10)
    t0 = pa.table({"a": [1, 1, 2], "b": ["x", "y", "x"], "v": [1, 2, 3]})
    s.commit(1, t0)
    t1 = pa.table({"a": [1, 1, 2], "b": ["x", "y", "x"], "v": [9, 2, 3]})
    s.commit(2, t1, upsert_keys={(1, "x")}, key_names=["a", "b"])
    r = StateStore(d)
    r.load(2)
    rows = {(a, b): v for a, b, v in zip(r.table.column("a").to_pylist(),
                                         r.table.column("b").to_pylist(),
                                         r.table.column("v").to_pylist())}
    assert rows == {(1, "x"): 9, (1, "y"): 2, (2, "x"): 3}


# ---------------------------------------------------------------------------
# Partitioned state (per-partition StateStore instances,
# sqlx/streaming/state/StateStore.scala:285)
# ---------------------------------------------------------------------------

def test_partitioned_commit_touches_only_hot_partitions():
    from spark_tpu.streaming.state import (
        PartitionedStateStore, _partition_of,
    )

    d = tempfile.mkdtemp(prefix="sparktpu-pstate-")
    s = PartitionedStateStore(d, num_partitions=4, snapshot_interval=100)
    s.commit(1, _mk_state(200))  # seed snapshot in every partition

    # v2 touches exactly one key → exactly one partition persists
    hot = (7,)
    t = _mk_state(200)
    s.commit(2, t, upsert_keys={hot}, key_names=["k"])
    hot_pid = _partition_of(hot, 4)
    for i, p in enumerate(s.parts):
        files_v2 = [f for f in os.listdir(p.dir) if f.startswith("2.")]
        if i == hot_pid:
            assert files_v2, "hot partition must persist v2"
        else:
            assert not files_v2, f"cold partition {i} wrote {files_v2}"


def test_partitioned_recovery_matches_flat_state():
    from spark_tpu.streaming.state import PartitionedStateStore

    d = tempfile.mkdtemp(prefix="sparktpu-pstate-")
    s = PartitionedStateStore(d, num_partitions=4, snapshot_interval=3)
    state = {k: k * 10 for k in range(50)}
    s.commit(1, pa.table({"k": list(state), "v": list(state.values())}))
    # several incremental versions: updates + inserts + deletes
    for v in range(2, 8):
        state[v * 100] = v  # insert
        state[v % 5] = -v   # update
        dead = 40 + v
        state.pop(dead, None)
        t = pa.table({"k": list(state), "v": list(state.values())})
        s.commit(v, t, upsert_keys={(v * 100,), (v % 5,)},
                 delete_keys=[(dead,)], key_names=["k"])
    r = PartitionedStateStore(d, num_partitions=4, snapshot_interval=3)
    r.load(7)
    got = dict(zip(r.table.column("k").to_pylist(),
                   r.table.column("v").to_pylist()))
    assert got == state


def test_partitioned_is_dropin_for_keyless_state():
    from spark_tpu.streaming.state import PartitionedStateStore

    d = tempfile.mkdtemp(prefix="sparktpu-pstate-")
    s = PartitionedStateStore(d, num_partitions=3)
    t = pa.table({"x": [1, 2, 3]})
    s.commit(1, t)  # no key_names at all
    r = PartitionedStateStore(d, num_partitions=3)
    r.load(1)
    assert r.table.column("x").to_pylist() == [1, 2, 3]
