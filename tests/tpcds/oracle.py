"""sqlite-based result oracle for the TPC-DS suite.

Role of the reference's committed `tpcds-query-results` (which are tied
to dsdgen SF1 data we cannot regenerate): an independent engine executes
the same query over the same generated tables and the row sets are
compared. sqlite 3.40 covers the full dialect except GROUPING
SETS/ROLLUP (those queries are validated by cross-config self-checks in
the harness instead).

The rewrite layer translates the handful of constructs sqlite spells
differently (date INTERVAL arithmetic, DECIMAL casts, stddev_samp via a
registered Python aggregate). Dates live as ISO text so BETWEEN/compare
work lexically.
"""

from __future__ import annotations

import datetime
import math
import re
import sqlite3
from decimal import Decimal


class _StddevSamp:
    def __init__(self):
        self.vals = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        m = sum(self.vals) / n
        return math.sqrt(sum((x - m) ** 2 for x in self.vals) / (n - 1))


class _VarSamp(_StddevSamp):
    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        m = sum(self.vals) / n
        return sum((x - m) ** 2 for x in self.vals) / (n - 1)


def _concat(*args):
    if any(a is None for a in args):
        return None
    return "".join(str(a) for a in args)


def load_sqlite(tables) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    conn.create_aggregate("stddev_samp", 1, _StddevSamp)
    conn.create_aggregate("var_samp", 1, _VarSamp)
    conn.create_aggregate("stddev", 1, _StddevSamp)
    conn.create_function("concat", -1, _concat)
    for name, tab in tables.items():
        cols = tab.column_names
        conn.execute(f"CREATE TABLE {name} ({', '.join(cols)})")
        pyrows = []
        pycols = []
        for c in cols:
            vals = tab.column(c).to_pylist()
            conv = []
            for v in vals:
                if isinstance(v, Decimal):
                    v = float(v)
                elif isinstance(v, (datetime.date, datetime.datetime)):
                    v = v.isoformat()[:10]
                conv.append(v)
            pycols.append(conv)
        pyrows = list(zip(*pycols))
        conn.executemany(
            f"INSERT INTO {name} VALUES ({','.join('?' * len(cols))})",
            pyrows)
    conn.commit()
    return conn


_INTERVAL = re.compile(
    r"\(\s*cast\s*\(\s*'(\d{4}-\d{2}-\d{2})'\s+as\s+date\s*\)\s*"
    r"([+-])\s*interval\s+(\d+)\s+days?\s*\)", re.I)
_INTERVAL_COL = re.compile(
    r"\(\s*cast\s*\(\s*([\w.]+)\s+as\s+date\s*\)\s*"
    r"([+-])\s*interval\s+(\d+)\s+days?\s*\)", re.I)
_CAST_DATE = re.compile(
    r"cast\s*\(\s*'(\d{4}-\d{2}-\d{2})'\s+as\s+date\s*\)", re.I)
_DECIMAL_T = re.compile(r"decimal\s*\(\s*\d+\s*,\s*\d+\s*\)", re.I)
# sqlite rejects parenthesized members of compound selects:
# "... UNION ALL (SELECT" / ") UNION ..." — unwrap the parens
_COMPOUND_OPEN = re.compile(
    r"\b(UNION\s+ALL|UNION|INTERSECT|EXCEPT)\s*\(\s*(SELECT)\b", re.I)


_COMPOUND_CLOSE = re.compile(
    r"\)\s*(UNION\s+ALL|UNION|INTERSECT|EXCEPT)\b", re.I)


def _unwrap_compound(sql: str) -> str:
    """Remove parentheses around compound-select members (sqlite rejects
    them): both `UNION (SELECT ...)` and `(SELECT ...) UNION`, matching
    parens by depth and unwrapping only when the paren directly wraps a
    SELECT."""
    while True:
        m = _COMPOUND_OPEN.search(sql)
        if not m:
            break
        open_idx = sql.index("(", m.end(1))
        depth, i = 0, open_idx
        while i < len(sql):
            if sql[i] == "(":
                depth += 1
            elif sql[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        sql = (sql[:open_idx] + " " + sql[open_idx + 1:i] + " " +
               sql[i + 1:])
    # leading members: `) UNION` whose matching `(` directly wraps SELECT
    while True:
        changed = False
        for m in _COMPOUND_CLOSE.finditer(sql):
            close_idx = m.start()
            depth, i = 0, close_idx
            while i >= 0:
                if sql[i] == ")":
                    depth += 1
                elif sql[i] == "(":
                    depth -= 1
                    if depth == 0:
                        break
                i -= 1
            prev = sql[:i].rstrip()[-1:] if i > 0 else ""
            # only a member-wrapper when directly wrapping SELECT and not
            # an expression paren (e.g. `IN (SELECT ...)` before UNION)
            if (i >= 0 and re.match(r"\(\s*SELECT\b", sql[i:], re.I)
                    and (prev == "(" or prev == "")):
                sql = (sql[:i] + " " + sql[i + 1:close_idx] + " " +
                       sql[close_idx + 1:])
                changed = True
                break
        if not changed:
            return sql


# per-query disambiguation patches: sqlite binds unqualified ORDER BY
# names to input tables before output aliases and reports ambiguity where
# the reference dialect resolves to the select-list alias
QUERY_PATCHES = {
    "q58": [("ORDER BY item_id", "ORDER BY ss_items.item_id")],
    "q72": [("w_warehouse_name, d_week_seq",
             "w_warehouse_name, d1.d_week_seq")],
}


def rewrite_for_sqlite(sql: str, qname: str | None = None) -> str:
    for old, new in QUERY_PATCHES.get(qname or "", []):
        sql = sql.replace(old, new)
    sql = _INTERVAL.sub(lambda m: f"date('{m.group(1)}', "
                        f"'{m.group(2)}{m.group(3)} day')", sql)
    sql = _INTERVAL_COL.sub(lambda m: f"date({m.group(1)}, "
                            f"'{m.group(2)}{m.group(3)} day')", sql)
    sql = _CAST_DATE.sub(lambda m: f"'{m.group(1)}'", sql)
    sql = _DECIMAL_T.sub("REAL", sql)
    # the reference dialect divides integers as doubles; sqlite truncates —
    # float-promote the known int/int division sites (q21/q34/q73)
    sql = re.sub(r"\b(hd_dep_count|inv_after)\s*/",
                 r"\1 * 1.0 /", sql)
    sql = _unwrap_compound(sql)
    return sql


_TRAILING_LIMIT = re.compile(r"\blimit\s+\d+\s*;?\s*$", re.I)


def strip_trailing_limit(sql: str) -> str:
    """Drop the final LIMIT so tie-broken top-N rows can't produce
    spurious mismatches between engines (the full sorted sets compare
    deterministically)."""
    return _TRAILING_LIMIT.sub("", sql.rstrip())


def _norm_cell(v):
    if v is None:
        return None
    if isinstance(v, Decimal):
        v = float(v)
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        return round(v, 2)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()[:10]
    if isinstance(v, bool):
        return int(v)
    return v


def _sort_key(row):
    return tuple((x is None, str(x)) for x in row)


def compare_rows(engine_rows, oracle_rows, rel_tol=1e-4, abs_tol=0.02):
    """Multiset comparison, order-insensitive, with numeric tolerance.
    Returns (ok, message)."""
    a = sorted([tuple(_norm_cell(c) for c in r) for r in engine_rows],
               key=_sort_key)
    b = sorted([tuple(_norm_cell(c) for c in r) for r in oracle_rows],
               key=_sort_key)
    if len(a) != len(b):
        return False, f"row count {len(a)} != oracle {len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if len(ra) != len(rb):
            return False, f"col count {len(ra)} != {len(rb)}"
        for ca, cb in zip(ra, rb):
            if ca == cb:
                continue
            if isinstance(ca, (int, float)) and isinstance(cb, (int, float)):
                if math.isclose(float(ca), float(cb), rel_tol=rel_tol,
                                abs_tol=abs_tol):
                    continue
            return False, (f"row {i}: {ra} != oracle {rb}")
    return True, "ok"
