"""sqlite-based result oracle for the TPC-DS suite.

Role of the reference's committed `tpcds-query-results` (which are tied
to dsdgen SF1 data we cannot regenerate): an independent engine executes
the same query over the same generated tables and the row sets are
compared. sqlite 3.40 covers the full dialect except GROUPING
SETS/ROLLUP (those queries are validated by cross-config self-checks in
the harness instead).

The rewrite layer translates the handful of constructs sqlite spells
differently (date INTERVAL arithmetic, DECIMAL casts, stddev_samp via a
registered Python aggregate). Dates live as ISO text so BETWEEN/compare
work lexically.
"""

from __future__ import annotations

import datetime
import math
import re
import sqlite3
from decimal import Decimal


class _StddevSamp:
    def __init__(self):
        self.vals = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        m = sum(self.vals) / n
        return math.sqrt(sum((x - m) ** 2 for x in self.vals) / (n - 1))


class _VarSamp(_StddevSamp):
    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        m = sum(self.vals) / n
        return sum((x - m) ** 2 for x in self.vals) / (n - 1)


def _concat(*args):
    if any(a is None for a in args):
        return None
    return "".join(str(a) for a in args)


def load_sqlite(tables) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    conn.create_aggregate("stddev_samp", 1, _StddevSamp)
    conn.create_aggregate("var_samp", 1, _VarSamp)
    conn.create_aggregate("stddev", 1, _StddevSamp)
    conn.create_function("concat", -1, _concat)
    for name, tab in tables.items():
        cols = tab.column_names
        conn.execute(f"CREATE TABLE {name} ({', '.join(cols)})")
        pyrows = []
        pycols = []
        for c in cols:
            vals = tab.column(c).to_pylist()
            conv = []
            for v in vals:
                if isinstance(v, Decimal):
                    v = float(v)
                elif isinstance(v, (datetime.date, datetime.datetime)):
                    v = v.isoformat()[:10]
                conv.append(v)
            pycols.append(conv)
        pyrows = list(zip(*pycols))
        conn.executemany(
            f"INSERT INTO {name} VALUES ({','.join('?' * len(cols))})",
            pyrows)
    conn.commit()
    return conn


_INTERVAL = re.compile(
    r"\(\s*cast\s*\(\s*'(\d{4}-\d{2}-\d{2})'\s+as\s+date\s*\)\s*"
    r"([+-])\s*interval\s+(\d+)\s+days?\s*\)", re.I)
_INTERVAL_COL = re.compile(
    r"\(\s*cast\s*\(\s*([\w.]+)\s+as\s+date\s*\)\s*"
    r"([+-])\s*interval\s+(\d+)\s+days?\s*\)", re.I)
_CAST_DATE = re.compile(
    r"cast\s*\(\s*'(\d{4}-\d{2}-\d{2})'\s+as\s+date\s*\)", re.I)
_DECIMAL_T = re.compile(r"decimal\s*\(\s*\d+\s*,\s*\d+\s*\)", re.I)
# sqlite rejects parenthesized members of compound selects:
# "... UNION ALL (SELECT" / ") UNION ..." — unwrap the parens
_COMPOUND_OPEN = re.compile(
    r"\b(UNION\s+ALL|UNION|INTERSECT|EXCEPT)\s*\(\s*(SELECT)\b", re.I)


_COMPOUND_CLOSE = re.compile(
    r"\)\s*(UNION\s+ALL|UNION|INTERSECT|EXCEPT)\b", re.I)


def _unwrap_compound(sql: str) -> str:
    """Remove parentheses around compound-select members (sqlite rejects
    them): both `UNION (SELECT ...)` and `(SELECT ...) UNION`, matching
    parens by depth and unwrapping only when the paren directly wraps a
    SELECT."""
    while True:
        m = _COMPOUND_OPEN.search(sql)
        if not m:
            break
        open_idx = sql.index("(", m.end(1))
        depth, i = 0, open_idx
        while i < len(sql):
            if sql[i] == "(":
                depth += 1
            elif sql[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        sql = (sql[:open_idx] + " " + sql[open_idx + 1:i] + " " +
               sql[i + 1:])
    # leading members: `) UNION` whose matching `(` directly wraps SELECT
    while True:
        changed = False
        for m in _COMPOUND_CLOSE.finditer(sql):
            close_idx = m.start()
            depth, i = 0, close_idx
            while i >= 0:
                if sql[i] == ")":
                    depth += 1
                elif sql[i] == "(":
                    depth -= 1
                    if depth == 0:
                        break
                i -= 1
            prev = sql[:i].rstrip()[-1:] if i > 0 else ""
            # only a member-wrapper when directly wrapping SELECT and not
            # an expression paren (e.g. `IN (SELECT ...)` before UNION)
            if (i >= 0 and re.match(r"\(\s*SELECT\b", sql[i:], re.I)
                    and (prev == "(" or prev == "")):
                sql = (sql[:i] + " " + sql[i + 1:close_idx] + " " +
                       sql[close_idx + 1:])
                changed = True
                break
        if not changed:
            return sql


# per-query disambiguation patches: sqlite binds unqualified ORDER BY
# names to input tables before output aliases and reports ambiguity where
# the reference dialect resolves to the select-list alias
QUERY_PATCHES = {
    "q58": [("ORDER BY item_id", "ORDER BY ss_items.item_id")],
    "q72": [("w_warehouse_name, d_week_seq",
             "w_warehouse_name, d1.d_week_seq")],
}


def _matching_paren(sql: str, open_idx: int) -> int:
    depth = 0
    for i in range(open_idx, len(sql)):
        if sql[i] == "(":
            depth += 1
        elif sql[i] == ")":
            depth -= 1
            if depth == 0:
                return i
    raise ValueError("unbalanced parens")


def _split_top_commas(text: str) -> list[str]:
    out, depth, start = [], 0, 0
    for i, ch in enumerate(text):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(text[start:i].strip())
            start = i + 1
    out.append(text[start:].strip())
    return out


_ROLLUP = re.compile(r"GROUP\s+BY\s+ROLLUP\s*\(", re.I)
_SELECT_KW = re.compile(r"\bSELECT\b", re.I)
_FROM_KW = re.compile(r"\bFROM\b", re.I)


def _owning_select(sql: str, group_idx: int) -> int:
    """Index of the SELECT that owns the clause at group_idx: nearest
    preceding SELECT with zero net paren balance between them."""
    balance = 0
    i = group_idx - 1
    while i >= 0:
        ch = sql[i]
        if ch == ")":
            balance += 1
        elif ch == "(":
            balance -= 1
        elif balance == 0 and sql[i:i + 6].upper() == "SELECT" and \
                (i == 0 or not (sql[i - 1].isalnum() or sql[i - 1] == "_")):
            return i
        i -= 1
    raise ValueError("no owning SELECT for ROLLUP clause")


def expand_rollup(sql: str) -> str:
    """Rewrite `GROUP BY ROLLUP (c1..cn)` into a UNION ALL of plain
    GROUP BY prefixes — the GROUPING SETS expansion sqlite cannot do
    itself — so rollup queries get real oracle verification instead of
    exec-only pins. Per branch with the first k columns grouped:
    `grouping(c)` becomes the literal 0/1 and each non-grouped rollup
    column becomes NULL (aliased when it was a bare select item). The
    ORDER BY (and anything else after the clause) moves outside a
    wrapping subselect so output-alias scoping is preserved. Window
    functions in the select list stay per-branch, which is exact
    whenever their partition key contains the grouping level (q36/q70/
    q86 partition on grouping()+grouping()); q67's cross-branch window
    already lives OUTSIDE the rollup subquery in the committed text.
    Limitation (unused by q1-q99): a rollup column referenced inside an
    aggregate argument would be nulled too."""
    while True:
        m = _ROLLUP.search(sql)
        if not m:
            return sql
        open_idx = m.end() - 1
        close_idx = _matching_paren(sql, open_idx)
        cols = _split_top_commas(sql[open_idx + 1:close_idx])
        suffix = sql[close_idx + 1:]
        sel_idx = _owning_select(sql, m.start())
        prefix = sql[:sel_idx]
        seg = sql[sel_idx:m.start()]
        # top-level FROM splits select list from relation/where text
        depth = 0
        from_idx = None
        for fm in _FROM_KW.finditer(seg):
            depth = seg[:fm.start()].count("(") - seg[:fm.start()].count(")")
            if depth == 0:
                from_idx = fm.start()
                break
        if from_idx is None:
            raise ValueError("ROLLUP select without top-level FROM")
        select_list = seg[len("SELECT"):from_idx]
        body = seg[from_idx:]
        items = _split_top_commas(select_list)

        def branch(k: int) -> str:
            grouped = set(cols[:k])
            out_items = []
            for item in items:
                t = item
                for c in cols:
                    t = re.sub(r"grouping\s*\(\s*%s\s*\)" % re.escape(c),
                               "0" if c in grouped else "1", t, flags=re.I)
                for c in cols[k:]:
                    if re.fullmatch(re.escape(c), t.strip(), re.I):
                        t = f"NULL AS {c}"
                    else:
                        t = re.sub(r"\b%s\b" % re.escape(c), "NULL", t,
                                   flags=re.I)
                out_items.append(t)
            b = "SELECT " + ", ".join(out_items) + " " + body
            if k:
                b += " GROUP BY " + ", ".join(cols[:k])
            return b

        union = " UNION ALL ".join(branch(k)
                                   for k in range(len(cols), -1, -1))
        sql = prefix + "SELECT * FROM (" + union + ") rollup_u " + suffix


def rewrite_for_sqlite(sql: str, qname: str | None = None) -> str:
    for old, new in QUERY_PATCHES.get(qname or "", []):
        sql = sql.replace(old, new)
    sql = expand_rollup(sql)
    sql = _INTERVAL.sub(lambda m: f"date('{m.group(1)}', "
                        f"'{m.group(2)}{m.group(3)} day')", sql)
    sql = _INTERVAL_COL.sub(lambda m: f"date({m.group(1)}, "
                            f"'{m.group(2)}{m.group(3)} day')", sql)
    sql = _CAST_DATE.sub(lambda m: f"'{m.group(1)}'", sql)
    sql = _DECIMAL_T.sub("REAL", sql)
    # the reference dialect divides integers as doubles; sqlite truncates —
    # float-promote the known int/int division sites (q21/q34/q73)
    sql = re.sub(r"\b(hd_dep_count|inv_after)\s*/",
                 r"\1 * 1.0 /", sql)
    sql = _unwrap_compound(sql)
    return sql


_TRAILING_LIMIT = re.compile(r"\blimit\s+\d+\s*;?\s*$", re.I)


def strip_trailing_limit(sql: str) -> str:
    """Drop the final LIMIT so tie-broken top-N rows can't produce
    spurious mismatches between engines (the full sorted sets compare
    deterministically)."""
    return _TRAILING_LIMIT.sub("", sql.rstrip())


def _norm_cell(v):
    if v is None:
        return None
    if isinstance(v, Decimal):
        v = float(v)
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        return round(v, 2)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()[:10]
    if isinstance(v, bool):
        return int(v)
    return v


def _sort_key(row):
    return tuple((x is None, str(x)) for x in row)


def compare_rows(engine_rows, oracle_rows, rel_tol=1e-4, abs_tol=0.02):
    """Multiset comparison, order-insensitive, with numeric tolerance.
    Returns (ok, message)."""
    a = sorted([tuple(_norm_cell(c) for c in r) for r in engine_rows],
               key=_sort_key)
    b = sorted([tuple(_norm_cell(c) for c in r) for r in oracle_rows],
               key=_sort_key)
    if len(a) != len(b):
        return False, f"row count {len(a)} != oracle {len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if len(ra) != len(rb):
            return False, f"col count {len(ra)} != {len(rb)}"
        for ca, cb in zip(ra, rb):
            if ca == cb:
                continue
            if isinstance(ca, (int, float)) and isinstance(cb, (int, float)):
                if math.isclose(float(ca), float(cb), rel_tol=rel_tol,
                                abs_tol=abs_tol):
                    continue
            return False, (f"row {i}: {ra} != oracle {rb}")
    return True, "ok"
