WITH ss AS (
  SELECT
    i_manufact_id,
    sum(ss_ext_sales_price) total_sales
  FROM
    store_sales, date_dim, customer_address, item
  WHERE
    i_manufact_id IN (SELECT i_manufact_id
    FROM item
    WHERE i_category IN ('Electronics'))
      AND ss_item_sk = i_item_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year = 1998
      AND d_moy = 5
      AND ss_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_manufact_id), cs AS
(SELECT
    i_manufact_id,
    sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE
    i_manufact_id IN (
      SELECT i_manufact_id
      FROM item
      WHERE
        i_category IN ('Electronics'))
      AND cs_item_sk = i_item_sk
      AND cs_sold_date_sk = d_date_sk
      AND d_year = 1998
      AND d_moy = 5
      AND cs_bill_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_manufact_id),
    ws AS (
    SELECT
      i_manufact_id,
      sum(ws_ext_sales_price) total_sales
    FROM
      web_sales, date_dim, customer_address, item
    WHERE
      i_manufact_id IN (SELECT i_manufact_id
      FROM item
      WHERE i_category IN ('Electronics'))
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = 1998
        AND d_moy = 5
        AND ws_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_manufact_id)
SELECT
  i_manufact_id,
  sum(total_sales) total_sales
FROM (SELECT *
      FROM ss
      UNION ALL
      SELECT *
      FROM cs
      UNION ALL
      SELECT *
      FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales
LIMIT 100
