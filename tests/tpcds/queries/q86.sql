SELECT
  sum(ws_net_paid) AS total_sum,
  i_category,
  i_class,
  grouping(i_category) + grouping(i_class) AS lochierarchy,
  rank()
  OVER (
    PARTITION BY grouping(i_category) + grouping(i_class),
      CASE WHEN grouping(i_class) = 0
        THEN i_category END
    ORDER BY sum(ws_net_paid) DESC) AS rank_within_parent
FROM
  web_sales, date_dim d1, item
WHERE
  d1.d_month_seq BETWEEN 1200 AND 1200 + 11
    AND d1.d_date_sk = ws_sold_date_sk
    AND i_item_sk = ws_item_sk
GROUP BY ROLLUP (i_category, i_class)
ORDER BY
  lochierarchy DESC,
  CASE WHEN lochierarchy = 0
    THEN i_category END,
  rank_within_parent
LIMIT 100
