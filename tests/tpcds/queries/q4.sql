WITH year_total AS (
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    c_preferred_cust_flag customer_preferred_cust_flag,
    c_birth_country customer_birth_country,
    c_login customer_login,
    c_email_address customer_email_address,
    d_year dyear,
    sum(((ss_ext_list_price - ss_ext_wholesale_cost - ss_ext_discount_amt) +
      ss_ext_sales_price) / 2) year_total,
    's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id,
    c_first_name,
    c_last_name,
    c_preferred_cust_flag,
    c_birth_country,
    c_login,
    c_email_address,
    d_year
  UNION ALL
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    c_preferred_cust_flag customer_preferred_cust_flag,
    c_birth_country customer_birth_country,
    c_login customer_login,
    c_email_address customer_email_address,
    d_year dyear,
    sum((((cs_ext_list_price - cs_ext_wholesale_cost - cs_ext_discount_amt) +
      cs_ext_sales_price) / 2)) year_total,
    'c' sale_type
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk AND cs_sold_date_sk = d_date_sk
  GROUP BY c_customer_id,
    c_first_name,
    c_last_name,
    c_preferred_cust_flag,
    c_birth_country,
    c_login,
    c_email_address,
    d_year
  UNION ALL
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    c_preferred_cust_flag customer_preferred_cust_flag,
    c_birth_country customer_birth_country,
    c_login customer_login,
    c_email_address customer_email_address,
    d_year dyear,
    sum((((ws_ext_list_price - ws_ext_wholesale_cost - ws_ext_discount_amt) + ws_ext_sales_price) /
      2)) year_total,
    'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id,
    c_first_name,
    c_last_name,
    c_preferred_cust_flag,
    c_birth_country,
    c_login,
    c_email_address,
    d_year)
SELECT
  t_s_secyear.customer_id,
  t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name,
  t_s_secyear.customer_preferred_cust_flag,
  t_s_secyear.customer_birth_country,
  t_s_secyear.customer_login,
  t_s_secyear.customer_email_address
FROM year_total t_s_firstyear, year_total t_s_secyear, year_total t_c_firstyear,
  year_total t_c_secyear, year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001
  AND t_s_secyear.dyear = 2001 + 1
  AND t_c_firstyear.dyear = 2001
  AND t_c_secyear.dyear = 2001 + 1
  AND t_w_firstyear.dyear = 2001
  AND t_w_secyear.dyear = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
  THEN t_c_secyear.year_total / t_c_firstyear.year_total
      ELSE NULL END
  > CASE WHEN t_s_firstyear.year_total > 0
  THEN t_s_secyear.year_total / t_s_firstyear.year_total
    ELSE NULL END
  AND CASE WHEN t_c_firstyear.year_total > 0
  THEN t_c_secyear.year_total / t_c_firstyear.year_total
      ELSE NULL END
  > CASE WHEN t_w_firstyear.year_total > 0
  THEN t_w_secyear.year_total / t_w_firstyear.year_total
    ELSE NULL END
ORDER BY
  t_s_secyear.customer_id,
  t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name,
  t_s_secyear.customer_preferred_cust_flag,
  t_s_secyear.customer_birth_country,
  t_s_secyear.customer_login,
  t_s_secyear.customer_email_address
LIMIT 100
