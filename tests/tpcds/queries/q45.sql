SELECT
  ca_zip,
  ca_city,
  sum(ws_sales_price)
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (substr(ca_zip, 1, 5) IN
  ('85669', '86197', '88274', '83405', '86475', '85392', '85460', '80348', '81792')
  OR
  i_item_id IN (SELECT i_item_id
  FROM item
  WHERE i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)
  )
)
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
LIMIT 100
