SELECT count(*)
FROM ((SELECT DISTINCT
  c_last_name,
  c_first_name,
  d_date
FROM store_sales, date_dim, customer
WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
  AND store_sales.ss_customer_sk = customer.c_customer_sk
  AND d_month_seq BETWEEN 1200 AND 1200 + 11)
      EXCEPT
      (SELECT DISTINCT
        c_last_name,
        c_first_name,
        d_date
      FROM catalog_sales, date_dim, customer
      WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11)
      EXCEPT
      (SELECT DISTINCT
        c_last_name,
        c_first_name,
        d_date
      FROM web_sales, date_dim, customer
      WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
        AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11)
     ) cool_cust
