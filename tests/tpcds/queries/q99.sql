SELECT
  substr(w_warehouse_name, 1, 20),
  sm_type,
  cc_name,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 30) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 60) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 90) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE
  d_month_seq BETWEEN 1200 AND 1200 + 11
    AND cs_ship_date_sk = d_date_sk
    AND cs_warehouse_sk = w_warehouse_sk
    AND cs_ship_mode_sk = sm_ship_mode_sk
    AND cs_call_center_sk = cc_call_center_sk
GROUP BY
  substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
LIMIT 100
