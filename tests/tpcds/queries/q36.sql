SELECT
  sum(ss_net_profit) / sum(ss_ext_sales_price) AS gross_margin,
  i_category,
  i_class,
  grouping(i_category) + grouping(i_class) AS lochierarchy,
  rank()
  OVER (
    PARTITION BY grouping(i_category) + grouping(i_class),
      CASE WHEN grouping(i_class) = 0
        THEN i_category END
    ORDER BY sum(ss_net_profit) / sum(ss_ext_sales_price) ASC) AS rank_within_parent
FROM
  store_sales, date_dim d1, item, store
WHERE
  d1.d_year = 2001
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN')
GROUP BY ROLLUP (i_category, i_class)
ORDER BY
  lochierarchy DESC
  , CASE WHEN lochierarchy = 0
  THEN i_category END
  , rank_within_parent
LIMIT 100
