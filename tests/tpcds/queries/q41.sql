SELECT DISTINCT (i_product_name)
FROM item i1
WHERE i_manufact_id BETWEEN 738 AND 738 + 40
  AND (SELECT count(*) AS item_cnt
FROM item
WHERE (i_manufact = i1.i_manufact AND
  ((i_category = 'Women' AND
    (i_color = 'powder' OR i_color = 'khaki') AND
    (i_units = 'Ounce' OR i_units = 'Oz') AND
    (i_size = 'medium' OR i_size = 'extra large')
  ) OR
    (i_category = 'Women' AND
      (i_color = 'brown' OR i_color = 'honeydew') AND
      (i_units = 'Bunch' OR i_units = 'Ton') AND
      (i_size = 'N/A' OR i_size = 'small')
    ) OR
    (i_category = 'Men' AND
      (i_color = 'floral' OR i_color = 'deep') AND
      (i_units = 'N/A' OR i_units = 'Dozen') AND
      (i_size = 'petite' OR i_size = 'large')
    ) OR
    (i_category = 'Men' AND
      (i_color = 'light' OR i_color = 'cornflower') AND
      (i_units = 'Box' OR i_units = 'Pound') AND
      (i_size = 'medium' OR i_size = 'extra large')
    ))) OR
  (i_manufact = i1.i_manufact AND
    ((i_category = 'Women' AND
      (i_color = 'midnight' OR i_color = 'snow') AND
      (i_units = 'Pallet' OR i_units = 'Gross') AND
      (i_size = 'medium' OR i_size = 'extra large')
    ) OR
      (i_category = 'Women' AND
        (i_color = 'cyan' OR i_color = 'papaya') AND
        (i_units = 'Cup' OR i_units = 'Dram') AND
        (i_size = 'N/A' OR i_size = 'small')
      ) OR
      (i_category = 'Men' AND
        (i_color = 'orange' OR i_color = 'frosted') AND
        (i_units = 'Each' OR i_units = 'Tbl') AND
        (i_size = 'petite' OR i_size = 'large')
      ) OR
      (i_category = 'Men' AND
        (i_color = 'forest' OR i_color = 'ghost') AND
        (i_units = 'Lb' OR i_units = 'Bundle') AND
        (i_size = 'medium' OR i_size = 'extra large')
      )))) > 0
ORDER BY i_product_name
LIMIT 100
