SELECT
  i_item_id,
  avg(cs_quantity) agg1,
  avg(cs_list_price) agg2,
  avg(cs_coupon_amt) agg3,
  avg(cs_sales_price) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND
  cs_item_sk = i_item_sk AND
  cs_bill_cdemo_sk = cd_demo_sk AND
  cs_promo_sk = p_promo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  (p_channel_email = 'N' OR p_channel_event = 'N') AND
  d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
