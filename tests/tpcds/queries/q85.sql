SELECT
  substr(r_reason_desc, 1, 20),
  avg(ws_quantity),
  avg(wr_refunded_cash),
  avg(wr_fee)
FROM web_sales, web_returns, web_page, customer_demographics cd1,
  customer_demographics cd2, customer_address, date_dim, reason
WHERE ws_web_page_sk = wp_web_page_sk
  AND ws_item_sk = wr_item_sk
  AND ws_order_number = wr_order_number
  AND ws_sold_date_sk = d_date_sk AND d_year = 2000
  AND cd1.cd_demo_sk = wr_refunded_cdemo_sk
  AND cd2.cd_demo_sk = wr_returning_cdemo_sk
  AND ca_address_sk = wr_refunded_addr_sk
  AND r_reason_sk = wr_reason_sk
  AND
  (
    (
      cd1.cd_marital_status = 'M'
        AND
        cd1.cd_marital_status = cd2.cd_marital_status
        AND
        cd1.cd_education_status = 'Advanced Degree'
        AND
        cd1.cd_education_status = cd2.cd_education_status
        AND
        ws_sales_price BETWEEN 100.00 AND 150.00
    )
      OR
      (
        cd1.cd_marital_status = 'S'
          AND
          cd1.cd_marital_status = cd2.cd_marital_status
          AND
          cd1.cd_education_status = 'College'
          AND
          cd1.cd_education_status = cd2.cd_education_status
          AND
          ws_sales_price BETWEEN 50.00 AND 100.00
      )
      OR
      (
        cd1.cd_marital_status = 'W'
          AND
          cd1.cd_marital_status = cd2.cd_marital_status
          AND
          cd1.cd_education_status = '2 yr Degree'
          AND
          cd1.cd_education_status = cd2.cd_education_status
          AND
          ws_sales_price BETWEEN 150.00 AND 200.00
      )
  )
  AND
  (
    (
      ca_country = 'United States'
        AND
        ca_state IN ('IN', 'OH', 'NJ')
        AND ws_net_profit BETWEEN 100 AND 200
    )
      OR
      (
        ca_country = 'United States'
          AND
          ca_state IN ('WI', 'CT', 'KY')
          AND ws_net_profit BETWEEN 150 AND 300
      )
      OR
      (
        ca_country = 'United States'
          AND
          ca_state IN ('LA', 'IA', 'AR')
          AND ws_net_profit BETWEEN 50 AND 250
      )
  )
GROUP BY r_reason_desc
ORDER BY substr(r_reason_desc, 1, 20)
  , avg(ws_quantity)
  , avg(wr_refunded_cash)
  , avg(wr_fee)
LIMIT 100
