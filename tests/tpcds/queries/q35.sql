SELECT
  ca_state,
  cd_gender,
  cd_marital_status,
  count(*) cnt1,
  min(cd_dep_count),
  max(cd_dep_count),
  avg(cd_dep_count),
  cd_dep_employed_count,
  count(*) cnt2,
  min(cd_dep_employed_count),
  max(cd_dep_employed_count),
  avg(cd_dep_employed_count),
  cd_dep_college_count,
  count(*) cnt3,
  min(cd_dep_college_count),
  max(cd_dep_college_count),
  avg(cd_dep_college_count)
FROM
  customer c, customer_address ca, customer_demographics
WHERE
  c.c_current_addr_sk = ca.ca_address_sk AND
    cd_demo_sk = c.c_current_cdemo_sk AND
    exists(SELECT *
           FROM store_sales, date_dim
           WHERE c.c_customer_sk = ss_customer_sk AND
             ss_sold_date_sk = d_date_sk AND
             d_year = 2002 AND
             d_qoy < 4) AND
    (exists(SELECT *
            FROM web_sales, date_dim
            WHERE c.c_customer_sk = ws_bill_customer_sk AND
              ws_sold_date_sk = d_date_sk AND
              d_year = 2002 AND
              d_qoy < 4) OR
      exists(SELECT *
             FROM catalog_sales, date_dim
             WHERE c.c_customer_sk = cs_ship_customer_sk AND
               cs_sold_date_sk = d_date_sk AND
               d_year = 2002 AND
               d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
  cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
  cd_dep_employed_count, cd_dep_college_count
LIMIT 100
