SELECT sum(ws_ext_discount_amt) AS `Excess Discount Amount `
FROM web_sales, item, date_dim
WHERE i_manufact_id = 350
  AND i_item_sk = ws_item_sk
  AND d_date BETWEEN '2000-01-27' AND (cast('2000-01-27' AS DATE) + INTERVAL 90 days)
  AND d_date_sk = ws_sold_date_sk
  AND ws_ext_discount_amt >
  (
    SELECT 1.3 * avg(ws_ext_discount_amt)
    FROM web_sales, date_dim
    WHERE ws_item_sk = i_item_sk
      AND d_date BETWEEN '2000-01-27' AND (cast('2000-01-27' AS DATE) + INTERVAL 90 days)
      AND d_date_sk = ws_sold_date_sk
  )
ORDER BY sum(ws_ext_discount_amt)
LIMIT 100
