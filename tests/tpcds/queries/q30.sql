WITH customer_total_return AS
(SELECT
    wr_returning_customer_sk AS ctr_customer_sk,
    ca_state AS ctr_state,
    sum(wr_return_amt) AS ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk
    AND d_year = 2002
    AND wr_returning_addr_sk = ca_address_sk
  GROUP BY wr_returning_customer_sk, ca_state)
SELECT
  c_customer_id,
  c_salutation,
  c_first_name,
  c_last_name,
  c_preferred_cust_flag,
  c_birth_day,
  c_birth_month,
  c_birth_year,
  c_birth_country,
  c_login,
  c_email_address,
  c_last_review_date,
  ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
FROM customer_total_return ctr2
WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name, c_preferred_cust_flag
  , c_birth_day, c_birth_month, c_birth_year, c_birth_country, c_login, c_email_address
  , c_last_review_date, ctr_total_return
LIMIT 100
