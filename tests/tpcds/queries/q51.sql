WITH web_v1 AS (
  SELECT
    ws_item_sk item_sk,
    d_date,
    sum(sum(ws_sales_price))
    OVER (PARTITION BY ws_item_sk
      ORDER BY d_date
      ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume_sales
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1200 + 11
    AND ws_item_sk IS NOT NULL
  GROUP BY ws_item_sk, d_date),
    store_v1 AS (
    SELECT
      ss_item_sk item_sk,
      d_date,
      sum(sum(ss_sales_price))
      OVER (PARTITION BY ss_item_sk
        ORDER BY d_date
        ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume_sales
    FROM store_sales, date_dim
    WHERE ss_sold_date_sk = d_date_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
      AND ss_item_sk IS NOT NULL
    GROUP BY ss_item_sk, d_date)
SELECT *
FROM (SELECT
  item_sk,
  d_date,
  web_sales,
  store_sales,
  max(web_sales)
  OVER (PARTITION BY item_sk
    ORDER BY d_date
    ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) web_cumulative,
  max(store_sales)
  OVER (PARTITION BY item_sk
    ORDER BY d_date
    ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) store_cumulative
FROM (SELECT
  CASE WHEN web.item_sk IS NOT NULL
    THEN web.item_sk
  ELSE store.item_sk END item_sk,
  CASE WHEN web.d_date IS NOT NULL
    THEN web.d_date
  ELSE store.d_date END d_date,
  web.cume_sales web_sales,
  store.cume_sales store_sales
FROM web_v1 web FULL OUTER JOIN store_v1 store ON (web.item_sk = store.item_sk
  AND web.d_date = store.d_date)
     ) x) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date
LIMIT 100
