SELECT
  asceding.rnk,
  i1.i_product_name best_performing,
  i2.i_product_name worst_performing
FROM (SELECT *
FROM (SELECT
  item_sk,
  rank()
  OVER (
    ORDER BY rank_col ASC) rnk
FROM (SELECT
  ss_item_sk item_sk,
  avg(ss_net_profit) rank_col
FROM store_sales ss1
WHERE ss_store_sk = 4
GROUP BY ss_item_sk
HAVING avg(ss_net_profit) > 0.9 * (SELECT avg(ss_net_profit) rank_col
FROM store_sales
WHERE ss_store_sk = 4
  AND ss_addr_sk IS NULL
GROUP BY ss_store_sk)) V1) V11
WHERE rnk < 11) asceding,
  (SELECT *
  FROM (SELECT
    item_sk,
    rank()
    OVER (
      ORDER BY rank_col DESC) rnk
  FROM (SELECT
    ss_item_sk item_sk,
    avg(ss_net_profit) rank_col
  FROM store_sales ss1
  WHERE ss_store_sk = 4
  GROUP BY ss_item_sk
  HAVING avg(ss_net_profit) > 0.9 * (SELECT avg(ss_net_profit) rank_col
  FROM store_sales
  WHERE ss_store_sk = 4
    AND ss_addr_sk IS NULL
  GROUP BY ss_store_sk)) V2) V21
  WHERE rnk < 11) descending,
  item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
LIMIT 100
