WITH wscs AS
( SELECT
    sold_date_sk,
    sales_price
  FROM (SELECT
    ws_sold_date_sk sold_date_sk,
    ws_ext_sales_price sales_price
  FROM web_sales) x
  UNION ALL
  (SELECT
    cs_sold_date_sk sold_date_sk,
    cs_ext_sales_price sales_price
  FROM catalog_sales)),
    wswscs AS
  ( SELECT
    d_week_seq,
    sum(CASE WHEN (d_day_name = 'Sunday')
      THEN sales_price
        ELSE NULL END)
    sun_sales,
    sum(CASE WHEN (d_day_name = 'Monday')
      THEN sales_price
        ELSE NULL END)
    mon_sales,
    sum(CASE WHEN (d_day_name = 'Tuesday')
      THEN sales_price
        ELSE NULL END)
    tue_sales,
    sum(CASE WHEN (d_day_name = 'Wednesday')
      THEN sales_price
        ELSE NULL END)
    wed_sales,
    sum(CASE WHEN (d_day_name = 'Thursday')
      THEN sales_price
        ELSE NULL END)
    thu_sales,
    sum(CASE WHEN (d_day_name = 'Friday')
      THEN sales_price
        ELSE NULL END)
    fri_sales,
    sum(CASE WHEN (d_day_name = 'Saturday')
      THEN sales_price
        ELSE NULL END)
    sat_sales
  FROM wscs, date_dim
  WHERE d_date_sk = sold_date_sk
  GROUP BY d_week_seq)
SELECT
  d_week_seq1,
  round(sun_sales1 / sun_sales2, 2),
  round(mon_sales1 / mon_sales2, 2),
  round(tue_sales1 / tue_sales2, 2),
  round(wed_sales1 / wed_sales2, 2),
  round(thu_sales1 / thu_sales2, 2),
  round(fri_sales1 / fri_sales2, 2),
  round(sat_sales1 / sat_sales2, 2)
FROM
  (SELECT
    wswscs.d_week_seq d_week_seq1,
    sun_sales sun_sales1,
    mon_sales mon_sales1,
    tue_sales tue_sales1,
    wed_sales wed_sales1,
    thu_sales thu_sales1,
    fri_sales fri_sales1,
    sat_sales sat_sales1
  FROM wswscs, date_dim
  WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2001) y,
  (SELECT
    wswscs.d_week_seq d_week_seq2,
    sun_sales sun_sales2,
    mon_sales mon_sales2,
    tue_sales tue_sales2,
    wed_sales wed_sales2,
    thu_sales thu_sales2,
    fri_sales fri_sales2,
    sat_sales sat_sales2
  FROM wswscs, date_dim
  WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2001 + 1) z
WHERE d_week_seq1 = d_week_seq2 - 53
ORDER BY d_week_seq1
