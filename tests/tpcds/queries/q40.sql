SELECT
  w_state,
  i_item_id,
  sum(CASE WHEN (cast(d_date AS DATE) < cast('2000-03-11' AS DATE))
    THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
      ELSE 0 END) AS sales_before,
  sum(CASE WHEN (cast(d_date AS DATE) >= cast('2000-03-11' AS DATE))
    THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
      ELSE 0 END) AS sales_after
FROM
  catalog_sales
  LEFT OUTER JOIN catalog_returns ON
                                    (cs_order_number = cr_order_number
                                      AND cs_item_sk = cr_item_sk)
  , warehouse, item, date_dim
WHERE
  i_current_price BETWEEN 0.99 AND 1.49
    AND i_item_sk = cs_item_sk
    AND cs_warehouse_sk = w_warehouse_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN (cast('2000-03-11' AS DATE) - INTERVAL 30 days)
  AND (cast('2000-03-11' AS DATE) + INTERVAL 30 days)
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
