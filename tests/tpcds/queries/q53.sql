SELECT *
FROM
  (SELECT
    i_manufact_id,
    sum(ss_sales_price) sum_sales,
    avg(sum(ss_sales_price))
    OVER (PARTITION BY i_manufact_id) avg_quarterly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND
    ss_sold_date_sk = d_date_sk AND
    ss_store_sk = s_store_sk AND
    d_month_seq IN (1200, 1200 + 1, 1200 + 2, 1200 + 3, 1200 + 4, 1200 + 5, 1200 + 6,
                          1200 + 7, 1200 + 8, 1200 + 9, 1200 + 10, 1200 + 11) AND
    ((i_category IN ('Books', 'Children', 'Electronics') AND
      i_class IN ('personal', 'portable', 'reference', 'self-help') AND
      i_brand IN ('scholaramalgamalg #14', 'scholaramalgamalg #7',
                  'exportiunivamalg #9', 'scholaramalgamalg #9'))
      OR
      (i_category IN ('Women', 'Music', 'Men') AND
        i_class IN ('accessories', 'classical', 'fragrances', 'pants') AND
        i_brand IN ('amalgimporto #1', 'edu packscholar #1', 'exportiimporto #1',
                    'importoamalg #1')))
  GROUP BY i_manufact_id, d_qoy) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0
  THEN abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      ELSE NULL END > 0.1
ORDER BY avg_quarterly_sales,
  sum_sales,
  i_manufact_id
LIMIT 100
