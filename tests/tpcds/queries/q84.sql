SELECT
  c_customer_id AS customer_id,
  concat(c_last_name, ', ', c_first_name) AS customername
FROM customer
  , customer_address
  , customer_demographics
  , household_demographics
  , income_band
  , store_returns
WHERE ca_city = 'Edgewood'
  AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 38128
  AND ib_upper_bound <= 38128 + 50000
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND sr_cdemo_sk = cd_demo_sk
ORDER BY c_customer_id
LIMIT 100
