WITH cross_items AS
(SELECT i_item_sk ss_item_sk
  FROM item,
    (SELECT
      iss.i_brand_id brand_id,
      iss.i_class_id class_id,
      iss.i_category_id category_id
    FROM store_sales, item iss, date_dim d1
    WHERE ss_item_sk = iss.i_item_sk
      AND ss_sold_date_sk = d1.d_date_sk
      AND d1.d_year BETWEEN 1999 AND 1999 + 2
    INTERSECT
    SELECT
      ics.i_brand_id,
      ics.i_class_id,
      ics.i_category_id
    FROM catalog_sales, item ics, date_dim d2
    WHERE cs_item_sk = ics.i_item_sk
      AND cs_sold_date_sk = d2.d_date_sk
      AND d2.d_year BETWEEN 1999 AND 1999 + 2
    INTERSECT
    SELECT
      iws.i_brand_id,
      iws.i_class_id,
      iws.i_category_id
    FROM web_sales, item iws, date_dim d3
    WHERE ws_item_sk = iws.i_item_sk
      AND ws_sold_date_sk = d3.d_date_sk
      AND d3.d_year BETWEEN 1999 AND 1999 + 2) x
  WHERE i_brand_id = brand_id
    AND i_class_id = class_id
    AND i_category_id = category_id
),
    avg_sales AS
  (SELECT avg(quantity * list_price) average_sales
  FROM (
         SELECT
           ss_quantity quantity,
           ss_list_price list_price
         FROM store_sales, date_dim
         WHERE ss_sold_date_sk = d_date_sk
           AND d_year BETWEEN 1999 AND 2001
         UNION ALL
         SELECT
           cs_quantity quantity,
           cs_list_price list_price
         FROM catalog_sales, date_dim
         WHERE cs_sold_date_sk = d_date_sk
           AND d_year BETWEEN 1999 AND 1999 + 2
         UNION ALL
         SELECT
           ws_quantity quantity,
           ws_list_price list_price
         FROM web_sales, date_dim
         WHERE ws_sold_date_sk = d_date_sk
           AND d_year BETWEEN 1999 AND 1999 + 2) x)
SELECT
  channel,
  i_brand_id,
  i_class_id,
  i_category_id,
  sum(sales),
  sum(number_sales)
FROM (
       SELECT
         'store' channel,
         i_brand_id,
         i_class_id,
         i_category_id,
         sum(ss_quantity * ss_list_price) sales,
         count(*) number_sales
       FROM store_sales, item, date_dim
       WHERE ss_item_sk IN (SELECT ss_item_sk
       FROM cross_items)
         AND ss_item_sk = i_item_sk
         AND ss_sold_date_sk = d_date_sk
         AND d_year = 1999 + 2
         AND d_moy = 11
       GROUP BY i_brand_id, i_class_id, i_category_id
       HAVING sum(ss_quantity * ss_list_price) > (SELECT average_sales
       FROM avg_sales)
       UNION ALL
       SELECT
         'catalog' channel,
         i_brand_id,
         i_class_id,
         i_category_id,
         sum(cs_quantity * cs_list_price) sales,
         count(*) number_sales
       FROM catalog_sales, item, date_dim
       WHERE cs_item_sk IN (SELECT ss_item_sk
       FROM cross_items)
         AND cs_item_sk = i_item_sk
         AND cs_sold_date_sk = d_date_sk
         AND d_year = 1999 + 2
         AND d_moy = 11
       GROUP BY i_brand_id, i_class_id, i_category_id
       HAVING sum(cs_quantity * cs_list_price) > (SELECT average_sales FROM avg_sales)
       UNION ALL
       SELECT
         'web' channel,
         i_brand_id,
         i_class_id,
         i_category_id,
         sum(ws_quantity * ws_list_price) sales,
         count(*) number_sales
       FROM web_sales, item, date_dim
       WHERE ws_item_sk IN (SELECT ss_item_sk
       FROM cross_items)
         AND ws_item_sk = i_item_sk
         AND ws_sold_date_sk = d_date_sk
         AND d_year = 1999 + 2
         AND d_moy = 11
       GROUP BY i_brand_id, i_class_id, i_category_id
       HAVING sum(ws_quantity * ws_list_price) > (SELECT average_sales
       FROM avg_sales)
     ) y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id
LIMIT 100
