WITH customer_total_return AS
( SELECT
    sr_customer_sk AS ctr_customer_sk,
    sr_store_sk AS ctr_store_sk,
    sum(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return >
  (SELECT avg(ctr_total_return) * 1.2
  FROM customer_total_return ctr2
  WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
