SELECT
  'web' AS channel,
  web.item,
  web.return_ratio,
  web.return_rank,
  web.currency_rank
FROM (
       SELECT
         item,
         return_ratio,
         currency_ratio,
         rank()
         OVER (
           ORDER BY return_ratio) AS return_rank,
         rank()
         OVER (
           ORDER BY currency_ratio) AS currency_rank
       FROM
         (SELECT
           ws.ws_item_sk AS item,
           (cast(sum(coalesce(wr.wr_return_quantity, 0)) AS DECIMAL(15, 4)) /
             cast(sum(coalesce(ws.ws_quantity, 0)) AS DECIMAL(15, 4))) AS return_ratio,
           (cast(sum(coalesce(wr.wr_return_amt, 0)) AS DECIMAL(15, 4)) /
             cast(sum(coalesce(ws.ws_net_paid, 0)) AS DECIMAL(15, 4))) AS currency_ratio
         FROM
           web_sales ws LEFT OUTER JOIN web_returns wr
             ON (ws.ws_order_number = wr.wr_order_number AND
             ws.ws_item_sk = wr.wr_item_sk)
           , date_dim
         WHERE
           wr.wr_return_amt > 10000
             AND ws.ws_net_profit > 1
             AND ws.ws_net_paid > 0
             AND ws.ws_quantity > 0
             AND ws_sold_date_sk = d_date_sk
             AND d_year = 2001
             AND d_moy = 12
         GROUP BY ws.ws_item_sk
         ) in_web
     ) web
WHERE (web.return_rank <= 10 OR web.currency_rank <= 10)
UNION
SELECT
  'catalog' AS channel,
  catalog.item,
  catalog.return_ratio,
  catalog.return_rank,
  catalog.currency_rank
FROM (
       SELECT
         item,
         return_ratio,
         currency_ratio,
         rank()
         OVER (
           ORDER BY return_ratio) AS return_rank,
         rank()
         OVER (
           ORDER BY currency_ratio) AS currency_rank
       FROM
         (SELECT
           cs.cs_item_sk AS item,
           (cast(sum(coalesce(cr.cr_return_quantity, 0)) AS DECIMAL(15, 4)) /
             cast(sum(coalesce(cs.cs_quantity, 0)) AS DECIMAL(15, 4))) AS return_ratio,
           (cast(sum(coalesce(cr.cr_return_amount, 0)) AS DECIMAL(15, 4)) /
             cast(sum(coalesce(cs.cs_net_paid, 0)) AS DECIMAL(15, 4))) AS currency_ratio
         FROM
           catalog_sales cs LEFT OUTER JOIN catalog_returns cr
             ON (cs.cs_order_number = cr.cr_order_number AND
             cs.cs_item_sk = cr.cr_item_sk)
           , date_dim
         WHERE
           cr.cr_return_amount > 10000
             AND cs.cs_net_profit > 1
             AND cs.cs_net_paid > 0
             AND cs.cs_quantity > 0
             AND cs_sold_date_sk = d_date_sk
             AND d_year = 2001
             AND d_moy = 12
         GROUP BY cs.cs_item_sk
         ) in_cat
     ) catalog
WHERE (catalog.return_rank <= 10 OR catalog.currency_rank <= 10)
UNION
SELECT
  'store' AS channel,
  store.item,
  store.return_ratio,
  store.return_rank,
  store.currency_rank
FROM (
       SELECT
         item,
         return_ratio,
         currency_ratio,
         rank()
         OVER (
           ORDER BY return_ratio) AS return_rank,
         rank()
         OVER (
           ORDER BY currency_ratio) AS currency_rank
       FROM
         (SELECT
           sts.ss_item_sk AS item,
           (cast(sum(coalesce(sr.sr_return_quantity, 0)) AS DECIMAL(15, 4)) /
             cast(sum(coalesce(sts.ss_quantity, 0)) AS DECIMAL(15, 4))) AS return_ratio,
           (cast(sum(coalesce(sr.sr_return_amt, 0)) AS DECIMAL(15, 4)) /
             cast(sum(coalesce(sts.ss_net_paid, 0)) AS DECIMAL(15, 4))) AS currency_ratio
         FROM
           store_sales sts LEFT OUTER JOIN store_returns sr
             ON (sts.ss_ticket_number = sr.sr_ticket_number AND sts.ss_item_sk = sr.sr_item_sk)
           , date_dim
         WHERE
           sr.sr_return_amt > 10000
             AND sts.ss_net_profit > 1
             AND sts.ss_net_paid > 0
             AND sts.ss_quantity > 0
             AND ss_sold_date_sk = d_date_sk
             AND d_year = 2001
             AND d_moy = 12
         GROUP BY sts.ss_item_sk
         ) in_store
     ) store
WHERE (store.return_rank <= 10 OR store.currency_rank <= 10)
ORDER BY 1, 4, 5
LIMIT 100
