WITH customer_total_return AS
(SELECT
    cr_returning_customer_sk AS ctr_customer_sk,
    ca_state AS ctr_state,
    sum(cr_return_amt_inc_tax) AS ctr_total_return
  FROM catalog_returns, date_dim, customer_address
  WHERE cr_returned_date_sk = d_date_sk
    AND d_year = 2000
    AND cr_returning_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state )
SELECT
  c_customer_id,
  c_salutation,
  c_first_name,
  c_last_name,
  ca_street_number,
  ca_street_name,
  ca_street_type,
  ca_suite_number,
  ca_city,
  ca_county,
  ca_state,
  ca_zip,
  ca_country,
  ca_gmt_offset,
  ca_location_type,
  ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
FROM customer_total_return ctr2
WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name, ca_street_number, ca_street_name
  , ca_street_type, ca_suite_number, ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset
  , ca_location_type, ctr_total_return
LIMIT 100
