SELECT
  s_store_name,
  i_item_desc,
  sc.revenue,
  i_current_price,
  i_wholesale_cost,
  i_brand
FROM store, item,
  (SELECT
    ss_store_sk,
    avg(revenue) AS ave
  FROM
    (SELECT
      ss_store_sk,
      ss_item_sk,
      sum(ss_sales_price) AS revenue
    FROM store_sales, date_dim
    WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 1176 AND 1176 + 11
    GROUP BY ss_store_sk, ss_item_sk) sa
  GROUP BY ss_store_sk) sb,
  (SELECT
    ss_store_sk,
    ss_item_sk,
    sum(ss_sales_price) AS revenue
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 1176 AND 1176 + 11
  GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk AND
  sc.revenue <= 0.1 * sb.ave AND
  s_store_sk = sc.ss_store_sk AND
  i_item_sk = sc.ss_item_sk
ORDER BY s_store_name, i_item_desc
LIMIT 100
