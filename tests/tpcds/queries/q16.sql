SELECT
  count(DISTINCT cs_order_number) AS `order count `,
  sum(cs_ext_ship_cost) AS `total shipping cost `,
  sum(cs_net_profit) AS `total net profit `
FROM
  catalog_sales cs1, date_dim, customer_address, call_center
WHERE
  d_date BETWEEN '2002-02-01' AND (CAST('2002-02-01' AS DATE) + INTERVAL 60 days)
    AND cs1.cs_ship_date_sk = d_date_sk
    AND cs1.cs_ship_addr_sk = ca_address_sk
    AND ca_state = 'GA'
    AND cs1.cs_call_center_sk = cc_call_center_sk
    AND cc_county IN
    ('Williamson County', 'Williamson County', 'Williamson County', 'Williamson County', 'Williamson County')
    AND EXISTS(SELECT *
               FROM catalog_sales cs2
               WHERE cs1.cs_order_number = cs2.cs_order_number
                 AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
    AND NOT EXISTS(SELECT *
                   FROM catalog_returns cr1
                   WHERE cs1.cs_order_number = cr1.cr_order_number)
ORDER BY count(DISTINCT cs_order_number)
LIMIT 100
