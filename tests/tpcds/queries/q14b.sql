WITH cross_items AS
(SELECT i_item_sk ss_item_sk
  FROM item,
    (SELECT
      iss.i_brand_id brand_id,
      iss.i_class_id class_id,
      iss.i_category_id category_id
    FROM store_sales, item iss, date_dim d1
    WHERE ss_item_sk = iss.i_item_sk
      AND ss_sold_date_sk = d1.d_date_sk
      AND d1.d_year BETWEEN 1999 AND 1999 + 2
    INTERSECT
    SELECT
      ics.i_brand_id,
      ics.i_class_id,
      ics.i_category_id
    FROM catalog_sales, item ics, date_dim d2
    WHERE cs_item_sk = ics.i_item_sk
      AND cs_sold_date_sk = d2.d_date_sk
      AND d2.d_year BETWEEN 1999 AND 1999 + 2
    INTERSECT
    SELECT
      iws.i_brand_id,
      iws.i_class_id,
      iws.i_category_id
    FROM web_sales, item iws, date_dim d3
    WHERE ws_item_sk = iws.i_item_sk
      AND ws_sold_date_sk = d3.d_date_sk
      AND d3.d_year BETWEEN 1999 AND 1999 + 2) x
  WHERE i_brand_id = brand_id
    AND i_class_id = class_id
    AND i_category_id = category_id
),
    avg_sales AS
  (SELECT avg(quantity * list_price) average_sales
  FROM (SELECT
          ss_quantity quantity,
          ss_list_price list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 1999 + 2
        UNION ALL
        SELECT
          cs_quantity quantity,
          cs_list_price list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 1999 + 2
        UNION ALL
        SELECT
          ws_quantity quantity,
          ws_list_price list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 1999 + 2) x)
SELECT *
FROM
  (SELECT
    'store' channel,
    i_brand_id,
    i_class_id,
    i_category_id,
    sum(ss_quantity * ss_list_price) sales,
    count(*) number_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk IN (SELECT ss_item_sk
  FROM cross_items)
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_week_seq = (SELECT d_week_seq
  FROM date_dim
  WHERE d_year = 1999 + 1 AND d_moy = 12 AND d_dom = 11)
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(ss_quantity * ss_list_price) > (SELECT average_sales
  FROM avg_sales)) this_year,
  (SELECT
    'store' channel,
    i_brand_id,
    i_class_id,
    i_category_id,
    sum(ss_quantity * ss_list_price) sales,
    count(*) number_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk IN (SELECT ss_item_sk
  FROM cross_items)
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_week_seq = (SELECT d_week_seq
  FROM date_dim
  WHERE d_year = 1999 AND d_moy = 12 AND d_dom = 11)
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(ss_quantity * ss_list_price) > (SELECT average_sales
  FROM avg_sales)) last_year
WHERE this_year.i_brand_id = last_year.i_brand_id
  AND this_year.i_class_id = last_year.i_class_id
  AND this_year.i_category_id = last_year.i_category_id
ORDER BY this_year.channel, this_year.i_brand_id, this_year.i_class_id, this_year.i_category_id
LIMIT 100
