SELECT
  s_store_name,
  s_store_id,
  sum(CASE WHEN (d_day_name = 'Sunday')
    THEN ss_sales_price
      ELSE NULL END) sun_sales,
  sum(CASE WHEN (d_day_name = 'Monday')
    THEN ss_sales_price
      ELSE NULL END) mon_sales,
  sum(CASE WHEN (d_day_name = 'Tuesday')
    THEN ss_sales_price
      ELSE NULL END) tue_sales,
  sum(CASE WHEN (d_day_name = 'Wednesday')
    THEN ss_sales_price
      ELSE NULL END) wed_sales,
  sum(CASE WHEN (d_day_name = 'Thursday')
    THEN ss_sales_price
      ELSE NULL END) thu_sales,
  sum(CASE WHEN (d_day_name = 'Friday')
    THEN ss_sales_price
      ELSE NULL END) fri_sales,
  sum(CASE WHEN (d_day_name = 'Saturday')
    THEN ss_sales_price
      ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk AND
  s_store_sk = ss_store_sk AND
  s_gmt_offset = -5 AND
  d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales, wed_sales,
  thu_sales, fri_sales, sat_sales
LIMIT 100
