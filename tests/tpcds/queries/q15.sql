SELECT
  ca_zip,
  sum(cs_sales_price)
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
  OR ca_state IN ('CA', 'WA', 'GA')
  OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
