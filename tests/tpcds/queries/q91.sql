SELECT
  cc_call_center_id Call_Center,
  cc_name Call_Center_Name,
  cc_manager Manager,
  sum(cr_net_loss) Returns_Loss
FROM
  call_center, catalog_returns, date_dim, customer, customer_address,
  customer_demographics, household_demographics
WHERE
  cr_call_center_sk = cc_call_center_sk
    AND cr_returned_date_sk = d_date_sk
    AND cr_returning_customer_sk = c_customer_sk
    AND cd_demo_sk = c_current_cdemo_sk
    AND hd_demo_sk = c_current_hdemo_sk
    AND ca_address_sk = c_current_addr_sk
    AND d_year = 1998
    AND d_moy = 11
    AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
    OR (cd_marital_status = 'W' AND cd_education_status = 'Advanced Degree'))
    AND hd_buy_potential LIKE 'Unknown%'
    AND ca_gmt_offset = -7
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status, cd_education_status
ORDER BY sum(cr_net_loss) DESC
