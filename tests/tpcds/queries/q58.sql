WITH ss_items AS
(SELECT
    i_item_id item_id,
    sum(ss_ext_sales_price) ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
  FROM date_dim
  WHERE d_week_seq = (SELECT d_week_seq
  FROM date_dim
  WHERE d_date = '2000-01-03'))
    AND ss_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
    cs_items AS
  (SELECT
    i_item_id item_id,
    sum(cs_ext_sales_price) cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
  FROM date_dim
  WHERE d_week_seq = (SELECT d_week_seq
  FROM date_dim
  WHERE d_date = '2000-01-03'))
    AND cs_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
    ws_items AS
  (SELECT
    i_item_id item_id,
    sum(ws_ext_sales_price) ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
  FROM date_dim
  WHERE d_week_seq = (SELECT d_week_seq
  FROM date_dim
  WHERE d_date = '2000-01-03'))
    AND ws_sold_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT
  ss_items.item_id,
  ss_item_rev,
  ss_item_rev / (ss_item_rev + cs_item_rev + ws_item_rev) / 3 * 100 ss_dev,
  cs_item_rev,
  cs_item_rev / (ss_item_rev + cs_item_rev + ws_item_rev) / 3 * 100 cs_dev,
  ws_item_rev,
  ws_item_rev / (ss_item_rev + cs_item_rev + ws_item_rev) / 3 * 100 ws_dev,
  (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
  AND ss_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND cs_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND cs_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND ws_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND ws_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
ORDER BY item_id, ss_item_rev
LIMIT 100
