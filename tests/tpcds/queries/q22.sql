SELECT
  i_product_name,
  i_brand,
  i_class,
  i_category,
  avg(inv_quantity_on_hand) qoh
FROM inventory, date_dim, item, warehouse
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND d_month_seq BETWEEN 1200 AND 1200 + 11
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category
LIMIT 100
