SELECT
  c_last_name,
  c_first_name,
  c_salutation,
  c_preferred_cust_flag,
  ss_ticket_number,
  cnt
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    count(*) cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND date_dim.d_dom BETWEEN 1 AND 2
    AND (household_demographics.hd_buy_potential = '>10000' OR
    household_demographics.hd_buy_potential = 'unknown')
    AND household_demographics.hd_vehicle_count > 0
    AND CASE WHEN household_demographics.hd_vehicle_count > 0
    THEN
      household_demographics.hd_dep_count / household_demographics.hd_vehicle_count
        ELSE NULL END > 1
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_county IN ('Williamson County', 'Franklin Parish', 'Bronx County', 'Orange County')
  GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC
