SELECT *
FROM (
       SELECT
         w_warehouse_name,
         i_item_id,
         sum(CASE WHEN (cast(d_date AS DATE) < cast('2000-03-11' AS DATE))
           THEN inv_quantity_on_hand
             ELSE 0 END) AS inv_before,
         sum(CASE WHEN (cast(d_date AS DATE) >= cast('2000-03-11' AS DATE))
           THEN inv_quantity_on_hand
             ELSE 0 END) AS inv_after
       FROM inventory, warehouse, item, date_dim
       WHERE i_current_price BETWEEN 0.99 AND 1.49
         AND i_item_sk = inv_item_sk
         AND inv_warehouse_sk = w_warehouse_sk
         AND inv_date_sk = d_date_sk
         AND d_date BETWEEN (cast('2000-03-11' AS DATE) - INTERVAL 30 days)
       AND (cast('2000-03-11' AS DATE) + INTERVAL 30 days)
       GROUP BY w_warehouse_name, i_item_id) x
WHERE (CASE WHEN inv_before > 0
  THEN inv_after / inv_before
       ELSE NULL
       END) BETWEEN 2.0 / 3.0 AND 3.0 / 2.0
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
