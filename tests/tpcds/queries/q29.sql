SELECT
  i_item_id,
  i_item_desc,
  s_store_id,
  s_store_name,
  sum(ss_quantity) AS store_sales_quantity,
  sum(sr_return_quantity) AS store_returns_quantity,
  sum(cs_quantity) AS catalog_sales_quantity
FROM
  store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
  date_dim d3, store, item
WHERE
  d1.d_moy = 9
    AND d1.d_year = 1999
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND ss_customer_sk = sr_customer_sk
    AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND sr_returned_date_sk = d2.d_date_sk
    AND d2.d_moy BETWEEN 9 AND 9 + 3
    AND d2.d_year = 1999
    AND sr_customer_sk = cs_bill_customer_sk
    AND sr_item_sk = cs_item_sk
    AND cs_sold_date_sk = d3.d_date_sk
    AND d3.d_year IN (1999, 1999 + 1, 1999 + 2)
GROUP BY
  i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY
  i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
