SELECT
  substr(w_warehouse_name, 1, 20),
  sm_type,
  web_name,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 30) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 60) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 90) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  web_sales, warehouse, ship_mode, web_site, date_dim
WHERE
  d_month_seq BETWEEN 1200 AND 1200 + 11
    AND ws_ship_date_sk = d_date_sk
    AND ws_warehouse_sk = w_warehouse_sk
    AND ws_ship_mode_sk = sm_ship_mode_sk
    AND ws_web_site_sk = web_site_sk
GROUP BY
  substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY
  substr(w_warehouse_name, 1, 20), sm_type, web_name
LIMIT 100
