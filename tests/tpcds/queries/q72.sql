SELECT
  i_item_desc,
  w_warehouse_name,
  d1.d_week_seq,
  count(CASE WHEN p_promo_sk IS NULL
    THEN 1
        ELSE 0 END) no_promo,
  count(CASE WHEN p_promo_sk IS NOT NULL
    THEN 1
        ELSE 0 END) promo,
  count(*) total_cnt
FROM catalog_sales
  JOIN inventory ON (cs_item_sk = inv_item_sk)
  JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
  JOIN item ON (i_item_sk = cs_item_sk)
  JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
  JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
  JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
  JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
  JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
  LEFT OUTER JOIN promotion ON (cs_promo_sk = p_promo_sk)
  LEFT OUTER JOIN catalog_returns ON (cr_item_sk = cs_item_sk AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > (cast(d1.d_date AS DATE) + interval 5 days)
  AND hd_buy_potential = '>10000'
  AND d1.d_year = 1999
  AND hd_buy_potential = '>10000'
  AND cd_marital_status = 'D'
  AND d1.d_year = 1999
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d_week_seq
LIMIT 100
