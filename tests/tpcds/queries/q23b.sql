WITH frequent_ss_items AS
(SELECT
    substr(i_item_desc, 1, 30) itemdesc,
    i_item_sk item_sk,
    d_date solddate,
    count(*) cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
    AND d_year IN (2000, 2000 + 1, 2000 + 2, 2000 + 3)
  GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING count(*) > 4),
    max_store_sales AS
  (SELECT max(csales) tpcds_cmax
  FROM (SELECT
    c_customer_sk,
    sum(ss_quantity * ss_sales_price) csales
  FROM store_sales, customer, date_dim
  WHERE ss_customer_sk = c_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2000, 2000 + 1, 2000 + 2, 2000 + 3)
  GROUP BY c_customer_sk) x),
    best_ss_customer AS
  (SELECT
    c_customer_sk,
    sum(ss_quantity * ss_sales_price) ssales
  FROM store_sales
    , customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING sum(ss_quantity * ss_sales_price) > (50 / 100.0) *
    (SELECT *
    FROM max_store_sales))
SELECT
  c_last_name,
  c_first_name,
  sales
FROM ((SELECT
  c_last_name,
  c_first_name,
  sum(cs_quantity * cs_list_price) sales
FROM catalog_sales, customer, date_dim
WHERE d_year = 2000
  AND d_moy = 2
  AND cs_sold_date_sk = d_date_sk
  AND cs_item_sk IN (SELECT item_sk
FROM frequent_ss_items)
  AND cs_bill_customer_sk IN (SELECT c_customer_sk
FROM best_ss_customer)
  AND cs_bill_customer_sk = c_customer_sk
GROUP BY c_last_name, c_first_name)
      UNION ALL
      (SELECT
        c_last_name,
        c_first_name,
        sum(ws_quantity * ws_list_price) sales
      FROM web_sales, customer, date_dim
      WHERE d_year = 2000
        AND d_moy = 2
        AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk
      FROM frequent_ss_items)
        AND ws_bill_customer_sk IN (SELECT c_customer_sk
      FROM best_ss_customer)
        AND ws_bill_customer_sk = c_customer_sk
      GROUP BY c_last_name, c_first_name)) y
ORDER BY c_last_name, c_first_name, sales
LIMIT 100
