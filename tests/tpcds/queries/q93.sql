SELECT
  ss_customer_sk,
  sum(act_sales) sumsales
FROM (SELECT
  ss_item_sk,
  ss_ticket_number,
  ss_customer_sk,
  CASE WHEN sr_return_quantity IS NOT NULL
    THEN (ss_quantity - sr_return_quantity) * ss_sales_price
  ELSE (ss_quantity * ss_sales_price) END act_sales
FROM store_sales
  LEFT OUTER JOIN store_returns
    ON (sr_item_sk = ss_item_sk AND sr_ticket_number = ss_ticket_number)
  ,
  reason
WHERE sr_reason_sk = r_reason_sk AND r_reason_desc = 'reason 28') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
