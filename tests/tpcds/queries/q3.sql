SELECT
  dt.d_year,
  item.i_brand_id brand_id,
  item.i_brand brand,
  SUM(ss_ext_sales_price) sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manufact_id = 128
  AND dt.d_moy = 11
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, sum_agg DESC, brand_id
LIMIT 100
