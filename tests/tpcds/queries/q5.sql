WITH ssr AS
( SELECT
    s_store_id,
    sum(sales_price) AS sales,
    sum(profit) AS profit,
    sum(return_amt) AS RETURNS,
    sum(net_loss) AS profit_loss
  FROM
    (SELECT
       ss_store_sk AS store_sk,
       ss_sold_date_sk AS date_sk,
       ss_ext_sales_price AS sales_price,
       ss_net_profit AS profit,
       cast(0 AS DECIMAL(7, 2)) AS return_amt,
       cast(0 AS DECIMAL(7, 2)) AS net_loss
     FROM store_sales
     UNION ALL
     SELECT
       sr_store_sk AS store_sk,
       sr_returned_date_sk AS date_sk,
       cast(0 AS DECIMAL(7, 2)) AS sales_price,
       cast(0 AS DECIMAL(7, 2)) AS profit,
       sr_return_amt AS return_amt,
       sr_net_loss AS net_loss
     FROM store_returns)
    salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND ((cast('2000-08-23' AS DATE) + INTERVAL 14 days))
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
    csr AS
  ( SELECT
    cp_catalog_page_id,
    sum(sales_price) AS sales,
    sum(profit) AS profit,
    sum(return_amt) AS RETURNS,
    sum(net_loss) AS profit_loss
  FROM
    (SELECT
       cs_catalog_page_sk AS page_sk,
       cs_sold_date_sk AS date_sk,
       cs_ext_sales_price AS sales_price,
       cs_net_profit AS profit,
       cast(0 AS DECIMAL(7, 2)) AS return_amt,
       cast(0 AS DECIMAL(7, 2)) AS net_loss
     FROM catalog_sales
     UNION ALL
     SELECT
       cr_catalog_page_sk AS page_sk,
       cr_returned_date_sk AS date_sk,
       cast(0 AS DECIMAL(7, 2)) AS sales_price,
       cast(0 AS DECIMAL(7, 2)) AS profit,
       cr_return_amount AS return_amt,
       cr_net_loss AS net_loss
     FROM catalog_returns
    ) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND ((cast('2000-08-23' AS DATE) + INTERVAL 14 days))
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id)
  ,
    wsr AS
  ( SELECT
    web_site_id,
    sum(sales_price) AS sales,
    sum(profit) AS profit,
    sum(return_amt) AS RETURNS,
    sum(net_loss) AS profit_loss
  FROM
    (SELECT
       ws_web_site_sk AS wsr_web_site_sk,
       ws_sold_date_sk AS date_sk,
       ws_ext_sales_price AS sales_price,
       ws_net_profit AS profit,
       cast(0 AS DECIMAL(7, 2)) AS return_amt,
       cast(0 AS DECIMAL(7, 2)) AS net_loss
     FROM web_sales
     UNION ALL
     SELECT
       ws_web_site_sk AS wsr_web_site_sk,
       wr_returned_date_sk AS date_sk,
       cast(0 AS DECIMAL(7, 2)) AS sales_price,
       cast(0 AS DECIMAL(7, 2)) AS profit,
       wr_return_amt AS return_amt,
       wr_net_loss AS net_loss
     FROM web_returns
       LEFT OUTER JOIN web_sales ON
                                   (wr_item_sk = ws_item_sk
                                     AND wr_order_number = ws_order_number)
    ) salesreturns, date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND ((cast('2000-08-23' AS DATE) + INTERVAL 14 days))
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_site_id)
SELECT
  channel,
  id,
  sum(sales) AS sales,
  sum(returns) AS returns,
  sum(profit) AS profit
FROM
  (SELECT
     'store channel' AS channel,
     concat('store', s_store_id) AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM ssr
   UNION ALL
   SELECT
     'catalog channel' AS channel,
     concat('catalog_page', cp_catalog_page_id) AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM csr
   UNION ALL
   SELECT
     'web channel' AS channel,
     concat('web_site', web_site_id) AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM wsr
  ) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
