SELECT
  a.ca_state state,
  count(*) cnt
FROM
  customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq =
  (SELECT DISTINCT (d_month_seq)
  FROM date_dim
  WHERE d_year = 2000 AND d_moy = 1)
  AND i.i_current_price > 1.2 *
  (SELECT avg(j.i_current_price)
  FROM item j
  WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING count(*) >= 10
ORDER BY cnt
LIMIT 100
