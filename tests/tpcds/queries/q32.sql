SELECT sum(cs_ext_discount_amt) AS `excess discount amount`
FROM
  catalog_sales, item, date_dim
WHERE
  i_manufact_id = 977
    AND i_item_sk = cs_item_sk
    AND d_date BETWEEN '2000-01-27' AND (cast('2000-01-27' AS DATE) + interval 90 days)
    AND d_date_sk = cs_sold_date_sk
    AND cs_ext_discount_amt > (
    SELECT 1.3 * avg(cs_ext_discount_amt)
    FROM catalog_sales, date_dim
    WHERE cs_item_sk = i_item_sk
      AND d_date BETWEEN '2000-01-27' AND (cast('2000-01-27' AS DATE) + interval 90 days)
      AND d_date_sk = cs_sold_date_sk)
LIMIT 100
