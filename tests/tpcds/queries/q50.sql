SELECT
  s_store_name,
  s_company_id,
  s_street_number,
  s_street_name,
  s_street_type,
  s_suite_number,
  s_city,
  s_county,
  s_state,
  s_zip,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 30) AND
    (sr_returned_date_sk - ss_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 60) AND
    (sr_returned_date_sk - ss_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 90) AND
    (sr_returned_date_sk - ss_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE
  d2.d_year = 2001
    AND d2.d_moy = 8
    AND ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_sold_date_sk = d1.d_date_sk
    AND sr_returned_date_sk = d2.d_date_sk
    AND ss_customer_sk = sr_customer_sk
    AND ss_store_sk = s_store_sk
GROUP BY
  s_store_name, s_company_id, s_street_number, s_street_name, s_street_type,
  s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY
  s_store_name, s_company_id, s_street_number, s_street_name, s_street_type,
  s_suite_number, s_city, s_county, s_state, s_zip
LIMIT 100
