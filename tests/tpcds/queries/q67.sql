SELECT *
FROM
  (SELECT
    i_category,
    i_class,
    i_brand,
    i_product_name,
    d_year,
    d_qoy,
    d_moy,
    s_store_id,
    sumsales,
    rank()
    OVER (PARTITION BY i_category
      ORDER BY sumsales DESC) rk
  FROM
    (SELECT
      i_category,
      i_class,
      i_brand,
      i_product_name,
      d_year,
      d_qoy,
      d_moy,
      s_store_id,
      sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales
    FROM store_sales, date_dim, store, item
    WHERE ss_sold_date_sk = d_date_sk
      AND ss_item_sk = i_item_sk
      AND ss_store_sk = s_store_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
    GROUP BY ROLLUP (i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
      d_moy, s_store_id)) dw1) dw2
WHERE rk <= 100
ORDER BY
  i_category, i_class, i_brand, i_product_name, d_year,
  d_qoy, d_moy, s_store_id, sumsales, rk
LIMIT 100
