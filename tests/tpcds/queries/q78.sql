WITH ws AS
(SELECT
    d_year AS ws_sold_year,
    ws_item_sk,
    ws_bill_customer_sk ws_customer_sk,
    sum(ws_quantity) ws_qty,
    sum(ws_wholesale_cost) ws_wc,
    sum(ws_sales_price) ws_sp
  FROM web_sales
    LEFT JOIN web_returns ON wr_order_number = ws_order_number AND ws_item_sk = wr_item_sk
    JOIN date_dim ON ws_sold_date_sk = d_date_sk
  WHERE wr_order_number IS NULL
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk
),
    cs AS
  (SELECT
    d_year AS cs_sold_year,
    cs_item_sk,
    cs_bill_customer_sk cs_customer_sk,
    sum(cs_quantity) cs_qty,
    sum(cs_wholesale_cost) cs_wc,
    sum(cs_sales_price) cs_sp
  FROM catalog_sales
    LEFT JOIN catalog_returns ON cr_order_number = cs_order_number AND cs_item_sk = cr_item_sk
    JOIN date_dim ON cs_sold_date_sk = d_date_sk
  WHERE cr_order_number IS NULL
  GROUP BY d_year, cs_item_sk, cs_bill_customer_sk
  ),
    ss AS
  (SELECT
    d_year AS ss_sold_year,
    ss_item_sk,
    ss_customer_sk,
    sum(ss_quantity) ss_qty,
    sum(ss_wholesale_cost) ss_wc,
    sum(ss_sales_price) ss_sp
  FROM store_sales
    LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number AND ss_item_sk = sr_item_sk
    JOIN date_dim ON ss_sold_date_sk = d_date_sk
  WHERE sr_ticket_number IS NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk
  )
SELECT
  round(ss_qty / (coalesce(ws_qty + cs_qty, 1)), 2) ratio,
  ss_qty store_qty,
  ss_wc store_wholesale_cost,
  ss_sp store_sales_price,
  coalesce(ws_qty, 0) + coalesce(cs_qty, 0) other_chan_qty,
  coalesce(ws_wc, 0) + coalesce(cs_wc, 0) other_chan_wholesale_cost,
  coalesce(ws_sp, 0) + coalesce(cs_sp, 0) other_chan_sales_price
FROM ss
  LEFT JOIN ws
    ON (ws_sold_year = ss_sold_year AND ws_item_sk = ss_item_sk AND ws_customer_sk = ss_customer_sk)
  LEFT JOIN cs
    ON (cs_sold_year = ss_sold_year AND cs_item_sk = ss_item_sk AND cs_customer_sk = ss_customer_sk)
WHERE coalesce(ws_qty, 0) > 0 AND coalesce(cs_qty, 0) > 0 AND ss_sold_year = 2000
ORDER BY
  ratio,
  ss_qty DESC, ss_wc DESC, ss_sp DESC,
  other_chan_qty,
  other_chan_wholesale_cost,
  other_chan_sales_price,
  round(ss_qty / (coalesce(ws_qty + cs_qty, 1)), 2)
LIMIT 100
