SELECT
  c_last_name,
  c_first_name,
  substr(s_city, 1, 30),
  ss_ticket_number,
  amt,
  profit
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    store.s_city,
    sum(ss_coupon_amt) amt,
    sum(ss_net_profit) profit
  FROM store_sales, date_dim, store, household_demographics
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND (household_demographics.hd_dep_count = 6 OR
    household_demographics.hd_vehicle_count > 2)
    AND date_dim.d_dow = 1
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_number_employees BETWEEN 200 AND 295
  GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, substr(s_city, 1, 30), profit
LIMIT 100
