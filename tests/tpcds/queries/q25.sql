SELECT
  i_item_id,
  i_item_desc,
  s_store_id,
  s_store_name,
  sum(ss_net_profit) AS store_sales_profit,
  sum(sr_net_loss) AS store_returns_loss,
  sum(cs_net_profit) AS catalog_sales_profit
FROM
  store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2, date_dim d3,
  store, item
WHERE
  d1.d_moy = 4
    AND d1.d_year = 2001
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND ss_customer_sk = sr_customer_sk
    AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND sr_returned_date_sk = d2.d_date_sk
    AND d2.d_moy BETWEEN 4 AND 10
    AND d2.d_year = 2001
    AND sr_customer_sk = cs_bill_customer_sk
    AND sr_item_sk = cs_item_sk
    AND cs_sold_date_sk = d3.d_date_sk
    AND d3.d_moy BETWEEN 4 AND 10
    AND d3.d_year = 2001
GROUP BY
  i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY
  i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100