SELECT
  i_item_id,
  ca_country,
  ca_state,
  ca_county,
  avg(cast(cs_quantity AS DECIMAL(12, 2))) agg1,
  avg(cast(cs_list_price AS DECIMAL(12, 2))) agg2,
  avg(cast(cs_coupon_amt AS DECIMAL(12, 2))) agg3,
  avg(cast(cs_sales_price AS DECIMAL(12, 2))) agg4,
  avg(cast(cs_net_profit AS DECIMAL(12, 2))) agg5,
  avg(cast(c_birth_year AS DECIMAL(12, 2))) agg6,
  avg(cast(cd1.cd_dep_count AS DECIMAL(12, 2))) agg7
FROM catalog_sales, customer_demographics cd1,
  customer_demographics cd2, customer, customer_address, date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND
  cs_item_sk = i_item_sk AND
  cs_bill_cdemo_sk = cd1.cd_demo_sk AND
  cs_bill_customer_sk = c_customer_sk AND
  cd1.cd_gender = 'F' AND
  cd1.cd_education_status = 'Unknown' AND
  c_current_cdemo_sk = cd2.cd_demo_sk AND
  c_current_addr_sk = ca_address_sk AND
  c_birth_month IN (1, 6, 8, 9, 12, 2) AND
  d_year = 1998 AND
  ca_state IN ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id
LIMIT 100
