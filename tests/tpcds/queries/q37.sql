SELECT
  i_item_id,
  i_item_desc,
  i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 68 AND 68 + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN cast('2000-02-01' AS DATE) AND (cast('2000-02-01' AS DATE) + INTERVAL 60 days)
  AND i_manufact_id IN (677, 940, 694, 808)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
