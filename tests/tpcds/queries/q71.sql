SELECT
  i_brand_id brand_id,
  i_brand brand,
  t_hour,
  t_minute,
  sum(ext_price) ext_price
FROM item,
  (SELECT
     ws_ext_sales_price AS ext_price,
     ws_sold_date_sk AS sold_date_sk,
     ws_item_sk AS sold_item_sk,
     ws_sold_time_sk AS time_sk
   FROM web_sales, date_dim
   WHERE d_date_sk = ws_sold_date_sk
     AND d_moy = 11
     AND d_year = 1999
   UNION ALL
   SELECT
     cs_ext_sales_price AS ext_price,
     cs_sold_date_sk AS sold_date_sk,
     cs_item_sk AS sold_item_sk,
     cs_sold_time_sk AS time_sk
   FROM catalog_sales, date_dim
   WHERE d_date_sk = cs_sold_date_sk
     AND d_moy = 11
     AND d_year = 1999
   UNION ALL
   SELECT
     ss_ext_sales_price AS ext_price,
     ss_sold_date_sk AS sold_date_sk,
     ss_item_sk AS sold_item_sk,
     ss_sold_time_sk AS time_sk
   FROM store_sales, date_dim
   WHERE d_date_sk = ss_sold_date_sk
     AND d_moy = 11
     AND d_year = 1999
  ) AS tmp, time_dim
WHERE
  sold_item_sk = i_item_sk
    AND i_manager_id = 1
    AND time_sk = t_time_sk
    AND (t_meal_time = 'breakfast' OR t_meal_time = 'dinner')
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, brand_id
