WITH year_total AS (
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    d_year AS year,
    sum(ss_net_paid) year_total,
    's' sale_type
  FROM
    customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2001 + 1)
  GROUP BY
    c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    d_year AS year,
    sum(ws_net_paid) year_total,
    'w' sale_type
  FROM
    customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2001 + 1)
  GROUP BY
    c_customer_id, c_first_name, c_last_name, d_year)
SELECT
  t_s_secyear.customer_id,
  t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name
FROM
  year_total t_s_firstyear, year_total t_s_secyear,
  year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.year = 2001
  AND t_s_secyear.year = 2001 + 1
  AND t_w_firstyear.year = 2001
  AND t_w_secyear.year = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
  THEN t_w_secyear.year_total / t_w_firstyear.year_total
      ELSE NULL END
  > CASE WHEN t_s_firstyear.year_total > 0
  THEN t_s_secyear.year_total / t_s_firstyear.year_total
    ELSE NULL END
ORDER BY 1, 1, 1
LIMIT 100
