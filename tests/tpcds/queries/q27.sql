SELECT
  i_item_id,
  s_state,
  grouping(s_state) g_state,
  avg(ss_quantity) agg1,
  avg(ss_list_price) agg2,
  avg(ss_coupon_amt) agg3,
  avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND
  ss_item_sk = i_item_sk AND
  ss_store_sk = s_store_sk AND
  ss_cdemo_sk = cd_demo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  d_year = 2002 AND
  s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN')
GROUP BY ROLLUP (i_item_id, s_state)
ORDER BY i_item_id, s_state
LIMIT 100
