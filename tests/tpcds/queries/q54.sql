WITH my_customers AS (
  SELECT DISTINCT
    c_customer_sk,
    c_current_addr_sk
  FROM
    (SELECT
       cs_sold_date_sk sold_date_sk,
       cs_bill_customer_sk customer_sk,
       cs_item_sk item_sk
     FROM catalog_sales
     UNION ALL
     SELECT
       ws_sold_date_sk sold_date_sk,
       ws_bill_customer_sk customer_sk,
       ws_item_sk item_sk
     FROM web_sales
    ) cs_or_ws_sales,
    item,
    date_dim,
    customer
  WHERE sold_date_sk = d_date_sk
    AND item_sk = i_item_sk
    AND i_category = 'Women'
    AND i_class = 'maternity'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_moy = 12
    AND d_year = 1998
)
  , my_revenue AS (
  SELECT
    c_customer_sk,
    sum(ss_ext_sales_price) AS revenue
  FROM my_customers,
    store_sales,
    customer_address,
    store,
    date_dim
  WHERE c_current_addr_sk = ca_address_sk
    AND ca_county = s_county
    AND ca_state = s_state
    AND ss_sold_date_sk = d_date_sk
    AND c_customer_sk = ss_customer_sk
    AND d_month_seq BETWEEN (SELECT DISTINCT d_month_seq + 1
  FROM date_dim
  WHERE d_year = 1998 AND d_moy = 12)
  AND (SELECT DISTINCT d_month_seq + 3
  FROM date_dim
  WHERE d_year = 1998 AND d_moy = 12)
  GROUP BY c_customer_sk
)
  , segments AS
(SELECT cast((revenue / 50) AS INT) AS segment
  FROM my_revenue)
SELECT
  segment,
  count(*) AS num_customers,
  segment * 50 AS segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers
LIMIT 100
