SELECT
  i_brand_id brand_id,
  i_brand brand,
  sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, brand_id
LIMIT 100
