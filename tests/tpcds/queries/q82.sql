SELECT
  i_item_id,
  i_item_desc,
  i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 62 AND 62 + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN cast('2000-05-25' AS DATE) AND (cast('2000-05-25' AS DATE) + INTERVAL 60 days)
  AND i_manufact_id IN (129, 270, 821, 423)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
