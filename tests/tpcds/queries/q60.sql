WITH ss AS (
  SELECT
    i_item_id,
    sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE
    i_item_id IN (SELECT i_item_id
    FROM item
    WHERE i_category IN ('Music'))
      AND ss_item_sk = i_item_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year = 1998
      AND d_moy = 9
      AND ss_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_item_id),
    cs AS (
    SELECT
      i_item_id,
      sum(cs_ext_sales_price) total_sales
    FROM catalog_sales, date_dim, customer_address, item
    WHERE
      i_item_id IN (SELECT i_item_id
      FROM item
      WHERE i_category IN ('Music'))
        AND cs_item_sk = i_item_sk
        AND cs_sold_date_sk = d_date_sk
        AND d_year = 1998
        AND d_moy = 9
        AND cs_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_item_id),
    ws AS (
    SELECT
      i_item_id,
      sum(ws_ext_sales_price) total_sales
    FROM web_sales, date_dim, customer_address, item
    WHERE
      i_item_id IN (SELECT i_item_id
      FROM item
      WHERE i_category IN ('Music'))
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = 1998
        AND d_moy = 9
        AND ws_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_item_id)
SELECT
  i_item_id,
  sum(total_sales) total_sales
FROM (SELECT *
      FROM ss
      UNION ALL
      SELECT *
      FROM cs
      UNION ALL
      SELECT *
      FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
