SELECT
  dt.d_year,
  item.i_brand_id brand_id,
  item.i_brand brand,
  sum(ss_ext_sales_price) ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = 11
  AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, ext_price DESC, brand_id
LIMIT 100
