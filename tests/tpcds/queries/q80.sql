WITH ssr AS
(SELECT
    s_store_id AS store_id,
    sum(ss_ext_sales_price) AS sales,
    sum(coalesce(sr_return_amt, 0)) AS returns,
    sum(ss_net_profit - coalesce(sr_net_loss, 0)) AS profit
  FROM store_sales
    LEFT OUTER JOIN store_returns ON
                                    (ss_item_sk = sr_item_sk AND
                                      ss_ticket_number = sr_ticket_number)
    ,
    date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND (cast('2000-08-23' AS DATE) + INTERVAL 30 days)
    AND ss_store_sk = s_store_sk
    AND ss_item_sk = i_item_sk
    AND i_current_price > 50
    AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id),
    csr AS
  (SELECT
    cp_catalog_page_id AS catalog_page_id,
    sum(cs_ext_sales_price) AS sales,
    sum(coalesce(cr_return_amount, 0)) AS returns,
    sum(cs_net_profit - coalesce(cr_net_loss, 0)) AS profit
  FROM catalog_sales
    LEFT OUTER JOIN catalog_returns ON
                                      (cs_item_sk = cr_item_sk AND
                                        cs_order_number = cr_order_number)
    ,
    date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND (cast('2000-08-23' AS DATE) + INTERVAL 30 days)
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk
    AND i_current_price > 50
    AND cs_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id),
    wsr AS
  (SELECT
    web_site_id,
    sum(ws_ext_sales_price) AS sales,
    sum(coalesce(wr_return_amt, 0)) AS returns,
    sum(ws_net_profit - coalesce(wr_net_loss, 0)) AS profit
  FROM web_sales
    LEFT OUTER JOIN web_returns ON
                                  (ws_item_sk = wr_item_sk AND ws_order_number = wr_order_number)
    ,
    date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND (cast('2000-08-23' AS DATE) + INTERVAL 30 days)
    AND ws_web_site_sk = web_site_sk
    AND ws_item_sk = i_item_sk
    AND i_current_price > 50
    AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_site_id)
SELECT
  channel,
  id,
  sum(sales) AS sales,
  sum(returns) AS returns,
  sum(profit) AS profit
FROM (SELECT
        'store channel' AS channel,
        concat('store', store_id) AS id,
        sales,
        returns,
        profit
      FROM ssr
      UNION ALL
      SELECT
        'catalog channel' AS channel,
        concat('catalog_page', catalog_page_id) AS id,
        sales,
        returns,
        profit
      FROM csr
      UNION ALL
      SELECT
        'web channel' AS channel,
        concat('web_site', web_site_id) AS id,
        sales,
        returns,
        profit
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
