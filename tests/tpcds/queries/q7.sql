SELECT
  i_item_id,
  avg(ss_quantity) agg1,
  avg(ss_list_price) agg2,
  avg(ss_coupon_amt) agg3,
  avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk AND
  ss_item_sk = i_item_sk AND
  ss_cdemo_sk = cd_demo_sk AND
  ss_promo_sk = p_promo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  (p_channel_email = 'N' OR p_channel_event = 'N') AND
  d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
