SELECT
  count(DISTINCT ws_order_number) AS `order count `,
  sum(ws_ext_ship_cost) AS `total shipping cost `,
  sum(ws_net_profit) AS `total net profit `
FROM
  web_sales ws1, date_dim, customer_address, web_site
WHERE
  d_date BETWEEN '1999-02-01' AND
  (CAST('1999-02-01' AS DATE) + INTERVAL 60 days)
    AND ws1.ws_ship_date_sk = d_date_sk
    AND ws1.ws_ship_addr_sk = ca_address_sk
    AND ca_state = 'IL'
    AND ws1.ws_web_site_sk = web_site_sk
    AND web_company_name = 'pri'
    AND EXISTS(SELECT *
               FROM web_sales ws2
               WHERE ws1.ws_order_number = ws2.ws_order_number
                 AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
    AND NOT EXISTS(SELECT *
                   FROM web_returns wr1
                   WHERE ws1.ws_order_number = wr1.wr_order_number)
ORDER BY count(DISTINCT ws_order_number)
LIMIT 100
