SELECT
  i_item_desc,
  i_category,
  i_class,
  i_current_price,
  sum(cs_ext_sales_price) AS itemrevenue,
  sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
  OVER
  (PARTITION BY i_class) AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN cast('1999-02-22' AS DATE)
AND (cast('1999-02-22' AS DATE) + INTERVAL 30 days)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
