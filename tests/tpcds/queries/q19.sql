SELECT
  i_brand_id brand_id,
  i_brand brand,
  i_manufact_id,
  i_manufact,
  sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 8
  AND d_moy = 11
  AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, brand, brand_id, i_manufact_id, i_manufact
LIMIT 100
