SELECT count(*)
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = time_dim.t_time_sk
  AND ss_hdemo_sk = household_demographics.hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND time_dim.t_hour = 20
  AND time_dim.t_minute >= 30
  AND household_demographics.hd_dep_count = 7
  AND store.s_store_name = 'ese'
ORDER BY count(*)
LIMIT 100
