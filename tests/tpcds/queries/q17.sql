SELECT
  i_item_id,
  i_item_desc,
  s_state,
  count(ss_quantity) AS store_sales_quantitycount,
  avg(ss_quantity) AS store_sales_quantityave,
  stddev_samp(ss_quantity) AS store_sales_quantitystdev,
  stddev_samp(ss_quantity) / avg(ss_quantity) AS store_sales_quantitycov,
  count(sr_return_quantity) as_store_returns_quantitycount,
  avg(sr_return_quantity) as_store_returns_quantityave,
  stddev_samp(sr_return_quantity) as_store_returns_quantitystdev,
  stddev_samp(sr_return_quantity) / avg(sr_return_quantity) AS store_returns_quantitycov,
  count(cs_quantity) AS catalog_sales_quantitycount,
  avg(cs_quantity) AS catalog_sales_quantityave,
  stddev_samp(cs_quantity) / avg(cs_quantity) AS catalog_sales_quantitystdev,
  stddev_samp(cs_quantity) / avg(cs_quantity) AS catalog_sales_quantitycov
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2, date_dim d3, store, item
WHERE d1.d_quarter_name = '2001Q1'
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3')
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3')
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state
LIMIT 100
