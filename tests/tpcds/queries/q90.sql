SELECT cast(amc AS DECIMAL(15, 4)) / cast(pmc AS DECIMAL(15, 4)) am_pm_ratio
FROM (SELECT count(*) amc
FROM web_sales, household_demographics, time_dim, web_page
WHERE ws_sold_time_sk = time_dim.t_time_sk
  AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
  AND ws_web_page_sk = web_page.wp_web_page_sk
  AND time_dim.t_hour BETWEEN 8 AND 8 + 1
  AND household_demographics.hd_dep_count = 6
  AND web_page.wp_char_count BETWEEN 5000 AND 5200) at,
  (SELECT count(*) pmc
  FROM web_sales, household_demographics, time_dim, web_page
  WHERE ws_sold_time_sk = time_dim.t_time_sk
    AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
    AND ws_web_page_sk = web_page.wp_web_page_sk
    AND time_dim.t_hour BETWEEN 19 AND 19 + 1
    AND household_demographics.hd_dep_count = 6
    AND web_page.wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
