WITH cs_ui AS
(SELECT
    cs_item_sk,
    sum(cs_ext_list_price) AS sale,
    sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) AS refund
  FROM catalog_sales
    , catalog_returns
  WHERE cs_item_sk = cr_item_sk
    AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) > 2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)),
    cross_sales AS
  (SELECT
    i_product_name product_name,
    i_item_sk item_sk,
    s_store_name store_name,
    s_zip store_zip,
    ad1.ca_street_number b_street_number,
    ad1.ca_street_name b_streen_name,
    ad1.ca_city b_city,
    ad1.ca_zip b_zip,
    ad2.ca_street_number c_street_number,
    ad2.ca_street_name c_street_name,
    ad2.ca_city c_city,
    ad2.ca_zip c_zip,
    d1.d_year AS syear,
    d2.d_year AS fsyear,
    d3.d_year s2year,
    count(*) cnt,
    sum(ss_wholesale_cost) s1,
    sum(ss_list_price) s2,
    sum(ss_coupon_amt) s3
  FROM store_sales, store_returns, cs_ui, date_dim d1, date_dim d2, date_dim d3,
    store, customer, customer_demographics cd1, customer_demographics cd2,
    promotion, household_demographics hd1, household_demographics hd2,
    customer_address ad1, customer_address ad2, income_band ib1, income_band ib2, item
  WHERE ss_store_sk = s_store_sk AND
    ss_sold_date_sk = d1.d_date_sk AND
    ss_customer_sk = c_customer_sk AND
    ss_cdemo_sk = cd1.cd_demo_sk AND
    ss_hdemo_sk = hd1.hd_demo_sk AND
    ss_addr_sk = ad1.ca_address_sk AND
    ss_item_sk = i_item_sk AND
    ss_item_sk = sr_item_sk AND
    ss_ticket_number = sr_ticket_number AND
    ss_item_sk = cs_ui.cs_item_sk AND
    c_current_cdemo_sk = cd2.cd_demo_sk AND
    c_current_hdemo_sk = hd2.hd_demo_sk AND
    c_current_addr_sk = ad2.ca_address_sk AND
    c_first_sales_date_sk = d2.d_date_sk AND
    c_first_shipto_date_sk = d3.d_date_sk AND
    ss_promo_sk = p_promo_sk AND
    hd1.hd_income_band_sk = ib1.ib_income_band_sk AND
    hd2.hd_income_band_sk = ib2.ib_income_band_sk AND
    cd1.cd_marital_status <> cd2.cd_marital_status AND
    i_color IN ('purple', 'burlywood', 'indian', 'spring', 'floral', 'medium') AND
    i_current_price BETWEEN 64 AND 64 + 10 AND
    i_current_price BETWEEN 64 + 1 AND 64 + 15
  GROUP BY i_product_name, i_item_sk, s_store_name, s_zip, ad1.ca_street_number,
    ad1.ca_street_name, ad1.ca_city, ad1.ca_zip, ad2.ca_street_number,
    ad2.ca_street_name, ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year
  )
SELECT
  cs1.product_name,
  cs1.store_name,
  cs1.store_zip,
  cs1.b_street_number,
  cs1.b_streen_name,
  cs1.b_city,
  cs1.b_zip,
  cs1.c_street_number,
  cs1.c_street_name,
  cs1.c_city,
  cs1.c_zip,
  cs1.syear,
  cs1.cnt,
  cs1.s1,
  cs1.s2,
  cs1.s3,
  cs2.s1,
  cs2.s2,
  cs2.s3,
  cs2.syear,
  cs2.cnt
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk AND
  cs1.syear = 1999 AND
  cs2.syear = 1999 + 1 AND
  cs2.cnt <= cs1.cnt AND
  cs1.store_name = cs2.store_name AND
  cs1.store_zip = cs2.store_zip
ORDER BY cs1.product_name, cs1.store_name, cs2.cnt
