SELECT *
FROM (
       SELECT
         i_category,
         i_class,
         i_brand,
         s_store_name,
         s_company_name,
         d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price))
         OVER
         (PARTITION BY i_category, i_brand, s_store_name, s_company_name)
         avg_monthly_sales
       FROM item, store_sales, date_dim, store
       WHERE ss_item_sk = i_item_sk AND
         ss_sold_date_sk = d_date_sk AND
         ss_store_sk = s_store_sk AND
         d_year IN (1999) AND
         ((i_category IN ('Books', 'Electronics', 'Sports') AND
           i_class IN ('computers', 'stereo', 'football'))
           OR (i_category IN ('Men', 'Jewelry', 'Women') AND
           i_class IN ('shirts', 'birdal', 'dresses')))
       GROUP BY i_category, i_class, i_brand,
         s_store_name, s_company_name, d_moy) tmp1
WHERE CASE WHEN (avg_monthly_sales <> 0)
  THEN (abs(sum_sales - avg_monthly_sales) / avg_monthly_sales)
      ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name
LIMIT 100
