SELECT
  c_last_name,
  c_first_name,
  ca_city,
  bought_city,
  ss_ticket_number,
  amt,
  profit
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    ca_city bought_city,
    sum(ss_coupon_amt) amt,
    sum(ss_net_profit) profit
  FROM store_sales, date_dim, store, household_demographics, customer_address
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND store_sales.ss_addr_sk = customer_address.ca_address_sk
    AND (household_demographics.hd_dep_count = 4 OR
    household_demographics.hd_vehicle_count = 3)
    AND date_dim.d_dow IN (6, 0)
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_city IN ('Fairview', 'Midway', 'Fairview', 'Fairview', 'Fairview')
  GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn, customer,
  customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
LIMIT 100
