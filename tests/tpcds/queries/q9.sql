SELECT
  CASE WHEN (SELECT count(*)
  FROM store_sales
  WHERE ss_quantity BETWEEN 1 AND 20) > 62316685
    THEN (SELECT avg(ss_ext_discount_amt)
    FROM store_sales
    WHERE ss_quantity BETWEEN 1 AND 20)
  ELSE (SELECT avg(ss_net_paid)
  FROM store_sales
  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
  CASE WHEN (SELECT count(*)
  FROM store_sales
  WHERE ss_quantity BETWEEN 21 AND 40) > 19045798
    THEN (SELECT avg(ss_ext_discount_amt)
    FROM store_sales
    WHERE ss_quantity BETWEEN 21 AND 40)
  ELSE (SELECT avg(ss_net_paid)
  FROM store_sales
  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
  CASE WHEN (SELECT count(*)
  FROM store_sales
  WHERE ss_quantity BETWEEN 41 AND 60) > 365541424
    THEN (SELECT avg(ss_ext_discount_amt)
    FROM store_sales
    WHERE ss_quantity BETWEEN 41 AND 60)
  ELSE (SELECT avg(ss_net_paid)
  FROM store_sales
  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3,
  CASE WHEN (SELECT count(*)
  FROM store_sales
  WHERE ss_quantity BETWEEN 61 AND 80) > 216357808
    THEN (SELECT avg(ss_ext_discount_amt)
    FROM store_sales
    WHERE ss_quantity BETWEEN 61 AND 80)
  ELSE (SELECT avg(ss_net_paid)
  FROM store_sales
  WHERE ss_quantity BETWEEN 61 AND 80) END bucket4,
  CASE WHEN (SELECT count(*)
  FROM store_sales
  WHERE ss_quantity BETWEEN 81 AND 100) > 184483884
    THEN (SELECT avg(ss_ext_discount_amt)
    FROM store_sales
    WHERE ss_quantity BETWEEN 81 AND 100)
  ELSE (SELECT avg(ss_net_paid)
  FROM store_sales
  WHERE ss_quantity BETWEEN 81 AND 100) END bucket5
FROM reason
WHERE r_reason_sk = 1
