SELECT
  cd_gender,
  cd_marital_status,
  cd_education_status,
  count(*) cnt1,
  cd_purchase_estimate,
  count(*) cnt2,
  cd_credit_rating,
  count(*) cnt3,
  cd_dep_count,
  count(*) cnt4,
  cd_dep_employed_count,
  count(*) cnt5,
  cd_dep_college_count,
  count(*) cnt6
FROM
  customer c, customer_address ca, customer_demographics
WHERE
  c.c_current_addr_sk = ca.ca_address_sk AND
    ca_county IN ('Rush County', 'Toole County', 'Jefferson County',
                  'Dona Ana County', 'La Porte County') AND
    cd_demo_sk = c.c_current_cdemo_sk AND
    exists(SELECT *
           FROM store_sales, date_dim
           WHERE c.c_customer_sk = ss_customer_sk AND
             ss_sold_date_sk = d_date_sk AND
             d_year = 2002 AND
             d_moy BETWEEN 1 AND 1 + 3) AND
    (exists(SELECT *
            FROM web_sales, date_dim
            WHERE c.c_customer_sk = ws_bill_customer_sk AND
              ws_sold_date_sk = d_date_sk AND
              d_year = 2002 AND
              d_moy BETWEEN 1 AND 1 + 3) OR
      exists(SELECT *
             FROM catalog_sales, date_dim
             WHERE c.c_customer_sk = cs_ship_customer_sk AND
               cs_sold_date_sk = d_date_sk AND
               d_year = 2002 AND
               d_moy BETWEEN 1 AND 1 + 3))
GROUP BY cd_gender,
  cd_marital_status,
  cd_education_status,
  cd_purchase_estimate,
  cd_credit_rating,
  cd_dep_count,
  cd_dep_employed_count,
  cd_dep_college_count
ORDER BY cd_gender,
  cd_marital_status,
  cd_education_status,
  cd_purchase_estimate,
  cd_credit_rating,
  cd_dep_count,
  cd_dep_employed_count,
  cd_dep_college_count
LIMIT 100
