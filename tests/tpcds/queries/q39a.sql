WITH inv AS
(SELECT
    w_warehouse_name,
    w_warehouse_sk,
    i_item_sk,
    d_moy,
    stdev,
    mean,
    CASE mean
    WHEN 0
      THEN NULL
    ELSE stdev / mean END cov
  FROM (SELECT
    w_warehouse_name,
    w_warehouse_sk,
    i_item_sk,
    d_moy,
    stddev_samp(inv_quantity_on_hand) stdev,
    avg(inv_quantity_on_hand) mean
  FROM inventory, item, warehouse, date_dim
  WHERE inv_item_sk = i_item_sk
    AND inv_warehouse_sk = w_warehouse_sk
    AND inv_date_sk = d_date_sk
    AND d_year = 2001
  GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  WHERE CASE mean
        WHEN 0
          THEN 0
        ELSE stdev / mean END > 1)
SELECT
  inv1.w_warehouse_sk,
  inv1.i_item_sk,
  inv1.d_moy,
  inv1.mean,
  inv1.cov,
  inv2.w_warehouse_sk,
  inv2.i_item_sk,
  inv2.d_moy,
  inv2.mean,
  inv2.cov
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = 1
  AND inv2.d_moy = 1 + 1
ORDER BY inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov
  , inv2.d_moy, inv2.mean, inv2.cov
