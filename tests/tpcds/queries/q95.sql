WITH ws_wh AS
(SELECT
    ws1.ws_order_number,
    ws1.ws_warehouse_sk wh1,
    ws2.ws_warehouse_sk wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT
  count(DISTINCT ws_order_number) AS `order count `,
  sum(ws_ext_ship_cost) AS `total shipping cost `,
  sum(ws_net_profit) AS `total net profit `
FROM
  web_sales ws1, date_dim, customer_address, web_site
WHERE
  d_date BETWEEN '1999-02-01' AND
  (CAST('1999-02-01' AS DATE) + INTERVAL 60 DAY)
    AND ws1.ws_ship_date_sk = d_date_sk
    AND ws1.ws_ship_addr_sk = ca_address_sk
    AND ca_state = 'IL'
    AND ws1.ws_web_site_sk = web_site_sk
    AND web_company_name = 'pri'
    AND ws1.ws_order_number IN (SELECT ws_order_number
  FROM ws_wh)
    AND ws1.ws_order_number IN (SELECT wr_order_number
  FROM web_returns, ws_wh
  WHERE wr_order_number = ws_wh.ws_order_number)
ORDER BY count(DISTINCT ws_order_number)
LIMIT 100
