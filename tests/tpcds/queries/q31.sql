WITH ss AS
(SELECT
    ca_county,
    d_qoy,
    d_year,
    sum(ss_ext_sales_price) AS store_sales
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
    ws AS
  (SELECT
    ca_county,
    d_qoy,
    d_year,
    sum(ws_ext_sales_price) AS web_sales
  FROM web_sales, date_dim, customer_address
  WHERE ws_sold_date_sk = d_date_sk
    AND ws_bill_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT
  ss1.ca_county,
  ss1.d_year,
  ws2.web_sales / ws1.web_sales web_q1_q2_increase,
  ss2.store_sales / ss1.store_sales store_q1_q2_increase,
  ws3.web_sales / ws2.web_sales web_q2_q3_increase,
  ss3.store_sales / ss2.store_sales store_q2_q3_increase
FROM
  ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
WHERE
  ss1.d_qoy = 1
    AND ss1.d_year = 2000
    AND ss1.ca_county = ss2.ca_county
    AND ss2.d_qoy = 2
    AND ss2.d_year = 2000
    AND ss2.ca_county = ss3.ca_county
    AND ss3.d_qoy = 3
    AND ss3.d_year = 2000
    AND ss1.ca_county = ws1.ca_county
    AND ws1.d_qoy = 1
    AND ws1.d_year = 2000
    AND ws1.ca_county = ws2.ca_county
    AND ws2.d_qoy = 2
    AND ws2.d_year = 2000
    AND ws1.ca_county = ws3.ca_county
    AND ws3.d_qoy = 3
    AND ws3.d_year = 2000
    AND CASE WHEN ws1.web_sales > 0
    THEN ws2.web_sales / ws1.web_sales
        ELSE NULL END
    > CASE WHEN ss1.store_sales > 0
    THEN ss2.store_sales / ss1.store_sales
      ELSE NULL END
    AND CASE WHEN ws2.web_sales > 0
    THEN ws3.web_sales / ws2.web_sales
        ELSE NULL END
    > CASE WHEN ss2.store_sales > 0
    THEN ss3.store_sales / ss2.store_sales
      ELSE NULL END
ORDER BY ss1.ca_county
