WITH sr_items AS
(SELECT
    i_item_id item_id,
    sum(sr_return_quantity) sr_item_qty
  FROM store_returns, item, date_dim
  WHERE sr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
  FROM date_dim
  WHERE d_week_seq IN
    (SELECT d_week_seq
    FROM date_dim
    WHERE d_date IN ('2000-06-30', '2000-09-27', '2000-11-17')))
    AND sr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
    cr_items AS
  (SELECT
    i_item_id item_id,
    sum(cr_return_quantity) cr_item_qty
  FROM catalog_returns, item, date_dim
  WHERE cr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date
  FROM date_dim
  WHERE d_week_seq IN
    (SELECT d_week_seq
    FROM date_dim
    WHERE d_date IN ('2000-06-30', '2000-09-27', '2000-11-17')))
    AND cr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
    wr_items AS
  (SELECT
    i_item_id item_id,
    sum(wr_return_quantity) wr_item_qty
  FROM web_returns, item, date_dim
  WHERE wr_item_sk = i_item_sk AND d_date IN
    (SELECT d_date
    FROM date_dim
    WHERE d_week_seq IN
      (SELECT d_week_seq
      FROM date_dim
      WHERE d_date IN ('2000-06-30', '2000-09-27', '2000-11-17')))
    AND wr_returned_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT
  sr_items.item_id,
  sr_item_qty,
  sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 sr_dev,
  cr_item_qty,
  cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 cr_dev,
  wr_item_qty,
  wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 wr_dev,
  (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id, sr_item_qty
LIMIT 100
