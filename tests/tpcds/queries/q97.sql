WITH ssci AS (
  SELECT
    ss_customer_sk customer_sk,
    ss_item_sk item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1200 + 11
  GROUP BY ss_customer_sk, ss_item_sk),
    csci AS (
    SELECT
      cs_bill_customer_sk customer_sk,
      cs_item_sk item_sk
    FROM catalog_sales, date_dim
    WHERE cs_sold_date_sk = d_date_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
    GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT
  sum(CASE WHEN ssci.customer_sk IS NOT NULL AND csci.customer_sk IS NULL
    THEN 1
      ELSE 0 END) store_only,
  sum(CASE WHEN ssci.customer_sk IS NULL AND csci.customer_sk IS NOT NULL
    THEN 1
      ELSE 0 END) catalog_only,
  sum(CASE WHEN ssci.customer_sk IS NOT NULL AND csci.customer_sk IS NOT NULL
    THEN 1
      ELSE 0 END) store_and_catalog
FROM ssci
  FULL OUTER JOIN csci ON (ssci.customer_sk = csci.customer_sk
    AND ssci.item_sk = csci.item_sk)
LIMIT 100
