SELECT
  promotions,
  total,
  cast(promotions AS DECIMAL(15, 4)) / cast(total AS DECIMAL(15, 4)) * 100
FROM
  (SELECT sum(ss_ext_sales_price) promotions
  FROM store_sales, store, promotion, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_promo_sk = p_promo_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5
    AND i_category = 'Jewelry'
    AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y' OR p_channel_tv = 'Y')
    AND s_gmt_offset = -5
    AND d_year = 1998
    AND d_moy = 11) promotional_sales,
  (SELECT sum(ss_ext_sales_price) total
  FROM store_sales, store, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5
    AND i_category = 'Jewelry'
    AND s_gmt_offset = -5
    AND d_year = 1998
    AND d_moy = 11) all_sales
ORDER BY promotions, total
LIMIT 100
