WITH ssales AS
(SELECT
    c_last_name,
    c_first_name,
    s_store_name,
    ca_state,
    s_state,
    i_color,
    i_current_price,
    i_manager_id,
    i_units,
    i_size,
    sum(ss_net_paid) netpaid
  FROM store_sales, store_returns, store, item, customer, customer_address
  WHERE ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk
    AND c_birth_country = upper(ca_country)
    AND s_zip = ca_zip
    AND s_market_id = 8
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state, i_color,
    i_current_price, i_manager_id, i_units, i_size)
SELECT
  c_last_name,
  c_first_name,
  s_store_name,
  sum(netpaid) paid
FROM ssales
WHERE i_color = 'pale'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING sum(netpaid) > (SELECT 0.05 * avg(netpaid)
FROM ssales)
