SELECT
  avg(ss_quantity),
  avg(ss_ext_sales_price),
  avg(ss_ext_wholesale_cost),
  sum(ss_ext_wholesale_cost)
FROM store_sales
  , store
  , customer_demographics
  , household_demographics
  , customer_address
  , date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk
  AND cd_demo_sk = ss_cdemo_sk
  AND cd_marital_status = 'M'
  AND cd_education_status = 'Advanced Degree'
  AND ss_sales_price BETWEEN 100.00 AND 150.00
  AND hd_dep_count = 3
) OR
  (ss_hdemo_sk = hd_demo_sk
    AND cd_demo_sk = ss_cdemo_sk
    AND cd_marital_status = 'S'
    AND cd_education_status = 'College'
    AND ss_sales_price BETWEEN 50.00 AND 100.00
    AND hd_dep_count = 1
  ) OR
  (ss_hdemo_sk = hd_demo_sk
    AND cd_demo_sk = ss_cdemo_sk
    AND cd_marital_status = 'W'
    AND cd_education_status = '2 yr Degree'
    AND ss_sales_price BETWEEN 150.00 AND 200.00
    AND hd_dep_count = 1
  ))
  AND ((ss_addr_sk = ca_address_sk
  AND ca_country = 'United States'
  AND ca_state IN ('TX', 'OH', 'TX')
  AND ss_net_profit BETWEEN 100 AND 200
) OR
  (ss_addr_sk = ca_address_sk
    AND ca_country = 'United States'
    AND ca_state IN ('OR', 'NM', 'KY')
    AND ss_net_profit BETWEEN 150 AND 300
  ) OR
  (ss_addr_sk = ca_address_sk
    AND ca_country = 'United States'
    AND ca_state IN ('VA', 'TX', 'MS')
    AND ss_net_profit BETWEEN 50 AND 250
  ))
