SELECT
  cd_gender,
  cd_marital_status,
  cd_education_status,
  count(*) cnt1,
  cd_purchase_estimate,
  count(*) cnt2,
  cd_credit_rating,
  count(*) cnt3
FROM
  customer c, customer_address ca, customer_demographics
WHERE
  c.c_current_addr_sk = ca.ca_address_sk AND
    ca_state IN ('KY', 'GA', 'NM') AND
    cd_demo_sk = c.c_current_cdemo_sk AND
    exists(SELECT *
           FROM store_sales, date_dim
           WHERE c.c_customer_sk = ss_customer_sk AND
             ss_sold_date_sk = d_date_sk AND
             d_year = 2001 AND
             d_moy BETWEEN 4 AND 4 + 2) AND
    (NOT exists(SELECT *
                FROM web_sales, date_dim
                WHERE c.c_customer_sk = ws_bill_customer_sk AND
                  ws_sold_date_sk = d_date_sk AND
                  d_year = 2001 AND
                  d_moy BETWEEN 4 AND 4 + 2) AND
      NOT exists(SELECT *
                 FROM catalog_sales, date_dim
                 WHERE c.c_customer_sk = cs_ship_customer_sk AND
                   cs_sold_date_sk = d_date_sk AND
                   d_year = 2001 AND
                   d_moy BETWEEN 4 AND 4 + 2))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
  cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
  cd_purchase_estimate, cd_credit_rating
LIMIT 100
