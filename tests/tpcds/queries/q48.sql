SELECT sum(ss_quantity)
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND
  (
    (
      cd_demo_sk = ss_cdemo_sk
        AND
        cd_marital_status = 'M'
        AND
        cd_education_status = '4 yr Degree'
        AND
        ss_sales_price BETWEEN 100.00 AND 150.00
    )
      OR
      (
        cd_demo_sk = ss_cdemo_sk
          AND
          cd_marital_status = 'D'
          AND
          cd_education_status = '2 yr Degree'
          AND
          ss_sales_price BETWEEN 50.00 AND 100.00
      )
      OR
      (
        cd_demo_sk = ss_cdemo_sk
          AND
          cd_marital_status = 'S'
          AND
          cd_education_status = 'College'
          AND
          ss_sales_price BETWEEN 150.00 AND 200.00
      )
  )
  AND
  (
    (
      ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('CO', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000
    )
      OR
      (ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('OR', 'MN', 'KY')
        AND ss_net_profit BETWEEN 150 AND 3000
      )
      OR
      (ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('VA', 'CA', 'MS')
        AND ss_net_profit BETWEEN 50 AND 25000
      )
  )
