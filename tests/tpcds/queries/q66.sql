SELECT
  w_warehouse_name,
  w_warehouse_sq_ft,
  w_city,
  w_county,
  w_state,
  w_country,
  ship_carriers,
  year,
  sum(jan_sales) AS jan_sales,
  sum(feb_sales) AS feb_sales,
  sum(mar_sales) AS mar_sales,
  sum(apr_sales) AS apr_sales,
  sum(may_sales) AS may_sales,
  sum(jun_sales) AS jun_sales,
  sum(jul_sales) AS jul_sales,
  sum(aug_sales) AS aug_sales,
  sum(sep_sales) AS sep_sales,
  sum(oct_sales) AS oct_sales,
  sum(nov_sales) AS nov_sales,
  sum(dec_sales) AS dec_sales,
  sum(jan_sales / w_warehouse_sq_ft) AS jan_sales_per_sq_foot,
  sum(feb_sales / w_warehouse_sq_ft) AS feb_sales_per_sq_foot,
  sum(mar_sales / w_warehouse_sq_ft) AS mar_sales_per_sq_foot,
  sum(apr_sales / w_warehouse_sq_ft) AS apr_sales_per_sq_foot,
  sum(may_sales / w_warehouse_sq_ft) AS may_sales_per_sq_foot,
  sum(jun_sales / w_warehouse_sq_ft) AS jun_sales_per_sq_foot,
  sum(jul_sales / w_warehouse_sq_ft) AS jul_sales_per_sq_foot,
  sum(aug_sales / w_warehouse_sq_ft) AS aug_sales_per_sq_foot,
  sum(sep_sales / w_warehouse_sq_ft) AS sep_sales_per_sq_foot,
  sum(oct_sales / w_warehouse_sq_ft) AS oct_sales_per_sq_foot,
  sum(nov_sales / w_warehouse_sq_ft) AS nov_sales_per_sq_foot,
  sum(dec_sales / w_warehouse_sq_ft) AS dec_sales_per_sq_foot,
  sum(jan_net) AS jan_net,
  sum(feb_net) AS feb_net,
  sum(mar_net) AS mar_net,
  sum(apr_net) AS apr_net,
  sum(may_net) AS may_net,
  sum(jun_net) AS jun_net,
  sum(jul_net) AS jul_net,
  sum(aug_net) AS aug_net,
  sum(sep_net) AS sep_net,
  sum(oct_net) AS oct_net,
  sum(nov_net) AS nov_net,
  sum(dec_net) AS dec_net
FROM (
       (SELECT
         w_warehouse_name,
         w_warehouse_sq_ft,
         w_city,
         w_county,
         w_state,
         w_country,
         concat('DHL', ',', 'BARIAN') AS ship_carriers,
         d_year AS year,
         sum(CASE WHEN d_moy = 1
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS jan_sales,
         sum(CASE WHEN d_moy = 2
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS feb_sales,
         sum(CASE WHEN d_moy = 3
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS mar_sales,
         sum(CASE WHEN d_moy = 4
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS apr_sales,
         sum(CASE WHEN d_moy = 5
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS may_sales,
         sum(CASE WHEN d_moy = 6
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS jun_sales,
         sum(CASE WHEN d_moy = 7
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS jul_sales,
         sum(CASE WHEN d_moy = 8
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS aug_sales,
         sum(CASE WHEN d_moy = 9
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS sep_sales,
         sum(CASE WHEN d_moy = 10
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS oct_sales,
         sum(CASE WHEN d_moy = 11
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS nov_sales,
         sum(CASE WHEN d_moy = 12
           THEN ws_ext_sales_price * ws_quantity
             ELSE 0 END) AS dec_sales,
         sum(CASE WHEN d_moy = 1
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS jan_net,
         sum(CASE WHEN d_moy = 2
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS feb_net,
         sum(CASE WHEN d_moy = 3
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS mar_net,
         sum(CASE WHEN d_moy = 4
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS apr_net,
         sum(CASE WHEN d_moy = 5
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS may_net,
         sum(CASE WHEN d_moy = 6
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS jun_net,
         sum(CASE WHEN d_moy = 7
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS jul_net,
         sum(CASE WHEN d_moy = 8
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS aug_net,
         sum(CASE WHEN d_moy = 9
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS sep_net,
         sum(CASE WHEN d_moy = 10
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS oct_net,
         sum(CASE WHEN d_moy = 11
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS nov_net,
         sum(CASE WHEN d_moy = 12
           THEN ws_net_paid * ws_quantity
             ELSE 0 END) AS dec_net
       FROM
         web_sales, warehouse, date_dim, time_dim, ship_mode
       WHERE
         ws_warehouse_sk = w_warehouse_sk
           AND ws_sold_date_sk = d_date_sk
           AND ws_sold_time_sk = t_time_sk
           AND ws_ship_mode_sk = sm_ship_mode_sk
           AND d_year = 2001
           AND t_time BETWEEN 30838 AND 30838 + 28800
           AND sm_carrier IN ('DHL', 'BARIAN')
       GROUP BY
         w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country, d_year)
       UNION ALL
       (SELECT
         w_warehouse_name,
         w_warehouse_sq_ft,
         w_city,
         w_county,
         w_state,
         w_country,
         concat('DHL', ',', 'BARIAN') AS ship_carriers,
         d_year AS year,
         sum(CASE WHEN d_moy = 1
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS jan_sales,
         sum(CASE WHEN d_moy = 2
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS feb_sales,
         sum(CASE WHEN d_moy = 3
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS mar_sales,
         sum(CASE WHEN d_moy = 4
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS apr_sales,
         sum(CASE WHEN d_moy = 5
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS may_sales,
         sum(CASE WHEN d_moy = 6
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS jun_sales,
         sum(CASE WHEN d_moy = 7
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS jul_sales,
         sum(CASE WHEN d_moy = 8
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS aug_sales,
         sum(CASE WHEN d_moy = 9
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS sep_sales,
         sum(CASE WHEN d_moy = 10
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS oct_sales,
         sum(CASE WHEN d_moy = 11
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS nov_sales,
         sum(CASE WHEN d_moy = 12
           THEN cs_sales_price * cs_quantity
             ELSE 0 END) AS dec_sales,
         sum(CASE WHEN d_moy = 1
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS jan_net,
         sum(CASE WHEN d_moy = 2
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS feb_net,
         sum(CASE WHEN d_moy = 3
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS mar_net,
         sum(CASE WHEN d_moy = 4
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS apr_net,
         sum(CASE WHEN d_moy = 5
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS may_net,
         sum(CASE WHEN d_moy = 6
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS jun_net,
         sum(CASE WHEN d_moy = 7
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS jul_net,
         sum(CASE WHEN d_moy = 8
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS aug_net,
         sum(CASE WHEN d_moy = 9
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS sep_net,
         sum(CASE WHEN d_moy = 10
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS oct_net,
         sum(CASE WHEN d_moy = 11
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS nov_net,
         sum(CASE WHEN d_moy = 12
           THEN cs_net_paid_inc_tax * cs_quantity
             ELSE 0 END) AS dec_net
       FROM
         catalog_sales, warehouse, date_dim, time_dim, ship_mode
       WHERE
         cs_warehouse_sk = w_warehouse_sk
           AND cs_sold_date_sk = d_date_sk
           AND cs_sold_time_sk = t_time_sk
           AND cs_ship_mode_sk = sm_ship_mode_sk
           AND d_year = 2001
           AND t_time BETWEEN 30838 AND 30838 + 28800
           AND sm_carrier IN ('DHL', 'BARIAN')
       GROUP BY
         w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country, d_year
       )
     ) x
GROUP BY
  w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country,
  ship_carriers, year
ORDER BY w_warehouse_name
LIMIT 100
