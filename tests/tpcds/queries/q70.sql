SELECT
  sum(ss_net_profit) AS total_sum,
  s_state,
  s_county,
  grouping(s_state) + grouping(s_county) AS lochierarchy,
  rank()
  OVER (
    PARTITION BY grouping(s_state) + grouping(s_county),
      CASE WHEN grouping(s_county) = 0
        THEN s_state END
    ORDER BY sum(ss_net_profit) DESC) AS rank_within_parent
FROM
  store_sales, date_dim d1, store
WHERE
  d1.d_month_seq BETWEEN 1200 AND 1200 + 11
    AND d1.d_date_sk = ss_sold_date_sk
    AND s_store_sk = ss_store_sk
    AND s_state IN
    (SELECT s_state
    FROM
      (SELECT
        s_state AS s_state,
        rank()
        OVER (PARTITION BY s_state
          ORDER BY sum(ss_net_profit) DESC) AS ranking
      FROM store_sales, store, date_dim
      WHERE d_month_seq BETWEEN 1200 AND 1200 + 11
        AND d_date_sk = ss_sold_date_sk
        AND s_store_sk = ss_store_sk
      GROUP BY s_state) tmp1
    WHERE ranking <= 5)
GROUP BY ROLLUP (s_state, s_county)
ORDER BY
  lochierarchy DESC
  , CASE WHEN lochierarchy = 0
  THEN s_state END
  , rank_within_parent
LIMIT 100
