SELECT
  dt.d_year,
  item.i_category_id,
  item.i_category,
  sum(ss_ext_sales_price)
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = 11
  AND dt.d_year = 2000
GROUP BY dt.d_year
  , item.i_category_id
  , item.i_category
ORDER BY sum(ss_ext_sales_price) DESC, dt.d_year
  , item.i_category_id
  , item.i_category
LIMIT 100
