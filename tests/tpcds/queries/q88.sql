SELECT *
FROM
  (SELECT count(*) h8_30_to_9
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 8
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s1,
  (SELECT count(*) h9_to_9_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 9
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s2,
  (SELECT count(*) h9_30_to_10
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 9
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s3,
  (SELECT count(*) h10_to_10_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 10
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s4,
  (SELECT count(*) h10_30_to_11
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 10
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s5,
  (SELECT count(*) h11_to_11_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 11
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s6,
  (SELECT count(*) h11_30_to_12
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 11
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s7,
  (SELECT count(*) h12_to_12_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 12
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s8
