SELECT
  channel,
  col_name,
  d_year,
  d_qoy,
  i_category,
  COUNT(*) sales_cnt,
  SUM(ext_sales_price) sales_amt
FROM (
       SELECT
         'store' AS channel,
         ss_store_sk col_name,
         d_year,
         d_qoy,
         i_category,
         ss_ext_sales_price ext_sales_price
       FROM store_sales, item, date_dim
       WHERE ss_store_sk IS NULL
         AND ss_sold_date_sk = d_date_sk
         AND ss_item_sk = i_item_sk
       UNION ALL
       SELECT
         'web' AS channel,
         ws_ship_customer_sk col_name,
         d_year,
         d_qoy,
         i_category,
         ws_ext_sales_price ext_sales_price
       FROM web_sales, item, date_dim
       WHERE ws_ship_customer_sk IS NULL
         AND ws_sold_date_sk = d_date_sk
         AND ws_item_sk = i_item_sk
       UNION ALL
       SELECT
         'catalog' AS channel,
         cs_ship_addr_sk col_name,
         d_year,
         d_qoy,
         i_category,
         cs_ext_sales_price ext_sales_price
       FROM catalog_sales, item, date_dim
       WHERE cs_ship_addr_sk IS NULL
         AND cs_sold_date_sk = d_date_sk
         AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 100
