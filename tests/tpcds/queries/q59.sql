WITH wss AS
(SELECT
    d_week_seq,
    ss_store_sk,
    sum(CASE WHEN (d_day_name = 'Sunday')
      THEN ss_sales_price
        ELSE NULL END) sun_sales,
    sum(CASE WHEN (d_day_name = 'Monday')
      THEN ss_sales_price
        ELSE NULL END) mon_sales,
    sum(CASE WHEN (d_day_name = 'Tuesday')
      THEN ss_sales_price
        ELSE NULL END) tue_sales,
    sum(CASE WHEN (d_day_name = 'Wednesday')
      THEN ss_sales_price
        ELSE NULL END) wed_sales,
    sum(CASE WHEN (d_day_name = 'Thursday')
      THEN ss_sales_price
        ELSE NULL END) thu_sales,
    sum(CASE WHEN (d_day_name = 'Friday')
      THEN ss_sales_price
        ELSE NULL END) fri_sales,
    sum(CASE WHEN (d_day_name = 'Saturday')
      THEN ss_sales_price
        ELSE NULL END) sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk
)
SELECT
  s_store_name1,
  s_store_id1,
  d_week_seq1,
  sun_sales1 / sun_sales2,
  mon_sales1 / mon_sales2,
  tue_sales1 / tue_sales2,
  wed_sales1 / wed_sales2,
  thu_sales1 / thu_sales2,
  fri_sales1 / fri_sales2,
  sat_sales1 / sat_sales2
FROM
  (SELECT
    s_store_name s_store_name1,
    wss.d_week_seq d_week_seq1,
    s_store_id s_store_id1,
    sun_sales sun_sales1,
    mon_sales mon_sales1,
    tue_sales tue_sales1,
    wed_sales wed_sales1,
    thu_sales thu_sales1,
    fri_sales fri_sales1,
    sat_sales sat_sales1
  FROM wss, store, date_dim d
  WHERE d.d_week_seq = wss.d_week_seq AND
    ss_store_sk = s_store_sk AND
    d_month_seq BETWEEN 1212 AND 1212 + 11) y,
  (SELECT
    s_store_name s_store_name2,
    wss.d_week_seq d_week_seq2,
    s_store_id s_store_id2,
    sun_sales sun_sales2,
    mon_sales mon_sales2,
    tue_sales tue_sales2,
    wed_sales wed_sales2,
    thu_sales thu_sales2,
    fri_sales fri_sales2,
    sat_sales sat_sales2
  FROM wss, store, date_dim d
  WHERE d.d_week_seq = wss.d_week_seq AND
    ss_store_sk = s_store_sk AND
    d_month_seq BETWEEN 1212 + 12 AND 1212 + 23) x
WHERE s_store_id1 = s_store_id2
  AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
