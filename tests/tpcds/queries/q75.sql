WITH all_sales AS (
  SELECT
    d_year,
    i_brand_id,
    i_class_id,
    i_category_id,
    i_manufact_id,
    SUM(sales_cnt) AS sales_cnt,
    SUM(sales_amt) AS sales_amt
  FROM (
         SELECT
           d_year,
           i_brand_id,
           i_class_id,
           i_category_id,
           i_manufact_id,
           cs_quantity - COALESCE(cr_return_quantity, 0) AS sales_cnt,
           cs_ext_sales_price - COALESCE(cr_return_amount, 0.0) AS sales_amt
         FROM catalog_sales
           JOIN item ON i_item_sk = cs_item_sk
           JOIN date_dim ON d_date_sk = cs_sold_date_sk
           LEFT JOIN catalog_returns ON (cs_order_number = cr_order_number
             AND cs_item_sk = cr_item_sk)
         WHERE i_category = 'Books'
         UNION
         SELECT
           d_year,
           i_brand_id,
           i_class_id,
           i_category_id,
           i_manufact_id,
           ss_quantity - COALESCE(sr_return_quantity, 0) AS sales_cnt,
           ss_ext_sales_price - COALESCE(sr_return_amt, 0.0) AS sales_amt
         FROM store_sales
           JOIN item ON i_item_sk = ss_item_sk
           JOIN date_dim ON d_date_sk = ss_sold_date_sk
           LEFT JOIN store_returns ON (ss_ticket_number = sr_ticket_number
             AND ss_item_sk = sr_item_sk)
         WHERE i_category = 'Books'
         UNION
         SELECT
           d_year,
           i_brand_id,
           i_class_id,
           i_category_id,
           i_manufact_id,
           ws_quantity - COALESCE(wr_return_quantity, 0) AS sales_cnt,
           ws_ext_sales_price - COALESCE(wr_return_amt, 0.0) AS sales_amt
         FROM web_sales
           JOIN item ON i_item_sk = ws_item_sk
           JOIN date_dim ON d_date_sk = ws_sold_date_sk
           LEFT JOIN web_returns ON (ws_order_number = wr_order_number
             AND ws_item_sk = wr_item_sk)
         WHERE i_category = 'Books') sales_detail
  GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
SELECT
  prev_yr.d_year AS prev_year,
  curr_yr.d_year AS year,
  curr_yr.i_brand_id,
  curr_yr.i_class_id,
  curr_yr.i_category_id,
  curr_yr.i_manufact_id,
  prev_yr.sales_cnt AS prev_yr_cnt,
  curr_yr.sales_cnt AS curr_yr_cnt,
  curr_yr.sales_cnt - prev_yr.sales_cnt AS sales_cnt_diff,
  curr_yr.sales_amt - prev_yr.sales_amt AS sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2002
  AND prev_yr.d_year = 2002 - 1
  AND CAST(curr_yr.sales_cnt AS DECIMAL(17, 2)) / CAST(prev_yr.sales_cnt AS DECIMAL(17, 2)) < 0.9
ORDER BY sales_cnt_diff
LIMIT 100
