SELECT *
FROM (SELECT
  avg(ss_list_price) B1_LP,
  count(ss_list_price) B1_CNT,
  count(DISTINCT ss_list_price) B1_CNTD
FROM store_sales
WHERE ss_quantity BETWEEN 0 AND 5
  AND (ss_list_price BETWEEN 8 AND 8 + 10
  OR ss_coupon_amt BETWEEN 459 AND 459 + 1000
  OR ss_wholesale_cost BETWEEN 57 AND 57 + 20)) B1,
  (SELECT
    avg(ss_list_price) B2_LP,
    count(ss_list_price) B2_CNT,
    count(DISTINCT ss_list_price) B2_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 6 AND 10
    AND (ss_list_price BETWEEN 90 AND 90 + 10
    OR ss_coupon_amt BETWEEN 2323 AND 2323 + 1000
    OR ss_wholesale_cost BETWEEN 31 AND 31 + 20)) B2,
  (SELECT
    avg(ss_list_price) B3_LP,
    count(ss_list_price) B3_CNT,
    count(DISTINCT ss_list_price) B3_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 11 AND 15
    AND (ss_list_price BETWEEN 142 AND 142 + 10
    OR ss_coupon_amt BETWEEN 12214 AND 12214 + 1000
    OR ss_wholesale_cost BETWEEN 79 AND 79 + 20)) B3,
  (SELECT
    avg(ss_list_price) B4_LP,
    count(ss_list_price) B4_CNT,
    count(DISTINCT ss_list_price) B4_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 16 AND 20
    AND (ss_list_price BETWEEN 135 AND 135 + 10
    OR ss_coupon_amt BETWEEN 6071 AND 6071 + 1000
    OR ss_wholesale_cost BETWEEN 38 AND 38 + 20)) B4,
  (SELECT
    avg(ss_list_price) B5_LP,
    count(ss_list_price) B5_CNT,
    count(DISTINCT ss_list_price) B5_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 21 AND 25
    AND (ss_list_price BETWEEN 122 AND 122 + 10
    OR ss_coupon_amt BETWEEN 836 AND 836 + 1000
    OR ss_wholesale_cost BETWEEN 17 AND 17 + 20)) B5,
  (SELECT
    avg(ss_list_price) B6_LP,
    count(ss_list_price) B6_CNT,
    count(DISTINCT ss_list_price) B6_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 26 AND 30
    AND (ss_list_price BETWEEN 154 AND 154 + 10
    OR ss_coupon_amt BETWEEN 7326 AND 7326 + 1000
    OR ss_wholesale_cost BETWEEN 7 AND 7 + 20)) B6
LIMIT 100
