WITH ss AS
(SELECT
    s_store_sk,
    sum(ss_ext_sales_price) AS sales,
    sum(ss_net_profit) AS profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
    sr AS
  (SELECT
    s_store_sk,
    sum(sr_return_amt) AS returns,
    sum(sr_net_loss) AS profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
    cs AS
  (SELECT
    cs_call_center_sk,
    sum(cs_ext_sales_price) AS sales,
    sum(cs_net_profit) AS profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
  GROUP BY cs_call_center_sk),
    cr AS
  (SELECT
    sum(cr_return_amount) AS returns,
    sum(cr_net_loss) AS profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)),
    ws AS
  (SELECT
    wp_web_page_sk,
    sum(ws_ext_sales_price) AS sales,
    sum(ws_net_profit) AS profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
    wr AS
  (SELECT
    wp_web_page_sk,
    sum(wr_return_amt) AS returns,
    sum(wr_net_loss) AS profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk)
SELECT
  channel,
  id,
  sum(sales) AS sales,
  sum(returns) AS returns,
  sum(profit) AS profit
FROM
  (SELECT
     'store channel' AS channel,
     ss.s_store_sk AS id,
     sales,
     coalesce(returns, 0) AS returns,
     (profit - coalesce(profit_loss, 0)) AS profit
   FROM ss
     LEFT JOIN sr
       ON ss.s_store_sk = sr.s_store_sk
   UNION ALL
   SELECT
     'catalog channel' AS channel,
     cs_call_center_sk AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM cs, cr
   UNION ALL
   SELECT
     'web channel' AS channel,
     ws.wp_web_page_sk AS id,
     sales,
     coalesce(returns, 0) returns,
     (profit - coalesce(profit_loss, 0)) AS profit
   FROM ws
     LEFT JOIN wr
       ON ws.wp_web_page_sk = wr.wp_web_page_sk
  ) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
