WITH v1 AS (
  SELECT
    i_category,
    i_brand,
    s_store_name,
    s_company_name,
    d_year,
    d_moy,
    sum(ss_sales_price) sum_sales,
    avg(sum(ss_sales_price))
    OVER
    (PARTITION BY i_category, i_brand,
      s_store_name, s_company_name, d_year)
    avg_monthly_sales,
    rank()
    OVER
    (PARTITION BY i_category, i_brand,
      s_store_name, s_company_name
      ORDER BY d_year, d_moy) rn
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND
    ss_sold_date_sk = d_date_sk AND
    ss_store_sk = s_store_sk AND
    (
      d_year = 1999 OR
        (d_year = 1999 - 1 AND d_moy = 12) OR
        (d_year = 1999 + 1 AND d_moy = 1)
    )
  GROUP BY i_category, i_brand,
    s_store_name, s_company_name,
    d_year, d_moy),
    v2 AS (
    SELECT
      v1.i_category,
      v1.i_brand,
      v1.s_store_name,
      v1.s_company_name,
      v1.d_year,
      v1.d_moy,
      v1.avg_monthly_sales,
      v1.sum_sales,
      v1_lag.sum_sales psum,
      v1_lead.sum_sales nsum
    FROM v1, v1 v1_lag, v1 v1_lead
    WHERE v1.i_category = v1_lag.i_category AND
      v1.i_category = v1_lead.i_category AND
      v1.i_brand = v1_lag.i_brand AND
      v1.i_brand = v1_lead.i_brand AND
      v1.s_store_name = v1_lag.s_store_name AND
      v1.s_store_name = v1_lead.s_store_name AND
      v1.s_company_name = v1_lag.s_company_name AND
      v1.s_company_name = v1_lead.s_company_name AND
      v1.rn = v1_lag.rn + 1 AND
      v1.rn = v1_lead.rn - 1)
SELECT *
FROM v2
WHERE d_year = 1999 AND
  avg_monthly_sales > 0 AND
  CASE WHEN avg_monthly_sales > 0
    THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
  ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, 3
LIMIT 100
