"""Full TPC-DS data generator (all 24 tables) at miniature scale.

Role of the reference's GenTPCDSData.scala + dsdgen: deterministic star
schema covering every column of the standard TPC-DS schema
(tests/tpcds/schema.json, extracted from the public spec) with value
domains chosen so the filter literals in the 99 benchmark queries are
actually populated (d_year 1998-2002, s_state='TN',
cc_county='Williamson County', i_category/i_class/i_color/... pools).

Facts are internally consistent: returns are drawn from sales rows and
share (item_sk, ticket/order number); tickets/orders group several line
items under one customer+store+date; ext_* amounts are quantity * price.

Everything is numpy-vectorized; scale=1.0 is ~60k fact rows total and
generates in a couple of seconds.
"""

from __future__ import annotations

import datetime
import json
import os
import re
from decimal import Decimal

import numpy as np
import pyarrow as pa

_SCHEMA = json.load(open(os.path.join(os.path.dirname(__file__),
                                      "schema.json")))

EPOCH = datetime.date(1900, 1, 1)
DATE_LO = datetime.date(1997, 1, 1)
DATE_HI = datetime.date(2003, 12, 31)
SK_BASE = 2415022  # julian-style offset for date surrogate keys


def _dsk(d: datetime.date) -> int:
    return SK_BASE + (d - EPOCH).days


# value domains (public TPC-DS spec domains, filtered to what the 99
# queries reference so their literals hit real rows)
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = ["personal", "accessories", "portable", "self-help", "classical",
           "fragrances", "pants", "computers", "shirts", "reference",
           "refernece", "stereo", "football", "birdal", "dresses",
           "maternity", "rock", "fiction", "mystery", "romance"]
COLORS = ["slate", "purple", "floral", "pale", "burlywood", "indian",
          "spring", "medium", "powder", "khaki", "brown", "honeydew",
          "deep", "light", "cornflower", "midnight", "snow", "cyan",
          "papaya", "orange", "frosted", "forest", "ghost", "chiffon",
          "blanched", "burnished", "red", "green", "blue", "white",
          "black", "yellow", "plum", "misty", "rose", "metallic"]
BRANDS = ["scholaramalgamalg #14", "amalgimporto #1", "scholaramalgamalg #7",
          "exportiunivamalg #9", "scholaramalgamalg #9", "edu packscholar #1",
          "exportiimporto #1", "importoamalg #1"] + \
    [f"brand{i} #{i % 12 + 1}" for i in range(1, 25)]
SIZES = ["medium", "extra large", "N/A", "small", "petite", "large",
         "economy"]
UNITS = ["Ounce", "Oz", "Bunch", "Ton", "N/A", "Dozen", "Box", "Pound",
         "Pallet", "Gross", "Cup", "Dram", "Each", "Tbl", "Lb", "Bundle"]
CA_STATES = ["TX", "VA", "KY", "MS", "GA", "OR", "OH", "NM", "CA", "IN",
             "WI", "LA", "CO", "IL", "WA", "NJ", "CT", "IA", "AR", "MN",
             "ND", "OK", "TN", "NY", "FL", "MI", "SD", "AL", "MO", "NE"]
CA_COUNTIES = ["Rush County", "Toole County", "Jefferson County",
               "Dona Ana County", "La Porte County", "Williamson County",
               "Orange County", "Bronx County", "Franklin Parish",
               "Walker County", "Daviess County", "Barrow County",
               "Luce County", "Richland County", "Ziebach County"]
CA_CITIES = ["Edgewood", "Fairview", "Midway", "Oakland", "Glendale",
             "Riverside", "Centerville", "Mount Zion", "Pleasant Hill",
             "Union", "Salem", "Oak Grove", "Georgetown", "Marion",
             "Greenfield", "Clinton", "Bethel", "Liberty", "Five Points",
             "Shiloh"]
STREET_TYPES = ["Street", "Ave", "Blvd", "Way", "Ct", "Dr", "Ln",
                "Parkway", "Road", "Circle"]
STREET_NAMES = ["Main", "Oak", "Park", "First", "Elm", "Maple", "Pine",
                "Cedar", "Hill", "Lake", "Sunset", "Railroad", "Church",
                "Walnut", "Spring", "Highland", "Forest", "Ridge",
                "College", "River"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
MARITAL = ["M", "S", "D", "W", "U"]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
                 "unknown"]
MEALS = ["breakfast", "lunch", "dinner", None]
SM_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY",
            "LIBRARY"]
SM_CARRIERS = ["DHL", "BARIAN", "UPS", "FEDEX", "AIRBORNE", "USPS",
               "ZOUROS", "ZHOU", "MSC", "LATVIAN"]
FIRST_NAMES = ["James", "Mary", "John", "Linda", "Robert", "Barbara",
               "Michael", "Susan", "William", "Jessica", "David", "Sarah",
               "Richard", "Karen", "Joseph", "Nancy", "Thomas", "Lisa",
               "Charles", "Betty", "Anna", "Helen", "Sandra", "Donna",
               "Carol", "Ruth", "Sharon", "Paul", "Mark", "Donald"]
LAST_NAMES = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
              "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
              "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas",
              "Taylor", "Moore", "Jackson", "Martin", "Lee", "Perez",
              "Thompson", "White", "Harris", "Sanchez", "Clark",
              "Ramirez", "Lewis", "Robinson"]
COUNTRIES = ["United States", "Canada", "Mexico", "Germany", "France",
             "Japan", "Brazil", "India", "Italy", "Spain", "Chile",
             "Peru", "Kenya", "Egypt", "Norway", "Greece"]
STORE_NAMES = ["ese", "ought", "able", "pri", "bar", "anti", "cally",
               "ation", "eing", "n st"]


class _Gen:
    def __init__(self, scale: float, seed: int):
        self.rng = np.random.default_rng(seed)
        self.scale = scale
        self.tables: dict[str, pa.Table] = {}

    # ---- helpers ---------------------------------------------------------
    def n(self, base: int) -> int:
        return max(1, int(base * self.scale))

    def pick(self, pool, size, null_frac=0.0):
        pool = list(pool)
        idx = self.rng.integers(0, len(pool), size)
        vals = [pool[i] for i in idx]
        if null_frac:
            mask = self.rng.random(size) < null_frac
            vals = [None if m else v for v, m in zip(vals, mask)]
        return vals

    def ints(self, lo, hi, size, null_frac=0.0, dtype=np.int32):
        v = self.rng.integers(lo, hi, size).astype(dtype)
        if null_frac:
            mask = self.rng.random(size) < null_frac
            return [None if m else int(x) for x, m in zip(v, mask)]
        return v

    def money(self, lo, hi, size):
        return np.round(self.rng.uniform(lo, hi, size), 2)

    def _finish(self, name: str, cols: dict) -> pa.Table:
        """Order + type-coerce per schema; fill any unspecified column with
        a generic value of its declared type."""
        schema = _SCHEMA[name]
        arrays, fields = [], []
        nrows = len(next(iter(cols.values())))
        for cname, ctype in schema:
            ctype_u = ctype.upper()
            m = re.match(r"DECIMAL\((\d+),(\d+)\)", ctype_u)
            if cname in cols:
                v = cols[cname]
            elif ctype_u == "INT" or ctype_u == "BIGINT":
                v = self.ints(1, 100, nrows, null_frac=0.05)
            elif m:
                v = self.money(1, 1000, nrows)
            elif ctype_u == "DATE":
                v = self.pick([DATE_LO + datetime.timedelta(days=i * 37)
                               for i in range(60)], nrows, null_frac=0.05)
            else:
                v = self.pick([f"{cname}_{i}" for i in range(8)], nrows,
                              null_frac=0.03)
            if m:
                p, s = int(m.group(1)), int(m.group(2))
                q = Decimal(1).scaleb(-s)
                v = pa.array([None if x is None else
                              Decimal(str(round(float(x), s))).quantize(q)
                              for x in (v.tolist() if isinstance(
                                  v, np.ndarray) else v)],
                             pa.decimal128(p, s))
            elif ctype_u in ("INT",):
                v = pa.array(v if not isinstance(v, np.ndarray)
                             else v.astype(np.int32), pa.int32())
            elif ctype_u == "BIGINT":
                v = pa.array(v if not isinstance(v, np.ndarray)
                             else v.astype(np.int64), pa.int64())
            elif ctype_u == "DATE":
                v = pa.array(v, pa.date32())
            else:
                v = pa.array([None if x is None else str(x) for x in v],
                             pa.string())
            arrays.append(v)
            fields.append(cname)
        return pa.table(dict(zip(fields, arrays)))

    # ---- dimensions ------------------------------------------------------
    def date_dim(self):
        days = (DATE_HI - DATE_LO).days + 1
        dates = [DATE_LO + datetime.timedelta(days=i) for i in range(days)]
        dow = [(d.weekday() + 1) % 7 for d in dates]  # Sunday=0 like spec
        self.tables["date_dim"] = self._finish("date_dim", {
            "d_date_sk": np.array([_dsk(d) for d in dates], np.int64),
            "d_date_id": [f"AAAAAAAA{_dsk(d):08d}" for d in dates],
            "d_date": dates,
            "d_month_seq": np.array(
                [(d.year - 1900) * 12 + d.month - 1 for d in dates]),
            "d_week_seq": np.array(
                [(d - EPOCH).days // 7 + 1 for d in dates]),
            "d_quarter_seq": np.array(
                [(d.year - 1900) * 4 + (d.month - 1) // 3 for d in dates]),
            "d_year": np.array([d.year for d in dates]),
            "d_dow": np.array(dow),
            "d_moy": np.array([d.month for d in dates]),
            "d_dom": np.array([d.day for d in dates]),
            "d_qoy": np.array([(d.month - 1) // 3 + 1 for d in dates]),
            "d_fy_year": np.array([d.year for d in dates]),
            "d_fy_quarter_seq": np.array(
                [(d.year - 1900) * 4 + (d.month - 1) // 3 for d in dates]),
            "d_fy_week_seq": np.array(
                [(d - EPOCH).days // 7 + 1 for d in dates]),
            "d_day_name": [d.strftime("%A") for d in dates],
            "d_quarter_name": [f"{d.year}Q{(d.month - 1) // 3 + 1}"
                               for d in dates],
            "d_holiday": ["Y" if (d.month, d.day) in
                          ((1, 1), (7, 4), (12, 25)) else "N"
                          for d in dates],
            "d_weekend": ["Y" if w in (0, 6) else "N" for w in dow],
            "d_following_holiday": ["N"] * days,
            "d_first_dom": np.array([_dsk(d.replace(day=1)) for d in dates],
                                    np.int64),
            "d_last_dom": np.array([_dsk(d) for d in dates], np.int64),
            "d_same_day_ly": np.array([_dsk(d) - 365 for d in dates],
                                      np.int64),
            "d_same_day_lq": np.array([_dsk(d) - 91 for d in dates],
                                      np.int64),
            "d_current_day": ["N"] * days,
            "d_current_week": ["N"] * days,
            "d_current_month": ["N"] * days,
            "d_current_quarter": ["N"] * days,
            "d_current_year": ["N"] * days,
        })

    def time_dim(self):
        n = 1440  # one row per minute; facts sample these sks
        secs = np.arange(n) * 60
        hours = secs // 3600
        self.tables["time_dim"] = self._finish("time_dim", {
            "t_time_sk": secs.astype(np.int64),
            "t_time_id": [f"TIME{s:08d}" for s in secs],
            "t_time": secs,
            "t_hour": hours,
            "t_minute": (secs // 60) % 60,
            "t_second": secs % 60,
            "t_am_pm": ["AM" if h < 12 else "PM" for h in hours],
            "t_shift": ["first" if h < 8 else "second" if h < 16 else
                        "third" for h in hours],
            "t_sub_shift": ["morning" if h < 12 else "afternoon" if h < 17
                            else "evening" if h < 21 else "night"
                            for h in hours],
            "t_meal_time": ["breakfast" if 6 <= h <= 9 else
                            "lunch" if 11 <= h <= 13 else
                            "dinner" if 17 <= h <= 20 else None
                            for h in hours],
        })

    def item(self):
        n = self.n(400)
        n_ids = max(2, int(n * 0.75))  # some item_ids span several sks
        ids = [f"AAAAAAAA{i:08d}" for i in
               self.rng.permutation(n_ids)[:n_ids]]
        item_ids = [ids[i % n_ids] for i in range(n)]
        cat_idx = self.rng.integers(0, len(CATEGORIES), n)
        price = self.money(0.5, 300, n)
        self.tables["item"] = self._finish("item", {
            "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
            "i_item_id": item_ids,
            "i_rec_start_date": [datetime.date(1997, 10, 27)] * n,
            "i_rec_end_date": [None] * n,
            "i_item_desc": [f"item description {i}" for i in range(n)],
            "i_current_price": price,
            "i_wholesale_cost": np.round(price * 0.6, 2),
            "i_brand_id": self.ints(1001001, 10016017, n),
            "i_brand": self.pick(BRANDS, n),
            "i_class_id": self.ints(1, 16, n),
            "i_class": self.pick(CLASSES, n),
            "i_category_id": (cat_idx + 1).astype(np.int32),
            "i_category": [CATEGORIES[i] for i in cat_idx],
            "i_manufact_id": self.pick(
                [128, 129, 350, 677, 738, 977] + list(range(1, 1000, 7)), n),
            "i_manufact": [f"manufact{i % 100}" for i in range(n)],
            "i_size": self.pick(SIZES, n),
            "i_formulation": [f"formulation{i % 50}" for i in range(n)],
            "i_color": self.pick(COLORS, n),
            "i_units": self.pick(UNITS, n),
            "i_container": ["Unknown"] * n,
            "i_manager_id": self.pick(list(range(1, 101)), n),
            "i_product_name": [f"product{i}" for i in range(n)],
        })

    def customer_address(self):
        n = self.n(600)
        self.tables["customer_address"] = self._finish("customer_address", {
            "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
            "ca_address_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "ca_street_number": [str(self.rng.integers(1, 999))
                                 for _ in range(n)],
            "ca_street_name": self.pick(STREET_NAMES, n),
            "ca_street_type": self.pick(STREET_TYPES, n),
            "ca_suite_number": [f"Suite {i % 80}" for i in range(n)],
            "ca_city": self.pick(CA_CITIES, n),
            "ca_county": self.pick(CA_COUNTIES, n),
            "ca_state": self.pick(CA_STATES, n),
            "ca_zip": [f"{z:05d}" for z in self.ints(10000, 99999, n)],
            "ca_country": ["United States"] * n,
            "ca_gmt_offset": self.pick([-5.0, -6.0, -7.0, -8.0], n),
            "ca_location_type": self.pick(
                ["apartment", "condo", "single family"], n),
        })

    def customer_demographics(self):
        rows = []
        sk = 1
        for g in ["M", "F"]:
            for ms in MARITAL:
                for ed in EDUCATION:
                    for pe in [500, 2500, 5000, 7500, 10000]:
                        for cr in CREDIT:
                            rows.append((sk, g, ms, ed, pe, cr,
                                         sk % 7, sk % 7, sk % 7))
                            sk += 1
        a = list(zip(*rows))
        self.tables["customer_demographics"] = self._finish(
            "customer_demographics", {
                "cd_demo_sk": np.array(a[0], np.int64),
                "cd_gender": list(a[1]),
                "cd_marital_status": list(a[2]),
                "cd_education_status": list(a[3]),
                "cd_purchase_estimate": np.array(a[4]),
                "cd_credit_rating": list(a[5]),
                "cd_dep_count": np.array(a[6]),
                "cd_dep_employed_count": np.array(a[7]),
                "cd_dep_college_count": np.array(a[8]),
            })

    def household_demographics(self):
        rows = []
        sk = 1
        for ib in range(1, 21):
            for bp in BUY_POTENTIAL:
                for dep in range(0, 10, 3):
                    for veh in range(-1, 5):
                        rows.append((sk, ib, bp, dep, veh))
                        sk += 1
        a = list(zip(*rows))
        self.tables["household_demographics"] = self._finish(
            "household_demographics", {
                "hd_demo_sk": np.array(a[0], np.int64),
                "hd_income_band_sk": np.array(a[1], np.int64),
                "hd_buy_potential": list(a[2]),
                "hd_dep_count": np.array(a[3]),
                "hd_vehicle_count": np.array(a[4]),
            })

    def income_band(self):
        self.tables["income_band"] = self._finish("income_band", {
            "ib_income_band_sk": np.arange(1, 21, dtype=np.int64),
            "ib_lower_bound": np.arange(20) * 10000,
            "ib_upper_bound": (np.arange(20) + 1) * 10000,
        })

    def customer(self):
        n = self.n(1000)
        n_addr = self.tables["customer_address"].num_rows
        n_cd = self.tables["customer_demographics"].num_rows
        n_hd = self.tables["household_demographics"].num_rows
        first_dates = self.ints(_dsk(datetime.date(1998, 1, 1)),
                                _dsk(datetime.date(2001, 1, 1)), n,
                                dtype=np.int64)
        self.tables["customer"] = self._finish("customer", {
            "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
            "c_customer_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "c_current_cdemo_sk": self.ints(1, n_cd + 1, n, null_frac=0.02,
                                            dtype=np.int64),
            "c_current_hdemo_sk": self.ints(1, n_hd + 1, n, null_frac=0.02,
                                            dtype=np.int64),
            "c_current_addr_sk": self.ints(1, n_addr + 1, n,
                                           dtype=np.int64),
            "c_first_shipto_date_sk": first_dates + 30,
            "c_first_sales_date_sk": first_dates,
            "c_salutation": self.pick(["Mr.", "Mrs.", "Ms.", "Dr.",
                                       "Miss", "Sir"], n, null_frac=0.02),
            "c_first_name": self.pick(FIRST_NAMES, n, null_frac=0.02),
            "c_last_name": self.pick(LAST_NAMES, n, null_frac=0.02),
            "c_preferred_cust_flag": self.pick(["Y", "N"], n,
                                               null_frac=0.02),
            "c_birth_day": self.ints(1, 29, n, null_frac=0.02),
            "c_birth_month": self.ints(1, 13, n, null_frac=0.02),
            "c_birth_year": self.ints(1930, 1993, n, null_frac=0.02),
            "c_birth_country": self.pick(COUNTRIES, n, null_frac=0.02),
            "c_login": [None] * n,
            "c_email_address": [f"c{i}@example.com" for i in range(n)],
            "c_last_review_date": self.ints(
                _dsk(datetime.date(1999, 1, 1)),
                _dsk(datetime.date(2002, 1, 1)), n),
        })

    def store(self):
        n = max(6, self.n(12))
        emp = self.ints(200, 301, n)
        self.tables["store"] = self._finish("store", {
            "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
            "s_store_id": [f"AAAAAAAA{i % max(1, n // 2):08d}"
                           for i in range(n)],
            "s_rec_start_date": [datetime.date(1997, 3, 13)] * n,
            "s_rec_end_date": [None] * n,
            "s_closed_date_sk": [None] * n,
            "s_store_name": [STORE_NAMES[i % len(STORE_NAMES)]
                             for i in range(n)],
            "s_number_employees": emp,
            "s_floor_space": self.ints(5000000, 9000000, n),
            "s_hours": self.pick(["8AM-8PM", "8AM-4PM", "8AM-12AM"], n),
            "s_manager": self.pick(FIRST_NAMES, n),
            "s_market_id": self.ints(1, 11, n),
            "s_geography_class": ["Unknown"] * n,
            "s_market_desc": [f"market desc {i}" for i in range(n)],
            "s_market_manager": self.pick(LAST_NAMES, n),
            "s_division_id": np.ones(n, np.int32),
            "s_division_name": ["Unknown"] * n,
            "s_company_id": np.ones(n, np.int32),
            "s_company_name": ["Unknown"] * n,
            "s_street_number": [str(i * 10 + 1) for i in range(n)],
            "s_street_name": self.pick(STREET_NAMES, n),
            "s_street_type": self.pick(STREET_TYPES, n),
            "s_suite_number": [f"Suite {i}" for i in range(n)],
            "s_city": [(["Fairview"] * 6 + ["Midway"] * 3 +
                        ["Salem"])[i % 10] for i in range(n)],
            "s_county": [("Williamson County" if i % 8 else
                          "Franklin Parish") for i in range(1, n + 1)],
            "s_state": ["TN"] * n,
            "s_zip": [f"{38000 + i}" for i in range(n)],
            "s_country": ["United States"] * n,
            "s_gmt_offset": [-5.0] * n,
            "s_tax_precentage": self.pick([0.00, 0.01, 0.02, 0.03], n),
        })

    def warehouse(self):
        n = max(3, self.n(5))
        self.tables["warehouse"] = self._finish("warehouse", {
            "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int64),
            "w_warehouse_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "w_warehouse_name": [f"Warehouse {i}" for i in range(n)],
            "w_warehouse_sq_ft": self.ints(50000, 1000000, n),
            "w_street_number": [str(i + 1) for i in range(n)],
            "w_street_name": self.pick(STREET_NAMES, n),
            "w_street_type": self.pick(STREET_TYPES, n),
            "w_suite_number": [f"Suite {i}" for i in range(n)],
            "w_city": self.pick(CA_CITIES, n),
            "w_county": ["Williamson County"] * n,
            "w_state": ["TN"] * n,
            "w_zip": [f"{38100 + i}" for i in range(n)],
            "w_country": ["United States"] * n,
            "w_gmt_offset": [-5.0] * n,
        })

    def ship_mode(self):
        n = 20
        self.tables["ship_mode"] = self._finish("ship_mode", {
            "sm_ship_mode_sk": np.arange(1, n + 1, dtype=np.int64),
            "sm_ship_mode_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "sm_type": [SM_TYPES[i % len(SM_TYPES)] for i in range(n)],
            "sm_code": self.pick(["AIR", "SURFACE", "SEA"], n),
            "sm_carrier": [SM_CARRIERS[i % len(SM_CARRIERS)]
                           for i in range(n)],
            "sm_contract": [f"contract{i}" for i in range(n)],
        })

    def reason(self):
        n = 35
        self.tables["reason"] = self._finish("reason", {
            "r_reason_sk": np.arange(1, n + 1, dtype=np.int64),
            "r_reason_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "r_reason_desc": [f"reason {i}" for i in range(1, n + 1)],
        })

    def call_center(self):
        n = max(2, self.n(4))
        self.tables["call_center"] = self._finish("call_center", {
            "cc_call_center_sk": np.arange(1, n + 1, dtype=np.int64),
            "cc_call_center_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "cc_rec_start_date": [datetime.date(1998, 1, 1)] * n,
            "cc_rec_end_date": [None] * n,
            "cc_closed_date_sk": [None] * n,
            "cc_open_date_sk": [_dsk(datetime.date(1998, 1, 1))] * n,
            "cc_name": [f"call center {i}" for i in range(n)],
            "cc_class": self.pick(["small", "medium", "large"], n),
            "cc_employees": self.ints(100, 700, n),
            "cc_sq_ft": self.ints(10000, 50000, n),
            "cc_hours": self.pick(["8AM-8PM", "8AM-4PM"], n),
            "cc_manager": self.pick(FIRST_NAMES, n),
            "cc_mkt_id": self.ints(1, 7, n),
            "cc_mkt_class": [f"mkt class {i}" for i in range(n)],
            "cc_mkt_desc": [f"mkt desc {i}" for i in range(n)],
            "cc_market_manager": self.pick(LAST_NAMES, n),
            "cc_division": np.ones(n, np.int32),
            "cc_division_name": ["Unknown"] * n,
            "cc_company": np.ones(n, np.int32),
            "cc_company_name": ["Unknown"] * n,
            "cc_street_number": [str(i + 1) for i in range(n)],
            "cc_street_name": self.pick(STREET_NAMES, n),
            "cc_street_type": self.pick(STREET_TYPES, n),
            "cc_suite_number": [f"Suite {i}" for i in range(n)],
            "cc_city": ["Fairview"] * n,
            "cc_county": ["Williamson County"] * n,
            "cc_state": ["TN"] * n,
            "cc_zip": [f"{38200 + i}" for i in range(n)],
            "cc_country": ["United States"] * n,
            "cc_gmt_offset": [-5.0] * n,
            "cc_tax_percentage": self.pick([0.00, 0.01, 0.02], n),
        })

    def catalog_page(self):
        n = self.n(200)
        self.tables["catalog_page"] = self._finish("catalog_page", {
            "cp_catalog_page_sk": np.arange(1, n + 1, dtype=np.int64),
            "cp_catalog_page_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "cp_start_date_sk": self.ints(
                _dsk(datetime.date(1998, 1, 1)),
                _dsk(datetime.date(2002, 1, 1)), n, dtype=np.int64),
            "cp_end_date_sk": self.ints(
                _dsk(datetime.date(2002, 1, 2)),
                _dsk(datetime.date(2003, 12, 31)), n, dtype=np.int64),
            "cp_department": ["DEPARTMENT"] * n,
            "cp_catalog_number": self.ints(1, 20, n),
            "cp_catalog_page_number": self.ints(1, 100, n),
            "cp_description": [f"catalog page {i}" for i in range(n)],
            "cp_type": self.pick(["bi-annual", "quarterly", "monthly"], n),
        })

    def web_site(self):
        n = max(4, self.n(10))
        self.tables["web_site"] = self._finish("web_site", {
            "web_site_sk": np.arange(1, n + 1, dtype=np.int64),
            "web_site_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "web_rec_start_date": [datetime.date(1997, 8, 16)] * n,
            "web_rec_end_date": [None] * n,
            "web_name": [f"site_{i % max(1, n // 2)}" for i in range(n)],
            "web_open_date_sk": [_dsk(datetime.date(1998, 1, 1))] * n,
            "web_close_date_sk": [None] * n,
            "web_class": ["Unknown"] * n,
            "web_manager": self.pick(FIRST_NAMES, n),
            "web_mkt_id": self.ints(1, 7, n),
            "web_mkt_class": [f"mkt class {i}" for i in range(n)],
            "web_mkt_desc": [f"mkt desc {i}" for i in range(n)],
            "web_market_manager": self.pick(LAST_NAMES, n),
            "web_company_id": np.ones(n, np.int32),
            "web_company_name": [(["pri"] * 3 + ["able", "ese", "anti"])
                                 [i % 6] for i in range(n)],
            "web_street_number": [str(i + 1) for i in range(n)],
            "web_street_name": self.pick(STREET_NAMES, n),
            "web_street_type": self.pick(STREET_TYPES, n),
            "web_suite_number": [f"Suite {i}" for i in range(n)],
            "web_city": ["Midway"] * n,
            "web_county": ["Williamson County"] * n,
            "web_state": ["TN"] * n,
            "web_zip": [f"{38300 + i}" for i in range(n)],
            "web_country": ["United States"] * n,
            "web_gmt_offset": [-5.0] * n,
            "web_tax_percentage": self.pick([0.00, 0.01, 0.02], n),
        })

    def web_page(self):
        n = max(10, self.n(20))
        self.tables["web_page"] = self._finish("web_page", {
            "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int64),
            "wp_web_page_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "wp_rec_start_date": [datetime.date(1997, 9, 3)] * n,
            "wp_rec_end_date": [None] * n,
            "wp_creation_date_sk": [_dsk(datetime.date(1998, 1, 1))] * n,
            "wp_access_date_sk": [_dsk(datetime.date(2000, 1, 1))] * n,
            "wp_autogen_flag": self.pick(["Y", "N"], n),
            "wp_customer_sk": [None] * n,
            "wp_url": ["http://www.foo.com"] * n,
            "wp_type": self.pick(["order", "general", "welcome",
                                  "protected", "feedback", "ad"], n),
            "wp_char_count": self.ints(2000, 8000, n),
            "wp_link_count": self.ints(2, 25, n),
            "wp_image_count": self.ints(1, 7, n),
            "wp_max_ad_count": self.ints(0, 4, n),
        })

    def promotion(self):
        n = max(10, self.n(30))
        self.tables["promotion"] = self._finish("promotion", {
            "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
            "p_promo_id": [f"AAAAAAAA{i:08d}" for i in range(n)],
            "p_start_date_sk": self.ints(
                _dsk(datetime.date(1998, 1, 1)),
                _dsk(datetime.date(2001, 1, 1)), n, dtype=np.int64),
            "p_end_date_sk": self.ints(
                _dsk(datetime.date(2001, 1, 2)),
                _dsk(datetime.date(2003, 6, 30)), n, dtype=np.int64),
            "p_item_sk": self.ints(
                1, self.tables["item"].num_rows + 1, n, dtype=np.int64),
            "p_cost": np.full(n, 1000.0),
            "p_response_target": np.ones(n, np.int32),
            "p_promo_name": self.pick(["ought", "able", "pri", "ese",
                                       "anti", "cally"], n),
            "p_channel_dmail": self.pick(["Y", "N"], n),
            "p_channel_email": self.pick(["N", "N", "N", "Y"], n),
            "p_channel_catalog": self.pick(["N", "N", "Y"], n),
            "p_channel_tv": self.pick(["N", "N", "N", "Y"], n),
            "p_channel_radio": self.pick(["N", "Y"], n),
            "p_channel_press": self.pick(["N", "Y"], n),
            "p_channel_event": self.pick(["N", "N", "Y"], n),
            "p_channel_demo": self.pick(["N", "Y"], n),
            "p_channel_details": [f"promo details {i}" for i in range(n)],
            "p_purpose": ["Unknown"] * n,
            "p_discount_active": self.pick(["N", "Y"], n),
        })

    # ---- facts -----------------------------------------------------------
    def _sale_dates(self, size):
        lo = _dsk(datetime.date(1998, 1, 2))
        hi = _dsk(datetime.date(2002, 12, 30))
        return self.rng.integers(lo, hi, size).astype(np.int64)

    def _null_some(self, arr, frac=0.02):
        mask = self.rng.random(len(arr)) < frac
        return [None if m else int(x) for x, m in zip(arr, mask)]

    def store_sales(self):
        n = self.n(30000)
        n_orders = max(1, n // 4)
        n_item = self.tables["item"].num_rows
        n_cust = self.tables["customer"].num_rows
        n_store = self.tables["store"].num_rows
        n_hd = self.tables["household_demographics"].num_rows
        n_cd = self.tables["customer_demographics"].num_rows
        n_addr = self.tables["customer_address"].num_rows
        n_promo = self.tables["promotion"].num_rows
        # order-level attributes shared by line items of one ticket
        o_cust = self.rng.integers(1, n_cust + 1, n_orders)
        o_store = self.rng.integers(1, n_store + 1, n_orders)
        o_date = self._sale_dates(n_orders)
        o_time = self.rng.integers(0, 1440, n_orders) * 60
        o_hd = self.rng.integers(1, n_hd + 1, n_orders)
        o_cd = self.rng.integers(1, n_cd + 1, n_orders)
        o_addr = self.rng.integers(1, n_addr + 1, n_orders)
        oi = self.rng.integers(0, n_orders, n)
        qty = self.rng.integers(1, 100, n)
        wholesale = self.money(1, 100, n)
        list_p = np.round(wholesale * self.rng.uniform(1.0, 2.0, n), 2)
        sales_p = np.round(list_p * self.rng.uniform(0.3, 1.0, n), 2)
        ext_sales = np.round(qty * sales_p, 2)
        ext_whole = np.round(qty * wholesale, 2)
        ext_list = np.round(qty * list_p, 2)
        ext_tax = np.round(ext_sales * 0.05, 2)
        coupon = np.where(self.rng.random(n) < 0.1,
                          np.round(ext_sales * 0.2, 2), 0.0)
        net_paid = np.round(ext_sales - coupon, 2)
        self._ss = dict(oi=oi, qty=qty)
        self.tables["store_sales"] = self._finish("store_sales", {
            "ss_sold_date_sk": self._null_some(o_date[oi]),
            "ss_sold_time_sk": self._null_some(o_time[oi]),
            "ss_item_sk": self.rng.integers(1, n_item + 1, n
                                            ).astype(np.int64),
            "ss_customer_sk": self._null_some(o_cust[oi]),
            "ss_cdemo_sk": self._null_some(o_cd[oi]),
            "ss_hdemo_sk": self._null_some(o_hd[oi]),
            "ss_addr_sk": self._null_some(o_addr[oi]),
            "ss_store_sk": self._null_some(o_store[oi]),
            "ss_promo_sk": self._null_some(
                self.rng.integers(1, n_promo + 1, n), 0.3),
            "ss_ticket_number": (oi + 1).astype(np.int64),
            "ss_quantity": qty.astype(np.int32),
            "ss_wholesale_cost": wholesale,
            "ss_list_price": list_p,
            "ss_sales_price": sales_p,
            "ss_ext_discount_amt": np.round(ext_list - ext_sales, 2),
            "ss_ext_sales_price": ext_sales,
            "ss_ext_wholesale_cost": ext_whole,
            "ss_ext_list_price": ext_list,
            "ss_ext_tax": ext_tax,
            "ss_coupon_amt": coupon,
            "ss_net_paid": net_paid,
            "ss_net_paid_inc_tax": np.round(net_paid + ext_tax, 2),
            "ss_net_profit": np.round(net_paid - ext_whole, 2),
        })

    def store_returns(self):
        ss = self.tables["store_sales"]
        n_ss = ss.num_rows
        take = np.sort(self.rng.permutation(n_ss)[:max(1, n_ss // 10)])
        base = ss.take(pa.array(take))
        n = base.num_rows
        sold = np.array([x if x is not None else _dsk(
            datetime.date(2000, 1, 1))
            for x in base.column("ss_sold_date_sk").to_pylist()], np.int64)
        ret_date = sold + self.rng.integers(1, 90, n)
        rqty = np.maximum(1, (np.array(
            base.column("ss_quantity").to_pylist()) *
            self.rng.uniform(0.2, 1.0, n)).astype(np.int64))
        sales_p = np.array([float(x) if x is not None else 1.0 for x in
                            base.column("ss_sales_price").to_pylist()])
        amt = np.round(rqty * sales_p, 2)
        fee = self.money(0.5, 100, n)
        self.tables["store_returns"] = self._finish("store_returns", {
            "sr_returned_date_sk": self._null_some(ret_date),
            "sr_return_time_sk": self._null_some(
                self.rng.integers(0, 1440, n) * 60),
            "sr_item_sk": np.array(base.column("ss_item_sk").to_pylist(),
                                   np.int64),
            "sr_customer_sk": self._null_some(np.array(
                [x if x is not None else 1 for x in
                 base.column("ss_customer_sk").to_pylist()], np.int64)),
            "sr_cdemo_sk": self._null_some(np.array(
                [x if x is not None else 1 for x in
                 base.column("ss_cdemo_sk").to_pylist()], np.int64)),
            "sr_hdemo_sk": self._null_some(np.array(
                [x if x is not None else 1 for x in
                 base.column("ss_hdemo_sk").to_pylist()], np.int64)),
            "sr_addr_sk": self._null_some(np.array(
                [x if x is not None else 1 for x in
                 base.column("ss_addr_sk").to_pylist()], np.int64)),
            "sr_store_sk": self._null_some(np.array(
                [x if x is not None else 1 for x in
                 base.column("ss_store_sk").to_pylist()], np.int64)),
            "sr_reason_sk": self._null_some(
                self.rng.integers(1, 36, n)),
            "sr_ticket_number": np.array(
                base.column("ss_ticket_number").to_pylist(), np.int64),
            "sr_return_quantity": rqty.astype(np.int32),
            "sr_return_amt": amt,
            "sr_return_tax": np.round(amt * 0.05, 2),
            "sr_return_amt_inc_tax": np.round(amt * 1.05, 2),
            "sr_fee": fee,
            "sr_return_ship_cost": self.money(0, 50, n),
            "sr_refunded_cash": np.round(amt * 0.7, 2),
            "sr_reversed_charge": np.round(amt * 0.2, 2),
            "sr_store_credit": np.round(amt * 0.1, 2),
            "sr_net_loss": np.round(amt * 0.5 + fee, 2),
        })

    def _channel_sales(self, prefix: str, n: int, extra: dict,
                       table: str):
        """Shared generator for catalog_sales / web_sales line items."""
        n_item = self.tables["item"].num_rows
        n_cust = self.tables["customer"].num_rows
        n_orders = max(1, n // 3)
        o_bill = self.rng.integers(1, n_cust + 1, n_orders)
        same = self.rng.random(n_orders) < 0.85
        o_ship = np.where(same, o_bill,
                          self.rng.integers(1, n_cust + 1, n_orders))
        o_date = self._sale_dates(n_orders)
        oi = self.rng.integers(0, n_orders, n)
        qty = self.rng.integers(1, 100, n)
        wholesale = self.money(1, 100, n)
        list_p = np.round(wholesale * self.rng.uniform(1.0, 2.0, n), 2)
        sales_p = np.round(list_p * self.rng.uniform(0.3, 1.0, n), 2)
        ext_sales = np.round(qty * sales_p, 2)
        ext_whole = np.round(qty * wholesale, 2)
        ext_list = np.round(qty * list_p, 2)
        ext_tax = np.round(ext_sales * 0.05, 2)
        coupon = np.where(self.rng.random(n) < 0.1,
                          np.round(ext_sales * 0.2, 2), 0.0)
        net_paid = np.round(ext_sales - coupon, 2)
        ship_cost = self.money(0.5, 40, n)
        n_cd = self.tables["customer_demographics"].num_rows
        n_hd = self.tables["household_demographics"].num_rows
        n_addr = self.tables["customer_address"].num_rows
        o_cd = self.rng.integers(1, n_cd + 1, n_orders)
        o_hd = self.rng.integers(1, n_hd + 1, n_orders)
        o_ba = self.rng.integers(1, n_addr + 1, n_orders)
        o_sa = self.rng.integers(1, n_addr + 1, n_orders)
        cols = {
            f"{prefix}_sold_date_sk": self._null_some(o_date[oi]),
            f"{prefix}_sold_time_sk": self._null_some(
                self.rng.integers(0, 1440, n) * 60),
            f"{prefix}_ship_date_sk": self._null_some(
                o_date[oi] + self.rng.integers(1, 30, n)),
            f"{prefix}_bill_customer_sk": self._null_some(o_bill[oi]),
            f"{prefix}_bill_cdemo_sk": self._null_some(o_cd[oi]),
            f"{prefix}_bill_hdemo_sk": self._null_some(o_hd[oi]),
            f"{prefix}_bill_addr_sk": self._null_some(o_ba[oi]),
            f"{prefix}_ship_customer_sk": self._null_some(o_ship[oi]),
            f"{prefix}_ship_cdemo_sk": self._null_some(o_cd[oi]),
            f"{prefix}_ship_hdemo_sk": self._null_some(o_hd[oi]),
            f"{prefix}_ship_addr_sk": self._null_some(o_sa[oi]),
            f"{prefix}_ship_mode_sk": self._null_some(
                self.rng.integers(1, 21, n)),
            f"{prefix}_warehouse_sk": self._null_some(self.rng.integers(
                1, self.tables["warehouse"].num_rows + 1, n)),
            f"{prefix}_item_sk": self.rng.integers(
                1, n_item + 1, n).astype(np.int64),
            f"{prefix}_promo_sk": self._null_some(self.rng.integers(
                1, self.tables["promotion"].num_rows + 1, n), 0.3),
            f"{prefix}_order_number": (oi + 1).astype(np.int64),
            f"{prefix}_quantity": qty.astype(np.int32),
            f"{prefix}_wholesale_cost": wholesale,
            f"{prefix}_list_price": list_p,
            f"{prefix}_sales_price": sales_p,
            f"{prefix}_ext_discount_amt": np.round(ext_list - ext_sales, 2),
            f"{prefix}_ext_sales_price": ext_sales,
            f"{prefix}_ext_wholesale_cost": ext_whole,
            f"{prefix}_ext_list_price": ext_list,
            f"{prefix}_ext_tax": ext_tax,
            f"{prefix}_coupon_amt": coupon,
            f"{prefix}_ext_ship_cost": ship_cost,
            f"{prefix}_net_paid": net_paid,
            f"{prefix}_net_paid_inc_tax": np.round(net_paid + ext_tax, 2),
            f"{prefix}_net_paid_inc_ship": np.round(
                net_paid + ship_cost, 2),
            f"{prefix}_net_paid_inc_ship_tax": np.round(
                net_paid + ship_cost + ext_tax, 2),
            f"{prefix}_net_profit": np.round(net_paid - ext_whole, 2),
        }
        cols.update(extra(oi, n) if callable(extra) else extra)
        self.tables[table] = self._finish(table, cols)

    def catalog_sales(self):
        n = self.n(15000)
        n_cc = self.tables["call_center"].num_rows
        n_cp = self.tables["catalog_page"].num_rows

        def extra(oi, n):
            return {
                "cs_call_center_sk": self._null_some(
                    self.rng.integers(1, n_cc + 1, n)),
                "cs_catalog_page_sk": self._null_some(
                    self.rng.integers(1, n_cp + 1, n)),
            }
        self._channel_sales("cs", n, extra, "catalog_sales")

    def web_sales(self):
        n = self.n(10000)
        n_wp = self.tables["web_page"].num_rows
        n_web = self.tables["web_site"].num_rows

        def extra(oi, n):
            return {
                "ws_web_page_sk": self._null_some(
                    self.rng.integers(1, n_wp + 1, n)),
                "ws_web_site_sk": self._null_some(
                    self.rng.integers(1, n_web + 1, n)),
            }
        self._channel_sales("ws", n, extra, "web_sales")

    def _returns_from(self, sales: str, sp: str, rp: str, table: str,
                      extra_cols):
        st = self.tables[sales]
        n_s = st.num_rows
        take = np.sort(self.rng.permutation(n_s)[:max(1, n_s // 10)])
        base = st.take(pa.array(take))
        n = base.num_rows

        def col(name, default=1):
            return np.array([x if x is not None else default for x in
                             base.column(name).to_pylist()], np.int64)
        sold = col(f"{sp}_sold_date_sk", _dsk(datetime.date(2000, 1, 1)))
        rqty = np.maximum(1, (np.array(
            base.column(f"{sp}_quantity").to_pylist()) *
            self.rng.uniform(0.2, 1.0, n)).astype(np.int64))
        sales_p = np.array([float(x) if x is not None else 1.0 for x in
                            base.column(f"{sp}_sales_price").to_pylist()])
        amt = np.round(rqty * sales_p, 2)
        fee = self.money(0.5, 100, n)
        cols = {
            f"{rp}_returned_date_sk": self._null_some(
                sold + self.rng.integers(1, 90, n)),
            f"{rp}_returned_time_sk": self._null_some(
                self.rng.integers(0, 1440, n) * 60),
            f"{rp}_item_sk": col(f"{sp}_item_sk"),
            f"{rp}_order_number": np.array(
                base.column(f"{sp}_order_number").to_pylist(), np.int64),
            f"{rp}_return_quantity": rqty.astype(np.int32),
            f"{rp}_return_amount" if rp == "wr" else
            f"{rp}_return_amount": amt,
            f"{rp}_return_tax": np.round(amt * 0.05, 2),
            f"{rp}_return_amt_inc_tax": np.round(amt * 1.05, 2),
            f"{rp}_fee": fee,
            f"{rp}_return_ship_cost": self.money(0, 50, n),
            f"{rp}_refunded_cash": np.round(amt * 0.7, 2),
            f"{rp}_reversed_charge": np.round(amt * 0.2, 2),
            f"{rp}_net_loss": np.round(amt * 0.5 + fee, 2),
        }
        cols.update(extra_cols(base, col, n, amt))
        self.tables[table] = self._finish(table, cols)

    def catalog_returns(self):
        def extra(base, col, n, amt):
            return {
                "cr_refunded_customer_sk": self._null_some(
                    col("cs_bill_customer_sk")),
                "cr_refunded_cdemo_sk": self._null_some(
                    col("cs_bill_cdemo_sk")),
                "cr_refunded_hdemo_sk": self._null_some(
                    col("cs_bill_hdemo_sk")),
                "cr_refunded_addr_sk": self._null_some(
                    col("cs_bill_addr_sk")),
                "cr_returning_customer_sk": self._null_some(
                    col("cs_ship_customer_sk")),
                "cr_returning_cdemo_sk": self._null_some(
                    col("cs_ship_cdemo_sk")),
                "cr_returning_hdemo_sk": self._null_some(
                    col("cs_ship_hdemo_sk")),
                "cr_returning_addr_sk": self._null_some(
                    col("cs_ship_addr_sk")),
                "cr_call_center_sk": self._null_some(
                    col("cs_call_center_sk")),
                "cr_catalog_page_sk": self._null_some(
                    col("cs_catalog_page_sk")),
                "cr_ship_mode_sk": self._null_some(
                    col("cs_ship_mode_sk")),
                "cr_warehouse_sk": self._null_some(
                    col("cs_warehouse_sk")),
                "cr_reason_sk": self._null_some(
                    self.rng.integers(1, 36, n)),
                "cr_return_amount": amt,
                "cr_store_credit": np.round(amt * 0.1, 2),
            }
        self._returns_from("catalog_sales", "cs", "cr", "catalog_returns",
                           extra)

    def web_returns(self):
        def extra(base, col, n, amt):
            return {
                "wr_refunded_customer_sk": self._null_some(
                    col("ws_bill_customer_sk")),
                "wr_refunded_cdemo_sk": self._null_some(
                    col("ws_bill_cdemo_sk")),
                "wr_refunded_hdemo_sk": self._null_some(
                    col("ws_bill_hdemo_sk")),
                "wr_refunded_addr_sk": self._null_some(
                    col("ws_bill_addr_sk")),
                "wr_returning_customer_sk": self._null_some(
                    col("ws_ship_customer_sk")),
                "wr_returning_cdemo_sk": self._null_some(
                    col("ws_ship_cdemo_sk")),
                "wr_returning_hdemo_sk": self._null_some(
                    col("ws_ship_hdemo_sk")),
                "wr_returning_addr_sk": self._null_some(
                    col("ws_ship_addr_sk")),
                "wr_web_page_sk": self._null_some(col("ws_web_page_sk")),
                "wr_reason_sk": self._null_some(
                    self.rng.integers(1, 36, n)),
                "wr_return_amt": amt,
                "wr_account_credit": np.round(amt * 0.1, 2),
            }
        self._returns_from("web_sales", "ws", "wr", "web_returns", extra)

    def inventory(self):
        n_item = self.tables["item"].num_rows
        n_wh = self.tables["warehouse"].num_rows
        # weekly snapshots over the sales window, subsampled items
        week_starts = []
        d = datetime.date(1998, 1, 2)
        while d <= datetime.date(2002, 12, 30):
            week_starts.append(_dsk(d))
            d += datetime.timedelta(days=7)
        items = np.arange(1, n_item + 1)
        sample = items[self.rng.random(n_item) <
                       min(1.0, 120 / max(1, n_item))]
        if len(sample) == 0:
            sample = items[:1]
        combos = [(w, it, wh) for w in week_starts for it in sample
                  for wh in range(1, n_wh + 1)]
        n = len(combos)
        a = list(zip(*combos))
        self.tables["inventory"] = self._finish("inventory", {
            "inv_date_sk": np.array(a[0], np.int64),
            "inv_item_sk": np.array(a[1], np.int64),
            "inv_warehouse_sk": np.array(a[2], np.int64),
            "inv_quantity_on_hand": self.ints(0, 1000, n, null_frac=0.03),
        })


def gen_tpcds_full(scale: float = 1.0, seed: int = 17
                   ) -> dict[str, pa.Table]:
    g = _Gen(scale, seed)
    g.date_dim()
    g.time_dim()
    g.item()
    g.customer_address()
    g.customer_demographics()
    g.household_demographics()
    g.income_band()
    g.customer()
    g.store()
    g.warehouse()
    g.ship_mode()
    g.reason()
    g.call_center()
    g.catalog_page()
    g.web_site()
    g.web_page()
    g.promotion()
    g.store_sales()
    g.store_returns()
    g.catalog_sales()
    g.catalog_returns()
    g.web_sales()
    g.web_returns()
    g.inventory()
    # schema conformance guard
    for name, cols in _SCHEMA.items():
        t = g.tables[name]
        assert t.column_names == [c for c, _ in cols], \
            f"{name}: {t.column_names} != {[c for c, _ in cols]}"
    return g.tables
