"""Pallas MXU kernels vs numpy oracles (interpret mode on CPU; the same
programs compile for TPU — see ops/pallas_kernels.py)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from spark_tpu.ops.pallas_kernels import (  # noqa: E402
    dense_group_sum_f32, partition_histogram,
)


def test_partition_histogram_exact():
    rng = np.random.default_rng(0)
    for cap, parts in [(100, 3), (5000, 37), (8192, 128), (3000, 200)]:
        pids = rng.integers(0, parts, cap)
        mask = rng.random(cap) < 0.8
        got = np.asarray(partition_histogram(
            jnp.asarray(pids, jnp.int32), jnp.asarray(mask), parts))
        exp = np.bincount(pids[mask], minlength=parts)
        assert (got == exp).all()


def test_partition_histogram_all_dead_rows():
    pids = jnp.zeros(64, jnp.int32)
    mask = jnp.zeros(64, bool)
    got = np.asarray(partition_histogram(pids, mask, 4))
    assert (got == 0).all()


def test_dense_group_sum_matches_scatter():
    rng = np.random.default_rng(1)
    cap, groups = 4096, 300
    keys = rng.integers(0, groups, cap)
    vals = rng.random(cap).astype(np.float32)
    mask = rng.random(cap) < 0.9
    got = np.asarray(dense_group_sum_f32(
        jnp.asarray(keys, jnp.int32), jnp.asarray(vals),
        jnp.asarray(mask), groups))
    exp = np.zeros(groups, np.float64)
    np.add.at(exp, keys[mask], vals[mask])
    assert np.abs(got - exp).max() < 1e-3


def test_dense_group_sum_non_multiple_block():
    # capacity not a multiple of the block: padding rows must not leak
    keys = jnp.asarray(np.arange(10) % 3, jnp.int32)
    vals = jnp.ones(10, jnp.float32)
    mask = jnp.ones(10, bool)
    got = np.asarray(dense_group_sum_f32(keys, vals, mask, 3))
    assert got.tolist() == [4.0, 3.0, 3.0]
