"""TPC-DS query-shape tests against a pandas oracle
(reference: TPCDSQuerySuite / TPCDSQueryTestSuite, SURVEY.md §4)."""

import numpy as np
import pandas as pd
import pytest

from tpcds_mini import gen_tpcds, register_tpcds


@pytest.fixture(scope="module")
def tpcds(spark):
    tables = register_tpcds(spark)
    return {k: v.to_pandas() for k, v in tables.items()}


def _df(spark, sql):
    return spark.sql(sql).toPandas()


def _assert_frames(got: pd.DataFrame, want: pd.DataFrame, sort_by=None):
    if sort_by:
        got = got.sort_values(sort_by).reset_index(drop=True)
        want = want.sort_values(sort_by).reset_index(drop=True)
    assert list(got.columns) == list(want.columns)
    assert len(got) == len(want), f"{len(got)} vs {len(want)} rows"
    for c in got.columns:
        g = got[c].to_numpy()
        w = want[c].to_numpy()
        if np.issubdtype(np.asarray(w).dtype, np.floating):
            np.testing.assert_allclose(
                g.astype(float), w.astype(float), rtol=1e-9, atol=1e-9)
        else:
            assert list(g) == list(w), f"column {c} differs"


def test_q3_shape(spark, tpcds):
    """TPC-DS q3: scan→join→join→agg→sort (BASELINE config #4 shape)."""
    got = _df(spark, """
        SELECT dt.d_year, item.i_brand_id AS brand_id, item.i_brand AS brand,
               SUM(ss_ext_sales_price) AS sum_agg
        FROM date_dim dt, store_sales, item
        WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
          AND store_sales.ss_item_sk = item.i_item_sk
          AND item.i_manufact_id = 28
          AND dt.d_moy = 11
        GROUP BY dt.d_year, item.i_brand_id, item.i_brand
        ORDER BY dt.d_year, sum_agg DESC, brand_id
        LIMIT 100""")

    ss, dd, it = tpcds["store_sales"], tpcds["date_dim"], tpcds["item"]
    j = ss.merge(dd[dd.d_moy == 11], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    j = j.merge(it[it.i_manufact_id == 28], left_on="ss_item_sk",
                right_on="i_item_sk")
    want = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum()
            .rename(columns={"ss_ext_sales_price": "sum_agg",
                             "i_brand_id": "brand_id", "i_brand": "brand"})
            .sort_values(["d_year", "sum_agg", "brand_id"],
                         ascending=[True, False, True]).head(100)
            .reset_index(drop=True))
    _assert_frames(got, want[got.columns.tolist()],
                   sort_by=["d_year", "brand_id", "brand"])


def test_q7_shape_multi_join(spark, tpcds):
    got = _df(spark, """
        SELECT i.i_category, AVG(ss_quantity) AS agg1,
               AVG(ss_sales_price) AS agg2, COUNT(*) AS cnt
        FROM store_sales ss
        JOIN item i ON ss.ss_item_sk = i.i_item_sk
        JOIN date_dim d ON ss.ss_sold_date_sk = d.d_date_sk
        WHERE d.d_year = 1999
        GROUP BY i.i_category
        ORDER BY i.i_category""")

    ss, dd, it = tpcds["store_sales"], tpcds["date_dim"], tpcds["item"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk") \
          .merge(dd[dd.d_year == 1999], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    want = (j.groupby("i_category", as_index=False)
            .agg(agg1=("ss_quantity", "mean"),
                 agg2=("ss_sales_price", "mean"),
                 cnt=("ss_quantity", "size"))
            .sort_values("i_category").reset_index(drop=True))
    _assert_frames(got, want, sort_by=["i_category"])


def test_q19_shape_store_filter(spark, tpcds):
    got = _df(spark, """
        SELECT s.s_state, i.i_brand AS brand,
               SUM(ss.ss_ext_sales_price) AS ext_price
        FROM store_sales ss, item i, store s, date_dim d
        WHERE d.d_date_sk = ss.ss_sold_date_sk
          AND ss.ss_item_sk = i.i_item_sk
          AND ss.ss_store_sk = s.s_store_sk
          AND d.d_moy = 12 AND d.d_year = 1998
          AND i.i_category = 'Books'
        GROUP BY s.s_state, i.i_brand
        ORDER BY ext_price DESC, brand
        LIMIT 50""")

    ss, dd = tpcds["store_sales"], tpcds["date_dim"]
    it, st = tpcds["item"], tpcds["store"]
    j = (ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 1998)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(it[it.i_category == "Books"], left_on="ss_item_sk",
                right_on="i_item_sk")
         .merge(st, left_on="ss_store_sk", right_on="s_store_sk"))
    want = (j.groupby(["s_state", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum()
            .rename(columns={"ss_ext_sales_price": "ext_price",
                             "i_brand": "brand"})
            .sort_values(["ext_price", "brand"], ascending=[False, True])
            .head(50).reset_index(drop=True))
    _assert_frames(got[["s_state", "brand", "ext_price"]],
                   want[["s_state", "brand", "ext_price"]],
                   sort_by=["s_state", "brand"])


def test_q1_shape_correlated_scalar(spark, tpcds):
    """TPC-DS q1 core: customers whose returns exceed 1.2x their store avg —
    modeled over store_sales net profit."""
    got = _df(spark, """
        SELECT ss_customer_sk FROM store_sales s1
        WHERE ss_net_profit > (
            SELECT 1.2 * avg(ss_net_profit) FROM store_sales s2
            WHERE s2.ss_store_sk = s1.ss_store_sk)
        GROUP BY ss_customer_sk
        ORDER BY ss_customer_sk""")

    ss = tpcds["store_sales"]
    avg_per_store = ss.groupby("ss_store_sk")["ss_net_profit"] \
        .transform("mean")
    want = sorted(ss[ss.ss_net_profit > 1.2 * avg_per_store]
                  ["ss_customer_sk"].unique())
    assert got["ss_customer_sk"].tolist() == [int(x) for x in want]


def test_q42_shape_date_rollup(spark, tpcds):
    got = _df(spark, """
        SELECT d.d_year, i.i_category, SUM(ss_ext_sales_price) AS total
        FROM store_sales ss, date_dim d, item i
        WHERE ss.ss_sold_date_sk = d.d_date_sk
          AND ss.ss_item_sk = i.i_item_sk
          AND d.d_moy = 11
        GROUP BY d.d_year, i.i_category
        ORDER BY total DESC, d.d_year, i.i_category""")
    ss, dd, it = tpcds["store_sales"], tpcds["date_dim"], tpcds["item"]
    j = ss.merge(dd[dd.d_moy == 11], left_on="ss_sold_date_sk",
                 right_on="d_date_sk") \
          .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    want = (j.groupby(["d_year", "i_category"], as_index=False)
            ["ss_ext_sales_price"].sum()
            .rename(columns={"ss_ext_sales_price": "total"}))
    _assert_frames(got, want[got.columns.tolist()],
                   sort_by=["d_year", "i_category"])


def test_window_rank_by_store(spark, tpcds):
    """q44-style: rank items by revenue within store — window directly over
    the grouped SELECT."""
    got = _df(spark, """
        SELECT * FROM (
          SELECT ss_store_sk, ss_item_sk, SUM(ss_ext_sales_price) AS rev,
                 rank() OVER (PARTITION BY ss_store_sk
                              ORDER BY SUM(ss_ext_sales_price) DESC) AS rnk
          FROM store_sales GROUP BY ss_store_sk, ss_item_sk
        ) t WHERE rnk <= 3
        ORDER BY ss_store_sk, rnk, ss_item_sk""")

    ss = tpcds["store_sales"]
    rev = (ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .rename(columns={"ss_ext_sales_price": "rev"}))
    rev["rnk"] = rev.groupby("ss_store_sk")["rev"] \
        .rank(method="min", ascending=False).astype(int)
    want = (rev[rev.rnk <= 3]
            .sort_values(["ss_store_sk", "rnk", "ss_item_sk"])
            .reset_index(drop=True))
    _assert_frames(got, want[got.columns.tolist()],
                   sort_by=["ss_store_sk", "rnk", "ss_item_sk"])


def test_in_subquery_semi(spark, tpcds):
    got = _df(spark, """
        SELECT count(*) AS c FROM store_sales
        WHERE ss_item_sk IN (SELECT i_item_sk FROM item
                             WHERE i_category = 'Music')""")
    ss, it = tpcds["store_sales"], tpcds["item"]
    music = set(it[it.i_category == "Music"].i_item_sk)
    want = int((ss.ss_item_sk.isin(music)).sum())
    assert got["c"].tolist() == [want]


def test_q52_q55_brand_by_month(spark, tpcds):
    got = _df(spark, """
        SELECT d.d_year, i.i_brand_id AS brand_id, i.i_brand AS brand,
               SUM(ss_ext_sales_price) AS ext_price
        FROM date_dim d, store_sales ss, item i
        WHERE d.d_date_sk = ss.ss_sold_date_sk
          AND ss.ss_item_sk = i.i_item_sk
          AND i.i_manufact_id = 13 AND d.d_moy = 11 AND d.d_year = 1999
        GROUP BY d.d_year, i.i_brand_id, i.i_brand
        ORDER BY d.d_year, ext_price DESC, brand_id""")
    ss, dd, it = tpcds["store_sales"], tpcds["date_dim"], tpcds["item"]
    j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(it[it.i_manufact_id == 13], left_on="ss_item_sk",
                right_on="i_item_sk"))
    want = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum()
            .rename(columns={"ss_ext_sales_price": "ext_price",
                             "i_brand_id": "brand_id", "i_brand": "brand"}))
    _assert_frames(got, want[got.columns.tolist()],
                   sort_by=["brand_id", "brand"])


def test_q32_shape_interval_window(spark, tpcds):
    """q32 core: sales within 90 days of a start date, vs 1.3x average."""
    got = _df(spark, """
        SELECT SUM(ss_ext_discount_amt) AS excess
        FROM store_sales ss, date_dim d, item i
        WHERE d.d_date_sk = ss.ss_sold_date_sk
          AND ss.ss_item_sk = i.i_item_sk
          AND i.i_manufact_id = 7
          AND d.d_date BETWEEN DATE '1999-01-01'
                           AND DATE '1999-01-01' + INTERVAL 90 DAYS
          AND ss.ss_ext_discount_amt > (
              SELECT 1.3 * avg(ss_ext_discount_amt)
              FROM store_sales s2, date_dim d2
              WHERE s2.ss_item_sk = ss.ss_item_sk
                AND d2.d_date_sk = s2.ss_sold_date_sk
                AND d2.d_date BETWEEN DATE '1999-01-01'
                                  AND DATE '1999-01-01' + INTERVAL 90 DAYS)""")

    import datetime

    ss, dd, it = tpcds["store_sales"], tpcds["date_dim"], tpcds["item"]
    lo = datetime.date(1999, 1, 1)
    hi = datetime.date(1999, 4, 1)  # +90 days
    dwin = dd[(dd.d_date >= lo) & (dd.d_date <= hi)]
    j = ss.merge(dwin, left_on="ss_sold_date_sk", right_on="d_date_sk")
    avg_per_item = j.groupby("ss_item_sk")["ss_ext_discount_amt"] \
        .transform("mean")
    jj = j[j.ss_ext_discount_amt > 1.3 * avg_per_item]
    jj = jj.merge(it[it.i_manufact_id == 7], left_on="ss_item_sk",
                  right_on="i_item_sk")
    want = jj.ss_ext_discount_amt.sum()
    got_v = got["excess"][0]
    if want == 0:
        assert got_v is None or abs(got_v) < 1e-9
    else:
        assert abs(got_v - want) < 1e-6


def test_q65_shape_min_avg_revenue(spark, tpcds):
    """q65 core: items whose store revenue is at most 10% above the store's
    minimum item revenue."""
    got = _df(spark, """
        WITH sa AS (
            SELECT ss_store_sk, ss_item_sk, SUM(ss_sales_price) AS revenue
            FROM store_sales GROUP BY ss_store_sk, ss_item_sk),
        sb AS (
            SELECT ss_store_sk, MIN(revenue) AS minrev
            FROM sa GROUP BY ss_store_sk)
        SELECT sa.ss_store_sk, count(*) AS near_min
        FROM sa JOIN sb ON sa.ss_store_sk = sb.ss_store_sk
        WHERE sa.revenue <= 1.1 * sb.minrev
        GROUP BY sa.ss_store_sk ORDER BY sa.ss_store_sk""")

    ss = tpcds["store_sales"]
    sa = (ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
          ["ss_sales_price"].sum()
          .rename(columns={"ss_sales_price": "revenue"}))
    sb = sa.groupby("ss_store_sk", as_index=False)["revenue"].min() \
        .rename(columns={"revenue": "minrev"})
    j = sa.merge(sb, on="ss_store_sk")
    want = (j[j.revenue <= 1.1 * j.minrev]
            .groupby("ss_store_sk", as_index=False).size()
            .rename(columns={"size": "near_min"})
            .sort_values("ss_store_sk").reset_index(drop=True))
    assert got["ss_store_sk"].tolist() == want["ss_store_sk"].tolist()
    assert got["near_min"].tolist() == want["near_min"].tolist()
