CREATE OR REPLACE TEMP VIEW aei AS SELECT 1 v WHERE 1 = 0;
SELECT count(*) c, count(v) cv FROM aei;
SELECT sum(v) s, avg(v) a, min(v) mn, max(v) mx FROM aei;
SELECT count(*) c FROM aei GROUP BY v;
SELECT sum(v) s FROM aei HAVING count(*) > 0;
