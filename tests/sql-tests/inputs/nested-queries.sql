SELECT t.cat, t.n FROM (SELECT i_category AS cat, count(*) AS n FROM item GROUP BY i_category) t WHERE t.n > 30 ORDER BY t.cat;
SELECT outer_t.mx FROM (SELECT max(n) AS mx FROM (SELECT i_category, count(*) AS n FROM item GROUP BY i_category) inner_t) outer_t;
select i_category, COUNT(*) as N from item group by i_category order by i_category;
