SELECT i_item_sk FROM item ORDER BY i_item_sk LIMIT 5 OFFSET 10;
SELECT count(*) AS n FROM (SELECT i_item_sk FROM item LIMIT 50) t;
