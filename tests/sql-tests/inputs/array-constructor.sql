SELECT array(1, 2, 3) AS a, array('x', 'y') AS s, array() AS e;
SELECT size(array(1,2,3)) AS n, element_at(array(10,20,30), 2) AS el, element_at(array(10,20,30), -1) AS last_el;
SELECT array_contains(array(1,2), 2) AS c1, array_contains(array(1,2), 9) AS c2;
SELECT sort_array(array(3,1,2)) AS srt, array_distinct(array(1,2,1,3,2)) AS dst;
SELECT array_min(array(5,1,9)) AS mn, array_max(array(5,1,9)) AS mx;
SELECT flatten(array(array(1,2), array(3))) AS fl;
SELECT slice(array(1,2,3,4,5), 2, 3) AS sl, slice(array(1,2,3,4,5), -2, 2) AS sl2;
SELECT array_join(array('a','b','c'), '-') AS j1;
SELECT array_position(array('a','b'), 'b') AS p1, array_remove(array(1,2,1), 1) AS rm;
