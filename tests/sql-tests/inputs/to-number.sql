SELECT to_number('123', '999') AS n1, to_number('-12.34', '99.99') AS n2;
SELECT to_number('1,234', '9,999') AS grouped, to_number('$45.00', '$99.99') AS currency;
SELECT try_to_number('99', '999') AS ok, try_to_number('bogus', '999') AS bad;
SELECT try_to_number('12.345', '99.999') AS scaled;
