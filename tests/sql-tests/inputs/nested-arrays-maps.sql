SELECT array(array(1, 2), array(3)) AS aa;
SELECT flatten(array(array(1, 2), array(3))) AS flat;
SELECT size(array(array(1), array(2, 3))) AS outer_size;
SELECT element_at(array(array(10), array(20, 30)), 2) AS second_inner;
SELECT map_values(map('a', array(1, 2))) AS map_of_arrays;
SELECT transform(array(array(1,2), array(3)), x -> size(x)) AS sizes;
