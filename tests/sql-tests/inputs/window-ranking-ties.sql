CREATE OR REPLACE TEMP VIEW wrt AS SELECT 'a' k, 10 v UNION ALL SELECT 'a', 10 UNION ALL SELECT 'a', 20 UNION ALL SELECT 'b', 5;
SELECT k, v, rank() OVER (PARTITION BY k ORDER BY v) AS rnk, dense_rank() OVER (PARTITION BY k ORDER BY v) AS drnk, row_number() OVER (PARTITION BY k ORDER BY v) AS rn FROM wrt ORDER BY k, v, rn;
SELECT k, v, percent_rank() OVER (PARTITION BY k ORDER BY v) AS pr, cume_dist() OVER (PARTITION BY k ORDER BY v) AS cd FROM wrt ORDER BY k, v;
