CREATE OR REPLACE TEMP VIEW cin AS SELECT 1 AS MyCol, 'x' AS OTHER;
SELECT mycol, other FROM cin;
SELECT MYCOL + 1 AS bumped FROM cin;
SELECT t.MyCol FROM cin t WHERE T.mycol = 1;
