SELECT i_item_id FROM item ORDER BY i_current_price DESC, i_item_id LIMIT 5;
SELECT i_item_id FROM item ORDER BY i_current_price ASC NULLS FIRST LIMIT 3;
SELECT i_item_id, i_current_price FROM item ORDER BY 2 DESC, 1 LIMIT 3;
