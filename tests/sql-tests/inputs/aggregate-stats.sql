SELECT stddev(x) AS sd, variance(x) AS v, stddev_pop(x) AS sp, var_pop(x) AS vp FROM (SELECT 2 AS x UNION ALL SELECT 4 UNION ALL SELECT 6);
SELECT skewness(x) AS sk, kurtosis(x) AS ku FROM (SELECT 1 AS x UNION ALL SELECT 2 UNION ALL SELECT 3 UNION ALL SELECT 10);
SELECT corr(x, y) AS c, covar_samp(x, y) AS cs, covar_pop(x, y) AS cp FROM (SELECT 1 AS x, 2 AS y UNION ALL SELECT 2, 4 UNION ALL SELECT 3, 6);
SELECT percentile(x, 0.5) AS p50, median(x) AS med FROM (SELECT 1 AS x UNION ALL SELECT 3 UNION ALL SELECT 5 UNION ALL SELECT 100);
SELECT any_value(x) AS av, approx_count_distinct(x) AS acd FROM (SELECT 7 AS x UNION ALL SELECT 7 UNION ALL SELECT 8);
