SELECT count(*) AS n FROM item WHERE i_item_sk IN (SELECT ss_item_sk FROM store_sales WHERE ss_quantity > 18);
SELECT count(*) AS n FROM item WHERE i_item_sk NOT IN (SELECT ss_item_sk FROM store_sales);
SELECT count(*) AS n FROM item i WHERE EXISTS (SELECT 1 FROM store_sales WHERE ss_item_sk = i.i_item_sk AND ss_quantity = 19);
SELECT count(*) AS n FROM item i WHERE NOT EXISTS (SELECT 1 FROM store_sales WHERE ss_item_sk = i.i_item_sk);
