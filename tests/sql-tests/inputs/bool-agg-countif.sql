CREATE OR REPLACE TEMP VIEW bac AS SELECT 1 g, true b, 5 v UNION ALL SELECT 1, false, 10 UNION ALL SELECT 2, true, 1 UNION ALL SELECT 2, true, 2;
SELECT g, bool_and(b) AS ba, bool_or(b) AS bo, every(b) AS ev, any(b) AS an FROM bac GROUP BY g ORDER BY g;
SELECT g, count_if(v > 1) AS ci FROM bac GROUP BY g ORDER BY g;
SELECT count_if(v > 100) AS ci_zero FROM bac;
