SELECT cast('42' as int) AS i1, cast('  42  ' as int) AS i_trim, cast('4.9' as int) AS i_trunc;
SELECT cast('abc' as int) AS i_bad;
SELECT cast('true' as boolean) AS b1, cast('0' as boolean) AS b2, cast('yes' as boolean) AS b3;
SELECT cast(1.99 as int) AS trunc1, cast(-1.99 as int) AS trunc2;
SELECT cast(true as int) AS b2i, cast(0 as boolean) AS i2b;
SELECT cast('2020-06-01' as date) AS d1, cast('2020-06-01 12:30:00' as timestamp) AS ts1;
SELECT cast(3.14159 as decimal(5, 2)) AS dec1, cast('12.345' as double) AS dbl1;
