CREATE OR REPLACE TEMP VIEW pca AS SELECT 1.0 v UNION ALL SELECT 2.0 UNION ALL SELECT 3.0 UNION ALL SELECT 4.0 UNION ALL SELECT 100.0;
SELECT percentile(v, 0.5) AS p50, median(v) AS med FROM pca;
SELECT approx_count_distinct(v) AS acd FROM pca;
SELECT percentile_approx(v, 0.5) AS pa50 FROM pca;
