SELECT filter(array(1, null, 3), x -> x > 1) AS f_keeps_matching;
SELECT transform(array(1, null), x -> coalesce(x, -1)) AS t_null_elem;
SELECT aggregate(array(1, null, 3), 0, (a, x) -> a + coalesce(x, 0)) AS agg_null_elem;
SELECT exists(array(cast(null as int)), x -> x = 1) AS exists_only_null;
SELECT forall(array(cast(null as int)), x -> x = 1) AS forall_only_null;
