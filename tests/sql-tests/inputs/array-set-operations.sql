SELECT array_union(array(1, 2), array(2, 3)) AS un, array_intersect(array(1, 2, 3), array(2, 3, 4)) AS inter;
SELECT array_except(array(1, 2, 3), array(2)) AS ex;
SELECT arrays_overlap(array(1, 2), array(2, 3)) AS ov_t, arrays_overlap(array(1), array(9)) AS ov_f;
SELECT array_union(array(1, 1, 2), array(2, 2)) AS dedup;
SELECT array_append(array(1, 2), 3) AS app, array_prepend(array(2, 3), 1) AS prep;
SELECT array_insert(array(1, 3), 2, 2) AS ins;
SELECT array_compact(array(1, null, 2, null)) AS compacted;
