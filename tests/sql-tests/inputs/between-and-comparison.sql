SELECT 5 BETWEEN 1 AND 10 AS b1, 0 BETWEEN 1 AND 10 AS b2, 5 NOT BETWEEN 1 AND 10 AS nb;
SELECT 'm' BETWEEN 'a' AND 'z' AS str_between;
SELECT cast(null as int) BETWEEN 1 AND 10 AS null_between;
SELECT date '2020-06-15' BETWEEN date '2020-01-01' AND date '2020-12-31' AS date_between;
