SELECT i_item_id, row_number() OVER (ORDER BY i_current_price DESC, i_item_id) AS rn FROM item ORDER BY rn LIMIT 5;
SELECT i_category, i_item_id, rank() OVER (PARTITION BY i_category ORDER BY i_current_price DESC) AS r FROM item ORDER BY i_category, r LIMIT 10;
SELECT i_item_id, i_current_price, sum(i_current_price) OVER (ORDER BY i_item_sk ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS rs FROM item ORDER BY i_item_sk LIMIT 5;
SELECT i_item_id, lag(i_current_price) OVER (ORDER BY i_item_sk) AS lg, lead(i_current_price) OVER (ORDER BY i_item_sk) AS ld FROM item ORDER BY i_item_sk LIMIT 5;
SELECT i_category, avg(i_current_price) OVER (PARTITION BY i_category) AS ca FROM item ORDER BY i_category, ca LIMIT 8;
SELECT i_item_id, ntile(4) OVER (ORDER BY i_current_price) AS q FROM item ORDER BY i_current_price LIMIT 8;
SELECT i_item_id, percent_rank() OVER (ORDER BY i_current_price) AS pr, cume_dist() OVER (ORDER BY i_current_price) AS cd FROM item ORDER BY i_current_price LIMIT 5;
