SELECT d_day_name, count(*) AS n FROM date_dim GROUP BY d_day_name ORDER BY n DESC, d_day_name LIMIT 3;
SELECT d_year, d_moy FROM date_dim WHERE d_dom = 1 ORDER BY d_year, d_moy LIMIT 5 OFFSET 2
