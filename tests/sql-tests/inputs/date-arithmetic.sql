SELECT date_add(DATE '2024-01-30', 5) AS a, date_sub(DATE '2024-01-05', 10) AS b, datediff(DATE '2024-03-01', DATE '2024-02-01') AS c;
SELECT add_months(DATE '2024-01-31', 1) AS a, months_between(DATE '2024-03-31', DATE '2024-01-31') AS b, last_day(DATE '2024-02-05') AS c;
SELECT trunc(DATE '2024-07-17', 'MM') AS m, trunc(DATE '2024-07-17', 'YEAR') AS y, quarter(DATE '2024-07-17') AS q;
SELECT year(DATE '2021-12-31') AS y, month(DATE '2021-12-31') AS mo, day(DATE '2021-12-31') AS d, dayofweek(DATE '2021-12-31') AS dw, dayofyear(DATE '2021-12-31') AS dy, weekofyear(DATE '2021-12-31') AS wk;
SELECT make_date(2020, 2, 29) AS leap, to_date('2023-06-15') AS td;
