SELECT count(*) AS n, sum(ss_quantity) AS sq, min(ss_quantity) AS mn, max(ss_quantity) AS mx FROM store_sales;
SELECT ss_store_sk, count(*) AS n FROM store_sales GROUP BY ss_store_sk ORDER BY ss_store_sk;
SELECT count(DISTINCT ss_store_sk) AS stores FROM store_sales
