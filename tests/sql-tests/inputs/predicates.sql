SELECT 'hello' LIKE 'h%' AND 'hello' LIKE '%o' AS both;
SELECT 3 IN (1, 2, 3) a, 5 IN (1, 2) b, NULL IN (1, 2) n, 1 NOT IN (2, 3) nn;
SELECT 5 BETWEEN 1 AND 10 a, 0 BETWEEN 1 AND 10 b, 5 NOT BETWEEN 1 AND 10 c;
SELECT count(*) FROM store_sales WHERE ss_quantity >= 5 AND ss_quantity <= 10 AND ss_sales_price > 50;
