SELECT col1, col2 FROM (VALUES (1, 'a'), (2, 'b'), (3, 'c')) t WHERE col1 > 1 ORDER BY col1;
SELECT col1 * 2 AS d FROM (VALUES (1), (2)) v ORDER BY d;
