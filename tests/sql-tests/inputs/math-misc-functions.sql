SELECT hypot(3.0, 4.0) AS hy, factorial(6) AS fact;
SELECT bit_count(255) AS bc1, bit_count(0) AS bc0;
SELECT width_bucket(5.3, 0, 10, 5) AS wb1, width_bucket(-1, 0, 10, 5) AS wb_under, width_bucket(11, 0, 10, 5) AS wb_over;
SELECT log2(8.0) AS l2, log10(1000.0) AS l10, ln(e()) AS lne;
SELECT round(pi(), 4) AS pi4;
