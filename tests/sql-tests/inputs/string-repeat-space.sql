SELECT repeat('ab', 0) AS r0, repeat('x', 5) AS r5;
SELECT length(concat(repeat(' ', 3), 'x')) AS padded_len;
SELECT reverse('') AS rev_empty, reverse('ab c') AS rev;
SELECT substring('hello', 2, 3) AS sub, substring('hello', -3, 2) AS sub_neg;
SELECT left('spark', 10) AS left_over, right('spark', 2) AS r2;
