SELECT min(i_item_id) mn, max(i_item_id) mx FROM item;
SELECT min(d_date) mn, max(d_date) mx FROM date_dim;
