SELECT try_cast('42' AS int) AS ok_int, try_cast('abc' AS int) AS bad_int;
SELECT try_cast('3.99' AS double) AS ok_dbl, try_cast('x' AS double) AS bad_dbl;
SELECT try_cast('2020-01-15' AS date) AS ok_date;
SELECT try_cast('true' AS boolean) AS ok_bool;
SELECT typeof(1) AS t_int, typeof('s') AS t_str, typeof(1.5) AS t_dbl, typeof(array(1)) AS t_arr;
