SELECT CASE WHEN 1 > 2 THEN 'a' WHEN 2 > 1 THEN 'b' ELSE 'c' END AS c1;
SELECT CASE 3 WHEN 1 THEN 'one' WHEN 3 THEN 'three' ELSE 'other' END AS c2;
SELECT if(1 < 2, 'yes', 'no') i, if(1 > 2, 'yes', 'no') i2;
