SELECT date_part('year', date '2020-08-15') AS y, date_part('month', date '2020-08-15') AS m, date_part('day', date '2020-08-15') AS d;
SELECT date_part('hour', timestamp '2020-08-15 13:20:45') AS h, date_part('minute', timestamp '2020-08-15 13:20:45') AS mi;
SELECT make_timestamp(2021, 3, 14, 15, 9, 26.5) AS mts;
SELECT unix_date(date '1970-01-10') AS ud, unix_date(date '1969-12-31') AS ud_neg;
SELECT date_format(date '2020-06-01', 'yyyy/MM/dd') AS df;
