SELECT abs(-5) AS a, round(3.14159, 2) AS r, upper('hello') AS u, length('spark') AS l, coalesce(NULL, 7) AS c;
SELECT CASE WHEN 1 < 2 THEN 'yes' ELSE 'no' END AS answer, 10 % 3 AS m, cast('2020-05-17' AS date) AS d;
SELECT year(DATE '2021-06-15') AS y, quarter(DATE '2021-06-15') AS q, datediff(DATE '2021-01-10', DATE '2021-01-01') AS dd
