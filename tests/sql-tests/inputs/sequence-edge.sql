SELECT sequence(3, 3) AS single;
SELECT sequence(-2, 2) AS crossing_zero;
SELECT sequence(10, 4, -3) AS neg_step;
SELECT size(sequence(0, 999)) AS thousand;
