SELECT 1 / 0 AS div0, 0.0 / 0.0 AS nan_div, -0.0 AS negzero;
SELECT cast('inf' AS double) inf, cast('-inf' AS double) ninf, cast('nan' AS double) nan;
SELECT 9223372036854775807 AS maxlong, -9223372036854775808 AS minlong;
SELECT round(2.675, 2) AS banker, round(123456.789, -2) AS negscale;
SELECT cast('true' AS boolean) t, cast('false' AS boolean) f, cast('yes' AS boolean) y, cast(1 AS boolean) one;
