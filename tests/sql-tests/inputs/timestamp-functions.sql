SELECT to_timestamp('2024-03-15 12:34:56') AS ts, hour(TIMESTAMP '2024-03-15 12:34:56') AS h, minute(TIMESTAMP '2024-03-15 12:34:56') AS m, second(TIMESTAMP '2024-03-15 12:34:56') AS s;
SELECT date_format(TIMESTAMP '2024-03-15 12:34:56', 'yyyy/MM/dd') AS f1, date_format(DATE '2024-03-15', 'MM-dd-yyyy') AS f2;
SELECT unix_timestamp(TIMESTAMP '1970-01-02 00:00:00') AS u, from_unixtime(86400) AS ft;
SELECT date_trunc('day', TIMESTAMP '2024-03-15 12:34:56') AS td, date_trunc('month', TIMESTAMP '2024-03-15 12:34:56') AS tm;
