SELECT get_json_object('{"a": 1}', '$.a') AS hit, get_json_object('{"a": 1}', '$.b') AS miss;
SELECT get_json_object('{"a": null}', '$.a') AS json_null;
SELECT get_json_object('{"a": {"b": 2}}', '$.a.b') AS nested, get_json_object('{"a": {"b": 2}}', '$.a') AS obj;
SELECT get_json_object('{"arr": [1, 2, 3]}', '$.arr[1]') AS idx, get_json_object('{"arr": [1]}', '$.arr[5]') AS oob;
SELECT get_json_object('not json', '$.a') AS badjson;
SELECT get_json_object('{"b": true}', '$.b') AS boolval;
