SELECT add_months(date '2020-01-31', 1) AS am1, add_months(date '2020-03-31', -1) AS am2;
SELECT last_day(date '2020-02-10') AS ld_leap, last_day(date '2021-02-10') AS ld;
SELECT months_between(date '2020-03-31', date '2020-02-29') AS mb;
SELECT datediff(date '2020-06-10', date '2020-06-01') AS dd;
SELECT date_add(date '2019-12-30', 5) AS da, date_sub(date '2020-01-03', 5) AS ds;
SELECT dayofweek(date '2020-06-01') AS dow, dayofyear(date '2020-12-31') AS doy, weekofyear(date '2020-01-01') AS woy;
SELECT trunc(date '2020-06-17', 'MM') AS t_month, trunc(date '2020-06-17', 'YEAR') AS t_year;
