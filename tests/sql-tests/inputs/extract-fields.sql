SELECT extract(year FROM date '2020-06-15') AS y, extract(month FROM date '2020-06-15') AS m, extract(day FROM date '2020-06-15') AS d;
SELECT extract(hour FROM timestamp '2020-06-15 13:45:30') AS h, extract(minute FROM timestamp '2020-06-15 13:45:30') AS mi, extract(second FROM timestamp '2020-06-15 13:45:30') AS s;
SELECT year(date '2019-02-03') AS yr, quarter(date '2019-08-03') AS q;
SELECT hour(timestamp '2020-01-01 23:59:59') AS hh, minute(timestamp '2020-01-01 23:59:59') AS mm;
