SELECT id, tags['x'] AS x, element_at(tags, 'y') AS y FROM nested ORDER BY id;
SELECT id, map_keys(tags) AS mk, map_values(tags) AS mv, size(tags) AS sz FROM nested ORDER BY id;
SELECT id, map_contains_key(tags, 'y') AS has_y FROM nested ORDER BY id;
SELECT map('a', 1, 'b', 2) AS m;
SELECT id, explode(map_keys(tags)) AS k FROM nested ORDER BY id, k;
SELECT id FROM nested WHERE tags['x'] = 9;
SELECT element_at(nums, 1) AS first_num, sort_array(nums) AS sorted FROM nested ORDER BY id;
