CREATE OR REPLACE TEMP VIEW cin_t AS SELECT 1 a, 1 k UNION ALL SELECT cast(null as int) a, 1 k UNION ALL SELECT 5 a, 1 k UNION ALL SELECT 1 a, 2 k UNION ALL SELECT 2 a, 3 k;
CREATE OR REPLACE TEMP VIEW cin_u AS SELECT 1 b, 1 ku UNION ALL SELECT cast(null as int) b, 1 ku UNION ALL SELECT 2 b, 2 ku;
SELECT a, k, a IN (SELECT b FROM cin_u WHERE ku = k) AS in_r FROM cin_t ORDER BY k, a NULLS FIRST;
SELECT a, k, a NOT IN (SELECT b FROM cin_u WHERE ku = k) AS notin_r FROM cin_t ORDER BY k, a NULLS FIRST;
SELECT count(*) AS semi_cnt FROM cin_t WHERE a IN (SELECT b FROM cin_u WHERE ku = k);
SELECT count(*) AS anti_cnt FROM cin_t WHERE a NOT IN (SELECT b FROM cin_u WHERE ku = k);
