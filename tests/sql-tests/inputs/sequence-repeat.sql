SELECT sequence(1, 5) AS asc_seq, sequence(5, 1) AS desc_seq, sequence(1, 10, 3) AS stepped;
SELECT array_repeat('ab', 3) AS rep_str, array_repeat(7, 2) AS rep_int;
SELECT size(sequence(1, 100)) AS n;
