SELECT i_category FROM item WHERE i_brand_id < 5 INTERSECT SELECT i_category FROM item WHERE i_brand_id > 20 ORDER BY i_category;
SELECT d_year, SUM(d_dom) AS s FROM date_dim WHERE d_date BETWEEN DATE '1998-02-01' AND DATE '1998-02-01' + INTERVAL 1 MONTH GROUP BY ROLLUP(d_year) ORDER BY d_year NULLS LAST
