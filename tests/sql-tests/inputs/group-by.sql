SELECT i_category, count(*) AS cnt, sum(i_current_price) AS total FROM item GROUP BY i_category ORDER BY i_category;
SELECT c_state, count(DISTINCT c_birth_year) AS dy FROM customer GROUP BY c_state ORDER BY c_state;
SELECT i_category, avg(i_current_price) AS ap, min(i_current_price) AS mn, max(i_current_price) AS mx FROM item GROUP BY i_category ORDER BY i_category;
SELECT i_brand_id % 5 AS g, count(*) AS n FROM item GROUP BY i_brand_id % 5 ORDER BY g;
SELECT count(*) AS n, sum(ss_quantity) AS q, avg(ss_sales_price) AS p FROM store_sales;
SELECT count(DISTINCT ss_store_sk) AS stores FROM store_sales;
