SELECT greatest(1, 2.5, 2) AS g_mixed, least(1, 2.5, 0.5) AS l_mixed;
SELECT greatest(date '2020-01-01', date '2021-06-01') AS g_date;
SELECT greatest('b', 'a', 'c') AS g_str, least('b', 'a', 'c') AS l_str;
