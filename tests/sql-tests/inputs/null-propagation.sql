SELECT 1 + cast(null as int) AS add_null, cast(null as int) * 2 AS mul_null;
SELECT abs(cast(null as int)) AS abs_null, upper(cast(null as string)) AS upper_null;
SELECT length(cast(null as string)) AS len_null, concat('a', cast(null as string)) AS concat_null;
SELECT cast(null as int) = 1 AS eq_null, cast(null as int) <=> 1 AS nse_false, cast(null as int) <=> cast(null as int) AS nse_true;
SELECT NOT cast(null as boolean) AS not_null;
SELECT cast(null as boolean) AND false AS and_false, cast(null as boolean) OR true AS or_true;
SELECT cast(null as boolean) AND true AS and_null, cast(null as boolean) OR false AS or_null;
