SELECT 'abc' = 'ABC' AS exact, upper('abc') = 'ABC' AS upper_eq;
SELECT 'a' < 'b' AS lt, 'abc' < 'abd' AS lt2, 'Z' < 'a' AS ascii_order;
SELECT initcap('wORLD of SQL') AS ic, lower('MiXeD') AS lo;
SELECT length('héllo') AS unicode_len, upper('héllo') AS unicode_upper;
