SELECT greatest(3, 9, 1) AS g, least(3, 9, 1) AS l, greatest('b', 'a', 'c') AS gs;
SELECT greatest(1, NULL, 3) AS gn, least(NULL, NULL) AS ln;
SELECT pmod(-7, 3) AS pm, mod(-7, 3) AS m, -7 % 3 AS pct;
