SELECT flatten(collect_list(nums)) AS f FROM nested WHERE nums IS NOT NULL;
SELECT slice(nums, 1, 2) AS s1, slice(nums, -2, 2) AS s2, array_remove(nums, 1) AS ar FROM nested WHERE id = 1;
SELECT array_join(nums, '-') AS aj, array_position(nums, 2) AS ap, array_position(nums, 99) AS missing FROM nested WHERE id = 1;
