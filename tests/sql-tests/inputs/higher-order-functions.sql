SELECT transform(array(1,2,3), x -> x * 2) AS t1, transform(array(10,20), (x, i) -> x + i) AS t2;
SELECT filter(array(1,2,3,4,5), x -> x % 2 = 1) AS f1, filter(array(1,2), x -> x > 10) AS f2;
SELECT aggregate(array(1,2,3,4), 0, (acc, x) -> acc + x) AS a1, aggregate(array(1,2,3), 1, (acc, x) -> acc * x, acc -> acc + 100) AS a2;
SELECT reduce(array(5,10), 0, (a, b) -> a + b) AS r1;
SELECT exists(array(1,2,3), x -> x > 2) AS e1, exists(array(1,2), x -> x > 9) AS e2, exists(array(1,null), x -> x > 9) AS e3;
SELECT forall(array(2,4,6), x -> x % 2 = 0) AS fa1, forall(array(2,3), x -> x % 2 = 0) AS fa2;
SELECT zip_with(array(1,2,3), array(10,20,30), (a, b) -> a + b) AS z1, zip_with(array(1), array(1,2), (a, b) -> coalesce(a, 0) + b) AS z2;
SELECT array_sort(array(3,1,2), (a, b) -> case when a < b then 1 when a > b then -1 else 0 end) AS desc_sorted;
SELECT transform(array(1,2), x -> transform(array(10), y -> y + x)) AS nested;
