CREATE OR REPLACE TEMP VIEW cla AS SELECT 1 g, 3 v UNION ALL SELECT 1, 1 UNION ALL SELECT 1, 3 UNION ALL SELECT 2, 7;
SELECT g, sort_array(collect_list(v)) AS lst FROM cla GROUP BY g ORDER BY g;
SELECT g, sort_array(collect_set(v)) AS st FROM cla GROUP BY g ORDER BY g;
SELECT g, first(v) AS f, any_value(v) AS av FROM (SELECT * FROM cla ORDER BY v) GROUP BY g ORDER BY g;
