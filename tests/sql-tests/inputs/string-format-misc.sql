SELECT format_string('%s has %d items', 'cart', 3) AS fs, printf('%05d', 42) AS pf;
SELECT chr(72) AS c1, char(101) AS c2;
SELECT elt(1, 'first', 'second') AS e1, elt(9, 'a', 'b') AS e_oob;
SELECT find_in_set('b', 'a,b,c') AS fis, find_in_set('z', 'a,b') AS fis_miss;
SELECT conv('ff', 16, 10) AS c16to10, conv('7', 10, 2) AS c10to2;
SELECT hex(255) AS hx, unhex('414243') AS uh, bin(10) AS bn;
