SELECT 'spark' LIKE 'sp%' AS l1, 'spark' LIKE '%ark' AS l2, 'spark' LIKE 's_ark' AS l3;
SELECT 'spark' LIKE 'SPARK' AS case_sensitive;
SELECT 'a_b' LIKE 'a\\_b' AS escaped_underscore;
SELECT 'x' LIKE '%' AS match_all, '' LIKE '%' AS empty_match;
SELECT startswith('spark', 'sp') AS sw, endswith('spark', 'rk') AS ew, contains('spark', 'par') AS ct;
SELECT 'spark' RLIKE 'a.k' AS rl;
