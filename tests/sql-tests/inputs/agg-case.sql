SELECT sum(CASE WHEN i_category = 'Books' THEN 1 ELSE 0 END) AS books, sum(CASE WHEN i_category = 'Music' THEN 1 ELSE 0 END) AS music FROM item;
SELECT CASE WHEN i_brand_id > 20 THEN NULL ELSE i_brand_id END AS k, count(*) AS n FROM item GROUP BY k ORDER BY k NULLS FIRST LIMIT 5;
