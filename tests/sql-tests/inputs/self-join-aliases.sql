CREATE OR REPLACE TEMP VIEW sja AS SELECT 1 id, 10 v UNION ALL SELECT 2, 20 UNION ALL SELECT 3, 30;
SELECT l.id, r.id AS rid FROM sja l JOIN sja r ON l.id = r.id - 1 ORDER BY l.id;
SELECT a.id FROM sja a JOIN sja b ON a.v = b.v WHERE a.id = b.id ORDER BY a.id;
SELECT count(*) AS pairs FROM sja x CROSS JOIN sja y;
