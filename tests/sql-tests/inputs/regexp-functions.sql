SELECT 'hello' LIKE 'he%' a, 'hello' LIKE '%lo' b, 'hello' LIKE 'h_llo' c, 'hello' NOT LIKE 'x%' d;
SELECT 'hello' RLIKE 'h.*o' a, regexp('foo123', '[a-z]+[0-9]+') r;
SELECT regexp_extract('100-200', '(\\d+)-(\\d+)', 1) e1, regexp_replace('100-200', '(\\d+)', 'num') rr;
