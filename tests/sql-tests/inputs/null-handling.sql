SELECT coalesce(NULL, NULL, 3) c, ifnull(NULL, 'x') i, nullif(5, 5) nf, nullif(5, 6) nf2, nvl(NULL, 9) nv;
SELECT isnull(NULL) a, isnotnull(NULL) b, isnan(cast('nan' AS double)) c, isnan(1.0) d;
SELECT NULL + 1 a, NULL = NULL b, NULL AND false c, NULL OR true d, concat('x', NULL) e;
