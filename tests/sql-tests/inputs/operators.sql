SELECT 5 + 3 a, 5 - 3 s, 5 * 3 m, 5 / 3 dv, 5 div 3 idv, -5 neg, +5 pos;
SELECT 1 < 2 lt, 2 <= 2 le, 3 > 2 gt, 3 >= 4 ge, 1 = 1 eq, 1 != 2 ne, 1 <> 2 ne2, NULL <=> NULL nss, 1 <=> NULL ns2;
SELECT true AND false a, true OR false o, NOT true n, true AND NULL an, false OR NULL onn;
