SELECT 1 AS i, 1L AS l, 1.5 AS d, 'str' AS s, true AS b, NULL AS n;
SELECT 0x1F AS hexlit, 1e3 AS sci, -2.5E-2 AS negsci, .5 AS leadingdot;
SELECT DATE '2019-01-01' AS dt, TIMESTAMP '2019-01-01 12:34:56' AS ts;
