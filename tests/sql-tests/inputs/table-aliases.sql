SELECT a.i_item_sk FROM item a JOIN item b ON a.i_item_sk = b.i_item_sk WHERE a.i_item_sk <= 3 ORDER BY a.i_item_sk;
SELECT t.i_item_id FROM (SELECT * FROM item WHERE i_current_price > 90) AS t ORDER BY t.i_item_id LIMIT 3;
