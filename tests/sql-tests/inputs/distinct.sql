SELECT DISTINCT i_category FROM item ORDER BY i_category;
SELECT DISTINCT i_category, i_brand_id % 2 AS parity FROM item ORDER BY i_category, parity LIMIT 8;
