SELECT 'spark' LIKE 's%' AS a, 'spark' LIKE '%ark' AS b, 'spark' LIKE '_park' AS c, 'spark' LIKE 'S%' AS d;
SELECT 'a_b' LIKE 'a\\_b' AS esc, '50%' LIKE '50\\%' AS esc2;
SELECT 'spark' RLIKE '^sp.*k$' AS r1, regexp('123abc', '[0-9]+') AS r2;
SELECT 'hello' NOT LIKE 'h%' AS nl;
