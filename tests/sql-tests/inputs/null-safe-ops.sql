SELECT 1 <=> 1 AS a, NULL <=> NULL AS b, 1 <=> NULL AS c;
SELECT nullif(3, 3) AS n1, nullif(3, 4) AS n2, nvl(NULL, 'd') AS n3, ifnull(NULL, 9) AS n4, if(1 > 2, 'yes', 'no') AS n5;
SELECT coalesce(NULL, NULL, 5, 7) AS c1, isnull(NULL) AS i1, isnotnull(0) AS i2, isnan(0.0 / 0.0) AS i3;
SELECT NULL AND false AS a1, NULL AND true AS a2, NULL OR true AS o1, NULL OR false AS o2, NOT NULL AS n;
