SELECT map('a', 1, 'b', 2) AS m;
SELECT element_at(map('a', 1), 'a') AS hit, element_at(map('a', 1), 'z') AS miss;
SELECT map_keys(map('a', 1, 'b', 2)) AS ks, map_values(map('a', 1, 'b', 2)) AS vs;
SELECT map_contains_key(map('a', 1), 'a') AS has_a, map_contains_key(map('a', 1), 'z') AS has_z;
SELECT size(map('a', 1, 'b', 2)) AS n;
