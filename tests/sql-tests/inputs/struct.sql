SELECT id, person.name, person.age FROM nested ORDER BY id;
SELECT id, person FROM nested WHERE person.age > 28 ORDER BY id;
SELECT named_struct('a', 1, 'b', 'two') AS ns;
SELECT struct(id, person.name) AS st FROM nested ORDER BY id;
SELECT person.name AS nm, count(*) AS n FROM nested GROUP BY person.name ORDER BY nm NULLS FIRST;
SELECT id FROM nested ORDER BY person.age NULLS LAST, id;
