SELECT i_category, count(*) AS n FROM item GROUP BY i_category HAVING count(*) > 30 ORDER BY i_category;
SELECT c_state, count(*) AS n FROM customer GROUP BY c_state HAVING n >= 100 ORDER BY c_state;
