SELECT named_struct('a', 1, 'b', 'x') AS st;
SELECT named_struct('a', 1, 'b', 'x').a AS field_a;
SELECT struct(1, 'two').col1 AS c1;
SELECT named_struct('outer', named_struct('inner', 42)).outer.inner AS deep;
