SELECT md5('spark') AS m, sha2('spark', 256) AS s2;
SELECT crc32('spark') AS crc;
SELECT base64('spark') AS b64, unbase64(base64('spark')) AS rt;
