SELECT timestamp '2020-01-01 00:00:00' + interval '2' day * 3 AS mul;
SELECT timestamp '2020-01-07 00:00:00' - interval '2' day * 3 AS mul_sub;
SELECT timestamp '2020-01-02 00:00:00' - interval '1' day / 2 AS div_half;
SELECT timestamp '2020-01-01 00:00:00' + (interval '1' day + interval '12' hour) AS iv_add;
SELECT timestamp '2020-01-03 00:00:00' + (interval '2' day - interval '1' day) AS iv_sub;
SELECT date '2020-01-31' + interval '1' month AS month_clamp;
SELECT date '2020-02-29' + interval '1' year AS year_clamp;
