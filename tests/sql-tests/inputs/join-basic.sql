SELECT count(*) AS n FROM store_sales ss JOIN item i ON ss.ss_item_sk = i.i_item_sk WHERE i.i_category = 'Books';
SELECT s_state, count(*) AS n FROM store_sales JOIN store ON ss_store_sk = s_store_sk GROUP BY s_state ORDER BY s_state;
SELECT count(*) AS n FROM item i LEFT JOIN store_sales ss ON i.i_item_sk = ss.ss_item_sk AND ss.ss_quantity > 18 WHERE ss.ss_item_sk IS NULL;
SELECT c_state, s_state, count(*) AS n FROM store_sales JOIN customer ON ss_customer_sk = c_customer_sk JOIN store ON ss_store_sk = s_store_sk WHERE c_state = 'CA' AND s_state IN ('CA','TX') GROUP BY c_state, s_state ORDER BY s_state;
SELECT count(*) AS n FROM store CROSS JOIN date_dim WHERE d_year = 1998;
SELECT count(*) AS n FROM item i RIGHT JOIN store_sales ss ON i.i_item_sk = ss.ss_item_sk;
SELECT count(*) AS n FROM store s FULL OUTER JOIN customer c ON s.s_state = c.c_state;
