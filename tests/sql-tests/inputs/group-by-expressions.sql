CREATE OR REPLACE TEMP VIEW gbe AS SELECT 1 v UNION ALL SELECT 2 UNION ALL SELECT 3 UNION ALL SELECT 4 UNION ALL SELECT 5;
SELECT v % 2 AS parity, count(*) c, sum(v) s FROM gbe GROUP BY v % 2 ORDER BY parity;
SELECT v % 2 AS parity, v % 3 AS m3, count(*) c FROM gbe GROUP BY v % 2, v % 3 ORDER BY parity, m3;
SELECT CASE WHEN v <= 2 THEN 'low' ELSE 'high' END AS bucket, count(*) c FROM gbe GROUP BY CASE WHEN v <= 2 THEN 'low' ELSE 'high' END ORDER BY bucket;
