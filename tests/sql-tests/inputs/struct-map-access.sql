SELECT s.a AS fa, s.b AS fb FROM (SELECT named_struct('a', 1, 'b', 'x') AS s);
SELECT map('k1', 1, 'k2', 2) AS m, map_keys(map('k1', 1)) AS mk, map_values(map('k1', 7)) AS mv;
SELECT element_at(map('a', 10, 'b', 20), 'b') AS ea, map_contains_key(map('a', 1), 'a') AS mc;
SELECT size(map('a', 1, 'b', 2)) AS sz, cardinality(map('a', 1)) AS card;
