SELECT c_state AS st FROM customer UNION SELECT s_state FROM store ORDER BY st;
SELECT c_state AS st FROM customer UNION ALL SELECT s_state FROM store ORDER BY st LIMIT 5;
SELECT c_state AS st FROM customer EXCEPT SELECT s_state FROM store ORDER BY st;
SELECT c_state AS st FROM customer INTERSECT SELECT s_state FROM store ORDER BY st;
SELECT c_state AS st FROM customer MINUS SELECT s_state FROM store ORDER BY st;
