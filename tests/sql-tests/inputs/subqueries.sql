SELECT count(*) AS above_avg FROM store_sales WHERE ss_ext_sales_price > (SELECT avg(ss_ext_sales_price) FROM store_sales);
SELECT count(*) AS music_sales FROM store_sales WHERE ss_item_sk IN (SELECT i_item_sk FROM item WHERE i_category = 'Music');
SELECT s_store_id FROM store s WHERE EXISTS (SELECT 1 FROM store_sales ss WHERE ss.ss_store_sk = s.s_store_sk AND ss.ss_quantity > 18) ORDER BY s_store_id LIMIT 3
