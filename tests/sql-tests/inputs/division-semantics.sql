SELECT 7 / 2 AS fdiv, 7 DIV 2 AS idiv, 7 % 2 AS rem;
SELECT 1 / 0 AS div0, 0.0 / 0.0 AS nan0, -1.0 / 0.0 AS ninf;
SELECT try_divide(10, 0) AS td, try_divide(10, 4) AS td2;
