SELECT get_json_object('{"a": {"b": [10, 20]}, "s": "x"}', '$.a.b[1]') AS j1, get_json_object('{"a": 1}', '$.missing') AS j2, get_json_object('{"a": {"c": 3}}', '$.a') AS j3;
SELECT crc32('spark') AS c1, crc32('') AS c2;
SELECT nanvl(0.0 / 0.0, 7.5) AS nv, nanvl(3.0, 9.9) AS nv2;
SELECT bround(2.5, 0) AS b1, bround(3.5, 0) AS b2, round(2.5, 0) AS r1, bround(1.25, 1) AS b3;
