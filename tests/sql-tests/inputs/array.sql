SELECT split('a,b,c', ',') AS arr, size(split('a,b', ',')) AS sz, cardinality(split('a', ',')) AS card;
SELECT array_contains(split('a,b,c', ','), 'b') AS c1, array_contains(split('a,b', ','), 'z') AS c2;
SELECT sort_array(split('c,a,b', ',')) AS sa, array_distinct(split('a,b,a', ',')) AS ad;
SELECT array_max(split('3,1,2', ',')) AS mx, array_min(split('3,1,2', ',')) AS mn, element_at(split('a,b,c', ','), 2) AS el;
