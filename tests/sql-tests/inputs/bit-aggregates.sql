CREATE OR REPLACE TEMP VIEW bitagg AS SELECT 1 g, 12 v UNION ALL SELECT 1, 10 UNION ALL SELECT 2, 5 UNION ALL SELECT 2, cast(null as int) UNION ALL SELECT 3, -1 UNION ALL SELECT 3, 6;
SELECT g, bit_and(v) AS ba, bit_or(v) AS bo, bit_xor(v) AS bx FROM bitagg GROUP BY g ORDER BY g;
SELECT bit_and(v) AS ba, bit_or(v) AS bo, bit_xor(v) AS bx FROM bitagg;
SELECT bit_and(v) AS null_and FROM bitagg WHERE v IS NULL;
SELECT g, mode(v) AS m FROM bitagg GROUP BY g ORDER BY g;
SELECT mode(v) AS overall_mode FROM bitagg;
