SELECT 1 = 1.0 AS int_dbl, '1' = 1 AS str_int_coerce;
SELECT 1 < 1.5 AS lt_mixed, 2 >= 2.0 AS ge_mixed;
SELECT cast(1 as bigint) = cast(1 as int) AS long_int;
SELECT date '2020-01-01' < timestamp '2020-01-01 00:00:01' AS date_ts;
