SELECT explode(split('x,y,z', ',')) AS v;
SELECT i_item_sk, explode(split('a,b', ',')) AS part FROM item WHERE i_item_sk <= 2 ORDER BY i_item_sk, part;
