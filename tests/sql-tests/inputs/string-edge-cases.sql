SELECT length('') e, trim('') t, upper('') u, substring('abc', 10) oob, substring('abc', 0, 2) zero;
SELECT repeat('x', 0) r0, lpad('abcdef', 3, '0') truncated, split('', ',') emptysplit;
SELECT concat_ws(',', 'a', NULL, 'b') skip_null, concat('') empty;
