SELECT 1 IN (1, 2, 3) AS in_t, 9 IN (1, 2, 3) AS in_f;
SELECT 9 IN (1, cast(null as int)) AS in_unknown;
SELECT 1 IN (1, cast(null as int)) AS in_match_with_null;
SELECT cast(null as int) IN (1, 2) AS null_probe;
SELECT 2 NOT IN (1, 3) AS notin_t, 2 NOT IN (1, cast(null as int)) AS notin_unknown;
