SELECT 9223372036854775807 AS max_long;
SELECT 1e308 * 10 AS dbl_inf, -1e308 * 10 AS dbl_ninf;
SELECT 0.1 + 0.2 AS point_three;
SELECT cast(2147483647 as bigint) + 1 AS widened;
