SELECT true AND true AS tt, true AND false AS tf, false AND false AS ff;
SELECT true OR false AS t_or_f, false OR false AS f_or_f;
SELECT (1 = cast(null as int)) AND false AS unknown_and_false;
SELECT (1 = cast(null as int)) OR true AS unknown_or_true;
SELECT NOT (1 = cast(null as int)) AS not_unknown;
SELECT (1 > 0) = (2 > 1) AS bool_eq;
