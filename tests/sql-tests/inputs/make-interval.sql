SELECT date '2020-01-01' + make_interval(1, 2, 0, 3, 0, 0, 0) AS mi;
SELECT timestamp '2020-01-01 00:00:00' + make_dt_interval(1, 2, 30, 45.5) AS dt;
SELECT date '2020-03-31' + make_ym_interval(0, 1) AS ym;
SELECT timestamp '2020-01-01 00:00:00' + make_interval(0, 0, 1, 0, 0, 0, 0) AS weeks;
