WITH top_items AS (SELECT i_item_sk, i_category FROM item WHERE i_current_price > 50) SELECT i_category, count(*) AS n FROM top_items GROUP BY i_category ORDER BY i_category;
WITH a AS (SELECT c_state, count(*) AS n FROM customer GROUP BY c_state), b AS (SELECT c_state, n FROM a WHERE n > 90) SELECT * FROM b ORDER BY c_state;
WITH x AS (SELECT 1 AS v), y AS (SELECT v + 1 AS w FROM x) SELECT * FROM y;
