SELECT 3 & 5 a, 3 | 5 o, 3 ^ 5 x, ~3 n, shiftleft(1, 4) sl, shiftright(16, 2) sr;
