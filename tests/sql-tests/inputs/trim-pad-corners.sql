SELECT trim('  x  ') AS t1, ltrim('  x  ') AS t2, rtrim('  x  ') AS t3;
SELECT length(trim('   ')) AS empty_trim;
SELECT lpad('abcdef', 3, '0') AS lpad_truncates, rpad('ab', 5, 'xy') AS rpad_pattern;
SELECT initcap('hello spark world') AS ic;
SELECT substring_index('a.b.c', '.', 2) AS si1, substring_index('a.b.c', '.', -1) AS si2;
