SELECT count(*) AS star, count(c_birth_year) AS nonnull, count(DISTINCT c_state) AS ds FROM customer;
SELECT count(*) FROM customer WHERE 1 = 0;
SELECT sum(ss_quantity) FROM store_sales WHERE 1 = 0;
