SELECT i_category, i_brand_id % 3 AS b, count(*) AS n FROM item GROUP BY ROLLUP(i_category, i_brand_id % 3) ORDER BY i_category NULLS FIRST, b NULLS FIRST;
SELECT c_state, count(*) AS n, grouping(c_state) AS g FROM customer GROUP BY CUBE(c_state) ORDER BY c_state NULLS LAST;
