SELECT cbrt(27.0) AS cb, expm1(0.0) AS em, log1p(0.0) AS lp, log2(8.0) AS l2, log(100.0) AS ln_, log10(1000.0) AS l10;
SELECT degrees(pi()) AS deg, radians(180.0) AS rad, e() AS e_, sign(-5) AS sg, signum(3.2) AS sgn;
SELECT sinh(0.0) AS sh, cosh(0.0) AS ch, tanh(0.0) AS th, atan2(1.0, 1.0) AS at2;
SELECT shiftleft(1, 4) AS sl, shiftright(256, 4) AS sr;
