SELECT lpad('7', 3, '0') AS l, rpad('ab', 5, 'xy') AS r, repeat('ab', 3) AS rep, reverse('spark') AS rev;
SELECT split('a,b,,c', ',') AS parts, substring_index('a.b.c.d', '.', 2) AS si, translate('abcabc', 'abc', 'xyz') AS tr;
SELECT initcap('hello world') AS ic, ascii('A') AS asc, instr('hello', 'll') AS ins, locate('l', 'hello', 4) AS loc, position('lo' IN 'hello') AS pos;
SELECT substr('abcdef', 2, 3) AS s1, substr('abcdef', -2) AS s2, left('abcdef', 2) AS lf, right('abcdef', 2) AS rt, overlay('abcdef', 'XX', 3) AS ov;
SELECT concat_ws('-', 'a', NULL, 'b') AS cw, length('héllo') AS len, char_length('abc') AS cl;
