CREATE OR REPLACE TEMP VIEW sv AS SELECT 2.0 v UNION ALL SELECT 4.0 UNION ALL SELECT 4.0 UNION ALL SELECT 6.0;
SELECT round(stddev(v), 6) AS sd, round(stddev_pop(v), 6) AS sdp, round(stddev_samp(v), 6) AS sds FROM sv;
SELECT round(variance(v), 6) AS var, round(var_pop(v), 6) AS varp, round(var_samp(v), 6) AS vars FROM sv;
SELECT percentile(v, 0.5) AS p50, median(v) AS med FROM sv;
