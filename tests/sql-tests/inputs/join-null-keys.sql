CREATE OR REPLACE TEMP VIEW jl AS SELECT 1 k, 'l1' lv UNION ALL SELECT cast(null as int), 'l2' UNION ALL SELECT 3, 'l3';
CREATE OR REPLACE TEMP VIEW jr AS SELECT 1 k, 'r1' rv UNION ALL SELECT cast(null as int), 'r2' UNION ALL SELECT 4, 'r4';
SELECT l.lv, r.rv FROM jl l JOIN jr r ON l.k = r.k ORDER BY l.lv;
SELECT l.lv, r.rv FROM jl l LEFT JOIN jr r ON l.k = r.k ORDER BY l.lv;
SELECT l.lv, r.rv FROM jl l FULL OUTER JOIN jr r ON l.k = r.k ORDER BY l.lv NULLS LAST, r.rv NULLS LAST;
SELECT l.lv FROM jl l LEFT SEMI JOIN jr r ON l.k = r.k ORDER BY l.lv;
SELECT l.lv FROM jl l LEFT ANTI JOIN jr r ON l.k = r.k ORDER BY l.lv;
SELECT l.lv, r.rv FROM jl l JOIN jr r ON l.k <=> r.k ORDER BY l.lv;
