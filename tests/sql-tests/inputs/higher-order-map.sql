SELECT transform_keys(map('a', 1, 'b', 2), (k, v) -> upper(k)) AS tk;
SELECT transform_values(map('a', 1, 'b', 2), (k, v) -> v * 10) AS tv;
SELECT map_filter(map('a', 1, 'b', 2, 'c', 3), (k, v) -> v >= 2) AS mf;
SELECT map_zip_with(map('a', 1, 'b', 2), map('b', 20, 'c', 30), (k, v1, v2) -> coalesce(v1, 0) + coalesce(v2, 0)) AS mz;
SELECT map_keys(transform_values(map('x', 1), (k, v) -> v + 1)) AS mk;
