SELECT try_add(2147483647, 1) ta, try_subtract(-2147483648, 1) ts, try_multiply(9223372036854775807, 2) tm, try_divide(1, 0) td;
SELECT try_add(1, 2) a, try_divide(10, 4) d;
