SELECT count(*) AS n FROM item WHERE i_current_price > (SELECT avg(i_current_price) FROM item);
SELECT i_category, (SELECT max(s_number_employees) FROM store) AS me FROM item GROUP BY i_category ORDER BY i_category;
SELECT s_store_sk, (SELECT count(*) FROM store_sales WHERE ss_store_sk = s_store_sk) AS sales FROM store ORDER BY s_store_sk;
