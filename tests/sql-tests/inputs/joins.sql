SELECT s_state, count(*) AS n FROM store_sales ss JOIN store s ON ss.ss_store_sk = s.s_store_sk GROUP BY s_state ORDER BY s_state;
SELECT count(*) AS missing FROM store_sales ss LEFT ANTI JOIN item i ON ss.ss_item_sk = i.i_item_sk
