SELECT CASE WHEN 1 = 1 THEN 'a' WHEN 1 = 1 THEN 'b' ELSE 'c' END AS first_wins;
SELECT CASE WHEN 1 = 2 THEN 'a' END AS no_else_null;
SELECT CASE WHEN cast(null as boolean) THEN 'x' ELSE 'y' END AS null_cond;
SELECT CASE 3 WHEN 1 THEN 'one' WHEN 3 THEN 'three' ELSE 'other' END AS simple_case;
SELECT CASE WHEN 1 > 0 THEN 1 ELSE 2.5 END AS widened;
