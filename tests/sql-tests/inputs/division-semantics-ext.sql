SELECT 7 / 2 AS true_div, 7 div 2 AS int_div, -7 div 2 AS int_div_neg;
SELECT 1 / 0 AS div_zero, 0.0 / 0.0 AS zero_over_zero;
SELECT 7 % 3 AS mod_pos, -7 % 3 AS mod_neg_dividend, 7 % -3 AS mod_neg_divisor;
SELECT try_divide(4, 2) AS td_ok, try_divide(1, 0) AS td_zero;
