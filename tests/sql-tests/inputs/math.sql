SELECT abs(-3) a, ceil(1.2) c, floor(-1.2) f, round(2.5) r, round(-2.5) r2, round(3.14159, 3) r3;
SELECT sqrt(16.0) s, cbrt(27.0) cb, exp(0.0) e, ln(1.0) l, log10(100.0) l10, log2(8.0) l2, log(2.0, 8.0) lg;
SELECT pow(2, 10) p, power(3.0, 2.0) p2, mod(10, 3) m, pmod(-7, 3) pm, 10 % 3 pct;
SELECT sin(0.0) s, cos(0.0) c, tan(0.0) t, asin(1.0) asn, acos(1.0) acs, atan(1.0) at, atan2(1.0, 1.0) at2;
SELECT degrees(pi()) dg, radians(180.0) rd, e() ee, sign(-5) sg, signum(3.2) sg2;
SELECT sinh(0.0) sh, cosh(0.0) ch, tanh(0.0) th, expm1(0.0) em, log1p(0.0) lp;
SELECT greatest(1, 5, 3) g, least(1, 5, 3) l, greatest(1.0, NULL, 2.0) gn;
SELECT pi() p, e() e;
