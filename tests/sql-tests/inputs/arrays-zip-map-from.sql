SELECT arrays_zip(array(1, 2), array('a', 'b')) AS z;
SELECT map_from_arrays(array('k1', 'k2'), array(10, 20)) AS mfa;
SELECT str_to_map('a:1,b:2') AS stm, str_to_map('x=1;y=2', ';', '=') AS stm2;
