SELECT md5('spark') AS m, sha1('spark') AS s1, sha2('spark', 256) AS s2;
SELECT base64('hello') AS b64, unbase64(base64('hello')) AS ub;
SELECT format_number(1234567.891, 2) AS fn, format_number(1000, 0) AS fn0;
