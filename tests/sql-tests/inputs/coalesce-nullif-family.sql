SELECT coalesce(cast(null as int), 2, 3) AS c1, coalesce(cast(null as int), cast(null as int)) AS c2;
SELECT nullif(1, 1) AS n1, nullif(1, 2) AS n2, nullif(cast(null as int), 1) AS n3;
SELECT nvl(cast(null as int), 9) AS nvl_r, nvl2(cast(null as int), 1, 2) AS nvl2_r;
SELECT ifnull(cast(null as int), 7) AS ifnull_r;
SELECT isnull(cast(null as int)) AS is_n, isnotnull(3) AS is_nn;
