SELECT greatest(1, 5, 3) AS g1, least(1, 5, 3) AS l1;
SELECT greatest(1, cast(null as int), 3) AS g_null, least(cast(null as int), 2) AS l_null;
SELECT greatest('apple', 'pear') AS g_str;
SELECT greatest(1.5, 2) AS g_mixed;
