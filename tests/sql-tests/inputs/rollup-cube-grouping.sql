CREATE OR REPLACE TEMP VIEW rcg AS SELECT 'a' x, 'p' y, 1 v UNION ALL SELECT 'a', 'q', 2 UNION ALL SELECT 'b', 'p', 4;
SELECT x, y, sum(v) s FROM rcg GROUP BY ROLLUP(x, y) ORDER BY x NULLS LAST, y NULLS LAST;
SELECT x, y, sum(v) s, grouping(x) gx, grouping(y) gy FROM rcg GROUP BY CUBE(x, y) ORDER BY x NULLS LAST, y NULLS LAST;
SELECT x, sum(v) s, grouping_id(x) gid FROM rcg GROUP BY ROLLUP(x) ORDER BY x NULLS LAST;
