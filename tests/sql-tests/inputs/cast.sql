SELECT cast(3.9 AS int) AS a, cast('42' AS bigint) AS b, cast(1 AS double) AS c, cast('3.14' AS double) AS d;
SELECT cast('abc' AS int) AS bad_int, cast(NULL AS string) AS ns, cast(true AS int) AS bi, cast(0 AS boolean) AS ib;
SELECT cast(123.456 AS string) AS s1, cast(DATE '2020-02-29' AS string) AS s2;
