SELECT round(2.5) AS r1, round(3.5) AS r2, round(-2.5) AS r3;
SELECT round(2.345, 2) AS r4, round(123.456, -1) AS r5;
SELECT bround(2.5) AS b1, bround(3.5) AS b2;
SELECT floor(1.9) AS f1, floor(-1.1) AS f2, ceil(1.1) AS c1, ceil(-1.9) AS c2;
SELECT sign(-5) AS sg1, signum(3.2) AS sg2, sign(0) AS sg0;
SELECT pmod(10, 3) AS p1, pmod(-7, 3) AS p2, mod(-7, 3) AS m1, -7 % 3 AS m2;
SELECT power(2, 10) AS pw, sqrt(16.0) AS sq, cbrt(27.0) AS cb;
