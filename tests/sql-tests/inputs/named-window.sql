SELECT i_item_id, sum(i_current_price) OVER w AS s FROM item WINDOW w AS (PARTITION BY i_category ORDER BY i_item_sk) ORDER BY i_item_id LIMIT 5;
SELECT DISTINCT i_category, count(*) OVER (PARTITION BY i_category) AS n FROM item ORDER BY i_category;
