SELECT i_category AS cat, count(*) AS n FROM item GROUP BY 1 ORDER BY 1;
SELECT i_category AS cat, count(*) AS n FROM item GROUP BY cat ORDER BY cat;
