SELECT concat('a', 'b', 'c') AS c1, concat('x', cast(null as string)) AS c_null;
SELECT concat_ws('-', 'a', 'b', 'c') AS cw1, concat_ws('-', 'a', cast(null as string), 'c') AS cw_skip_null;
SELECT 'a' || 'b' || 'c' AS pipe_concat;
SELECT repeat('ab', 3) AS rep, reverse('spark') AS rev;
SELECT lpad('7', 3, '0') AS lp, rpad('7', 3, '*') AS rp;
