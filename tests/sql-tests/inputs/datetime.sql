SELECT year(DATE '2020-06-15') y, quarter(DATE '2020-06-15') q, month(DATE '2020-06-15') m, day(DATE '2020-06-15') d;
SELECT dayofmonth(DATE '2020-06-15') dm, dayofweek(DATE '2020-06-15') dw, dayofyear(DATE '2020-06-15') dy, weekofyear(DATE '2020-06-15') wy;
SELECT hour(TIMESTAMP '2020-06-15 13:45:30') h, minute(TIMESTAMP '2020-06-15 13:45:30') m, second(TIMESTAMP '2020-06-15 13:45:30') s;
SELECT date_add(DATE '2020-01-01', 30) da, date_sub(DATE '2020-01-01', 1) ds, datediff(DATE '2020-02-01', DATE '2020-01-01') dd;
SELECT add_months(DATE '2020-01-31', 1) am, months_between(DATE '2020-03-01', DATE '2020-01-01') mb, last_day(DATE '2020-02-05') ld;
SELECT make_date(2020, 2, 29) md, to_date('2020-05-17') td, date_trunc('month', TIMESTAMP '2020-06-15 13:45:30') dt;
SELECT date_format(DATE '2020-06-15', 'yyyy/MM/dd') df, unix_timestamp(TIMESTAMP '1970-01-02 00:00:00') ut, from_unixtime(86400) fu;
SELECT trunc(DATE '2020-06-15', 'year') ty, trunc(DATE '2020-06-15', 'mm') tm;
