SELECT regexp_extract_all('a1b2c3', '([a-z])(\\d)', 1) AS groups1, regexp_extract_all('a1b2c3', '([a-z])(\\d)', 2) AS groups2;
SELECT regexp_extract_all('foo12bar34', '\\d+') AS nums;
SELECT regexp_substr('hello world', 'o\\w') AS sub1, regexp_substr('abc', 'zz') AS sub_null;
SELECT regexp_instr('abcabc', 'bc') AS pos1, regexp_instr('abc', 'zz') AS pos0;
SELECT regexp_count('banana', 'an') AS cnt, regexp_count('aaa', 'b') AS zero;
SELECT regexp_like('spark', '^sp') AS rl1, regexp_like('spark', '^qq') AS rl2;
SELECT regexp_replace('a1b2', '\\d', '#') AS rep;
SELECT regexp_extract('2020-06-01', '(\\d{4})-(\\d{2})', 2) AS month_part;
