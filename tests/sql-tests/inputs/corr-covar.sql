CREATE OR REPLACE TEMP VIEW cvr AS SELECT 1.0 x, 2.0 y UNION ALL SELECT 2.0, 4.0 UNION ALL SELECT 3.0, 6.0;
SELECT round(corr(x, y), 6) AS c FROM cvr;
SELECT round(covar_pop(x, y), 6) AS cp, round(covar_samp(x, y), 6) AS cs FROM cvr;
SELECT round(skewness(x), 6) AS sk, round(kurtosis(x), 6) AS kt FROM cvr;
