CREATE OR REPLACE TEMP VIEW aggn AS SELECT 1 g, 10 v UNION ALL SELECT 1, cast(null as int) UNION ALL SELECT 2, cast(null as int) UNION ALL SELECT 2, cast(null as int);
SELECT g, count(*) AS cnt_star, count(v) AS cnt_v, sum(v) AS sum_v, avg(v) AS avg_v, min(v) AS min_v, max(v) AS max_v FROM aggn GROUP BY g ORDER BY g;
SELECT count(distinct v) AS cd FROM aggn;
SELECT sum(v) AS all_sum FROM aggn WHERE v IS NULL;
