SELECT col1, col2 FROM (VALUES (1, 'a'), (2, 'b')) t ORDER BY col1;
SELECT col1 * 10 AS ten FROM (VALUES (1), (2), (3)) v WHERE col1 > 1 ORDER BY ten;
