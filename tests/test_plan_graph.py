"""Per-operator SQLMetrics + live-UI plan graph (reference:
sqlx/metric/SQLMetrics.scala, sqlx/execution/ui/SparkPlanGraph.scala)."""

import re
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(3)
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 8, 2000),
        "v": rng.integers(0, 100, 2000)})) \
        .createOrReplaceTempView("pg_t")
    return spark


def test_plan_graph_records_rows_and_time(data):
    spark = data
    df = spark.sql("select k, sum(v) s from pg_t where v > 50 "
                   "group by k order by k")
    df.toArrow()
    graph = df.query_execution.plan_graph()
    assert graph, "empty plan graph"
    by_op = {}
    for nd in graph:
        by_op.setdefault(nd["op"], nd)
    # the scan saw every input row; the aggregate output is 8 groups
    assert by_op["LocalTableScanExec"]["rows"] == 2000
    assert by_op["HashAggregateExec"]["rows"] == 8
    # inclusive wall time recorded on every executed operator
    assert all(nd["ms"] is not None for nd in graph
               if nd["op"] != "AQE")
    # parent inclusive time >= child inclusive time
    root = graph[0]
    assert all(root["ms"] >= nd["ms"] for nd in graph[1:]
               if nd["ms"] is not None)


def test_plan_graph_off_when_disabled(spark):
    spark.conf.set("spark.tpu.ui.operatorMetrics", "false")
    try:
        df = spark.sql("select 1 x")
        df.toArrow()
        graph = df.query_execution.plan_graph()
        assert all(nd["rows"] is None and nd["ms"] is None
                   for nd in graph)
    finally:
        spark.conf.set("spark.tpu.ui.operatorMetrics", "true")


def test_live_ui_renders_tpcds_plan_graph(spark):
    """The VERDICT bar: browsing a TPC-DS query in the live UI shows
    per-operator rows/time."""
    from tests.tpcds.datagen import gen_tpcds_full

    tables = gen_tpcds_full(scale=0.01)
    for name in ("date_dim", "store_sales", "item"):
        spark.createDataFrame(tables[name]).createOrReplaceTempView(name)
    ui = spark.startUI()
    try:
        import os

        sql = open(os.path.join(
            os.path.dirname(__file__), "tpcds", "queries",
            "q3.sql")).read()
        spark.sql(sql).toArrow()
        deadline = time.time() + 10
        qp = ""
        while time.time() < deadline:
            html = urllib.request.urlopen(
                ui.url + f"app?id={spark.name}").read().decode()
            m = re.search(rf"/query\?id={spark.name}&n=(\d+)", html)
            if m:
                qp = urllib.request.urlopen(
                    ui.url +
                    f"query?id={spark.name}&n={m.group(1)}"
                ).read().decode()
                if "Plan graph" in qp:
                    break
            time.sleep(0.2)
        assert "Plan graph" in qp
        assert "HashAggregateExec" in qp or "ScanExec" in qp
        # a rows cell rendered with a real number
        assert re.search(r"<td>\d+</td>", qp), qp[-800:]
    finally:
        ui.stop()
