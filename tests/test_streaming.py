"""Structured Streaming tests — the StreamTest DSL style
(reference: sql/core/src/test/.../streaming/StreamTest.scala: AddData /
CheckAnswer / StopStream against MemoryStream)."""

import time

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F


def _sink_rows(spark, name):
    return spark.sql(f"SELECT * FROM {name}").toArrow().to_pydict()


def test_stateless_append(spark):
    src, df = spark.memory_stream(pa.schema([("x", pa.int64())]))
    q = (df.filter(F.col("x") > 1)
           .select((F.col("x") * 10).alias("y"))
           .writeStream.format("memory").queryName("s_append")
           .outputMode("append").start())
    try:
        src.add_data({"x": [1, 2, 3]})
        q.processAllAvailable()
        src.add_data({"x": [4]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_append")
        assert sorted(out["y"]) == [20, 30, 40]
    finally:
        q.stop()


def test_stateful_aggregation_complete(spark):
    src, df = spark.memory_stream(pa.schema([("k", pa.string()),
                                             ("v", pa.int64())]))
    q = (df.groupBy("k").agg(F.sum("v").alias("s"),
                             F.count("*").alias("c"))
           .writeStream.format("memory").queryName("s_agg")
           .outputMode("complete").start())
    try:
        src.add_data({"k": ["a", "b", "a"], "v": [1, 2, 3]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_agg")
        assert dict(zip(out["k"], out["s"])) == {"a": 4, "b": 2}

        # second batch merges into state
        src.add_data({"k": ["a", "c"], "v": [10, 7]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_agg")
        assert dict(zip(out["k"], out["s"])) == {"a": 14, "b": 2, "c": 7}
        assert dict(zip(out["k"], out["c"])) == {"a": 3, "b": 1, "c": 1}
    finally:
        q.stop()


def test_update_mode_emits_only_changed(spark):
    src, df = spark.memory_stream(pa.schema([("k", pa.string()),
                                             ("v", pa.int64())]))
    collected = []

    def collect(batch_df, batch_id):
        collected.append(batch_df.toArrow().to_pydict())

    q = (df.groupBy("k").agg(F.sum("v").alias("s"))
           .writeStream.foreachBatch(collect).outputMode("update").start())
    try:
        src.add_data({"k": ["a", "b"], "v": [1, 2]})
        q.processAllAvailable()
        src.add_data({"k": ["a"], "v": [5]})
        q.processAllAvailable()
        time.sleep(0.1)
        assert len(collected) == 2
        # second batch only re-emits 'a'
        assert collected[1]["k"] == ["a"]
        assert collected[1]["s"] == [6]
    finally:
        q.stop()


def test_checkpoint_resume(spark, tmp_path):
    ck = str(tmp_path / "ckpt")
    src, df = spark.memory_stream(pa.schema([("k", pa.string()),
                                             ("v", pa.int64())]))
    agg = df.groupBy("k").agg(F.sum("v").alias("s"))
    q = (agg.writeStream.format("memory").queryName("s_ck")
         .outputMode("complete").option("checkpointLocation", ck).start())
    src.add_data({"k": ["a"], "v": [1]})
    src.add_data({"k": ["a"], "v": [2]})
    q.processAllAvailable()
    q.stop()

    # resume from checkpoint: state survives, committed batches not replayed
    q2 = (agg.writeStream.format("memory").queryName("s_ck2")
          .outputMode("complete").option("checkpointLocation", ck).start())
    try:
        src.add_data({"k": ["a", "b"], "v": [10, 5]})
        q2.processAllAvailable()
        out = _sink_rows(spark, "s_ck2")
        assert dict(zip(out["k"], out["s"])) == {"a": 13, "b": 5}
    finally:
        q2.stop()


def test_trigger_once_drains(spark):
    src, df = spark.memory_stream(pa.schema([("x", pa.int64())]))
    src.add_data({"x": [1, 2]})
    src.add_data({"x": [3]})
    q = (df.writeStream.format("memory").queryName("s_once")
         .outputMode("append").trigger(once=True).start())
    assert q.awaitTermination(10)
    out = _sink_rows(spark, "s_once")
    assert sorted(out["x"]) == [1, 2, 3]


def test_rate_source(spark):
    df = spark.readStream.format("rate").option("rowsPerSecond", 100).load()
    q = (df.writeStream.format("memory").queryName("s_rate")
         .outputMode("append").start())
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                out = _sink_rows(spark, "s_rate")
                if len(out.get("value", [])) > 0:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        out = _sink_rows(spark, "s_rate")
        assert len(out["value"]) > 0
    finally:
        q.stop()


def test_file_stream_source(spark, tmp_path):
    import pyarrow.parquet as pq

    d = tmp_path / "in"
    d.mkdir()
    pq.write_table(pa.table({"x": [1, 2]}), str(d / "a.parquet"))
    df = spark.readStream.format("parquet").load(str(d))
    q = (df.writeStream.format("memory").queryName("s_file")
         .outputMode("append").start())
    try:
        q.processAllAvailable()
        pq.write_table(pa.table({"x": [3]}), str(d / "b.parquet"))
        q.processAllAvailable()
        out = _sink_rows(spark, "s_file")
        assert sorted(out["x"]) == [1, 2, 3]
    finally:
        q.stop()


def test_stream_join_static_dimension(spark):
    """Streaming fact rows join a static dimension per micro-batch
    (reference: stream-static joins in MicroBatchExecution)."""
    dim = spark.createDataFrame(pa.table({
        "id": [1, 2], "name": ["ann", "bob"]}))
    dim.createOrReplaceTempView("dim_users")

    src, facts = spark.memory_stream(pa.schema([
        ("uid", pa.int64()), ("v", pa.int64())]))
    q = (facts.join(dim, facts["uid"] == dim["id"])
         .select("name", "v")
         .writeStream.format("memory").queryName("sj")
         .outputMode("append").start())
    try:
        src.add_data({"uid": [1, 2, 9], "v": [10, 20, 30]})
        q.processAllAvailable()
        out = spark.sql("SELECT * FROM sj ORDER BY name").toArrow().to_pydict()
        assert out["name"] == ["ann", "bob"]
        assert out["v"] == [10, 20]
    finally:
        q.stop()


def test_streaming_dedup_via_distinct(spark):
    """Streaming dropDuplicates rides the stateful-aggregate path
    (Distinct → Aggregate → buffer-table state)."""
    src, df = spark.memory_stream(pa.schema([("k", pa.string()),
                                             ("v", pa.int64())]))
    q = (df.dropDuplicates()
         .writeStream.format("memory").queryName("s_dedup")
         .outputMode("complete").start())
    try:
        src.add_data({"k": ["a", "a", "b"], "v": [1, 1, 2]})
        q.processAllAvailable()
        src.add_data({"k": ["a", "c"], "v": [1, 3]})  # 'a',1 seen before
        q.processAllAvailable()
        out = _sink_rows(spark, "s_dedup")
        rows = sorted(zip(out["k"], out["v"]))
        assert rows == [("a", 1), ("b", 2), ("c", 3)]
    finally:
        q.stop()


def test_streaming_dedup_append(spark):
    src, df = spark.memory_stream(pa.schema([("k", pa.string()),
                                             ("v", pa.int64())]))
    q = (df.dropDuplicates(["k"])
           .writeStream.format("memory").queryName("s_dedup")
           .outputMode("append").start())
    try:
        src.add_data({"k": ["a", "b", "a"], "v": [1, 2, 3]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_dedup")
        assert sorted(zip(out["k"], out["v"])) == [("a", 1), ("b", 2)]
        # duplicates across batches are suppressed; new keys emitted
        src.add_data({"k": ["a", "c"], "v": [9, 4]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_dedup")
        assert sorted(zip(out["k"], out["v"])) == \
            [("a", 1), ("b", 2), ("c", 4)]
    finally:
        q.stop()


def test_streaming_distinct_append(spark):
    src, df = spark.memory_stream(pa.schema([("x", pa.int64())]))
    q = (df.distinct()
           .writeStream.format("memory").queryName("s_dist")
           .outputMode("append").start())
    try:
        src.add_data({"x": [1, 1, 2]})
        q.processAllAvailable()
        src.add_data({"x": [2, 3]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_dist")
        assert sorted(out["x"]) == [1, 2, 3]
    finally:
        q.stop()


def test_append_mode_watermark_aggregate(spark):
    src, df = spark.memory_stream(pa.schema([("t", pa.int64()),
                                             ("v", pa.int64())]))
    q = (df.withWatermark("t", "2 seconds")
           .groupBy("t").agg(F.sum("v").alias("s"))
           .writeStream.format("memory").queryName("s_wm_app")
           .outputMode("append").start())
    try:
        src.add_data({"t": [1, 1, 2], "v": [10, 20, 5]})
        q.processAllAvailable()
        # watermark = 2-2 = 0 → nothing finalized yet
        out = _sink_rows(spark, "s_wm_app")
        assert out["t"] == []
        src.add_data({"t": [5, 1], "v": [7, 100]})
        q.processAllAvailable()
        # watermark = 5-2 = 3 → groups t=1 (incl. late row), t=2 finalize
        out = _sink_rows(spark, "s_wm_app")
        assert dict(zip(out["t"], out["s"])) == {1: 130, 2: 5}
        src.add_data({"t": [9], "v": [1]})
        q.processAllAvailable()
        # watermark = 7 → t=5 finalizes; t=1/2 already emitted, not again
        out = _sink_rows(spark, "s_wm_app")
        assert dict(zip(out["t"], out["s"])) == {1: 130, 2: 5, 5: 7}
    finally:
        q.stop()


def test_stream_stream_inner_join(spark):
    src_l, dfl = spark.memory_stream(pa.schema([("k", pa.string()),
                                                ("lv", pa.int64())]))
    src_r, dfr = spark.memory_stream(pa.schema([("k2", pa.string()),
                                                ("rv", pa.int64())]))
    joined = dfl.join(dfr, dfl["k"] == dfr["k2"], "inner") \
                .select(dfl["k"], dfl["lv"], dfr["rv"])
    q = (joined.writeStream.format("memory").queryName("s_ssj")
         .outputMode("append").start())
    try:
        src_l.add_data({"k": ["a", "b"], "lv": [1, 2]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_ssj")
        assert out["k"] == []        # right side empty so far
        src_r.add_data({"k2": ["a"], "rv": [10]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_ssj")
        assert sorted(zip(out["k"], out["lv"], out["rv"])) == \
            [("a", 1, 10)]
        # late left row joins BUFFERED right rows; no duplicates
        src_l.add_data({"k": ["a"], "lv": [3]})
        src_r.add_data({"k2": ["b"], "rv": [20]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_ssj")
        assert sorted(zip(out["k"], out["lv"], out["rv"])) == \
            [("a", 1, 10), ("a", 3, 10), ("b", 2, 20)]
    finally:
        q.stop()


def test_apply_in_pandas_with_state(spark):
    import pandas as pd

    from spark_tpu.types import (
        IntegerType, LongType, StringType, StructField, StructType,
    )

    out_schema = StructType([StructField("k", StringType()),
                             StructField("running", LongType())])

    def running_sum(key, pdf, state):
        total = (state.get() or 0) + int(pdf["v"].sum())
        state.update(total)
        return pd.DataFrame({"k": [key[0]], "running": [total]})

    src, df = spark.memory_stream(pa.schema([("k", pa.string()),
                                             ("v", pa.int64())]))
    q = (df.groupBy("k").applyInPandasWithState(running_sum, out_schema)
           .writeStream.format("memory").queryName("s_state_map")
           .outputMode("update").start())
    try:
        src.add_data({"k": ["a", "a", "b"], "v": [1, 2, 5]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_state_map")
        assert dict(zip(out["k"], out["running"])) == {"a": 3, "b": 5}
        src.add_data({"k": ["a"], "v": [10]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_state_map")
        assert out["running"][-1] == 13  # state carried across batches
    finally:
        q.stop()


def test_apply_in_pandas_with_state_batch_mode(spark):
    import pandas as pd

    from spark_tpu.types import (
        LongType, StringType, StructField, StructType,
    )

    out_schema = StructType([StructField("k", StringType()),
                             StructField("n", LongType())])

    def count_rows(key, pdf, state):
        return pd.DataFrame({"k": [key[0]], "n": [len(pdf)]})

    df = spark.createDataFrame(pa.table({
        "k": ["x", "x", "y"], "v": [1, 2, 3]}))
    out = df.groupBy("k").applyInPandasWithState(count_rows, out_schema) \
        .toArrow().to_pydict()
    assert dict(zip(out["k"], out["n"])) == {"x": 2, "y": 1}


def test_append_watermark_drops_late_rows(spark):
    # a row older than the watermark must be dropped, never re-emitting a
    # finalized group (ADVICE r1: late-data filter + previous-batch
    # watermark semantics)
    src, df = spark.memory_stream(pa.schema([("t", pa.int64()),
                                             ("v", pa.int64())]))
    q = (df.withWatermark("t", "0 seconds")
           .groupBy("t").agg(F.sum("v").alias("s"))
           .writeStream.format("memory").queryName("s_wm_late")
           .outputMode("append").start())
    try:
        src.add_data({"t": [1, 2], "v": [10, 5]})
        q.processAllAvailable()
        src.add_data({"t": [5], "v": [7]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_wm_late")
        assert dict(zip(out["t"], out["s"])) == {1: 10, 2: 5}
        # t=1 is far below the watermark (5): dropped, NOT re-emitted
        src.add_data({"t": [1, 9], "v": [100, 1]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_wm_late")
        assert dict(zip(out["t"], out["s"])) == {1: 10, 2: 5, 5: 7}
    finally:
        q.stop()


def test_streaming_checkpoint_restores_watermark(tmp_path, spark):
    # watermark + state survive a checkpoint restart; a late row after
    # recovery must still be dropped (code-review r2 finding)
    ckpt = str(tmp_path / "ck_wm")
    schema = pa.schema([("t", pa.int64()), ("v", pa.int64())])
    src, df = spark.memory_stream(schema)
    q = (df.withWatermark("t", "0 seconds")
           .groupBy("t").agg(F.sum("v").alias("s"))
           .writeStream.format("memory").queryName("s_wm_ck")
           .outputMode("append").option("checkpointLocation", ckpt).start())
    try:
        src.add_data({"t": [1, 2], "v": [10, 5]})
        q.processAllAvailable()
        src.add_data({"t": [5], "v": [7]})
        q.processAllAvailable()
    finally:
        q.stop()
    # restart from the checkpoint with a fresh source: the watermark (5)
    # must be restored so the late t=1 row is dropped, and retained state
    # (t=5 buffer) must be recovered
    src2, df2 = spark.memory_stream(schema)
    q2 = (df2.withWatermark("t", "0 seconds")
             .groupBy("t").agg(F.sum("v").alias("s"))
             .writeStream.format("memory").queryName("s_wm_ck2")
             .outputMode("append").option("checkpointLocation", ckpt).start())
    try:
        assert q2.current_watermark_us == 5_000_000
        src2.add_data({"t": [1, 9], "v": [100, 1]})
        q2.processAllAvailable()
        out = _sink_rows(spark, "s_wm_ck2")
        # t=1 dropped as late (not re-emitted with 100); t=5 finalizes
        # from recovered state
        assert dict(zip(out["t"], out["s"])) == {5: 7}
    finally:
        q2.stop()


def test_stream_stream_left_outer_join(spark):
    """Left-outer stream-stream join: unmatched left rows emit
    null-extended once their event time passes the watermark, exactly
    once; state is trimmed below the watermark (reference:
    StreamingSymmetricHashJoinExec outer semantics)."""
    src_l, dfl = spark.memory_stream(pa.schema([
        ("t", pa.timestamp("us")), ("k", pa.string()),
        ("lv", pa.int64())]))
    src_r, dfr = spark.memory_stream(pa.schema([
        ("t2", pa.timestamp("us")), ("k2", pa.string()),
        ("rv", pa.int64())]))
    dfl = dfl.withWatermark("t", "0 seconds")
    dfr = dfr.withWatermark("t2", "0 seconds")
    joined = dfl.join(dfr, dfl["k"] == dfr["k2"], "left_outer") \
                .select(dfl["k"], dfl["lv"], dfr["rv"])
    q = (joined.writeStream.format("memory").queryName("s_loj")
         .outputMode("append").start())

    import datetime as dt

    def ts(s):
        return dt.datetime(2024, 1, 1, 0, 0, s)

    try:
        src_l.add_data({"t": [ts(1), ts(2)], "k": ["a", "b"],
                        "lv": [1, 2]})
        src_r.add_data({"t2": [ts(1)], "k2": ["a"], "rv": [10]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_loj")
        # inner match emits immediately; 'b' awaits the watermark
        assert sorted(zip(out["k"], out["lv"])) == [("a", 1)]

        # advance both sides' event time → watermark passes t=2,
        # so unmatched 'b' finalizes null-extended
        src_l.add_data({"t": [ts(30)], "k": ["z"], "lv": [9]})
        src_r.add_data({"t2": [ts(30)], "k2": ["y"], "rv": [99]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_loj")
        rows = sorted(zip(out["k"], out["lv"],
                          [v if v is not None else -1 for v in out["rv"]]))
        assert ("b", 2, -1) in rows, rows
        assert rows.count(("b", 2, -1)) == 1
        # state trimmed: everything below the watermark evicted
        state_l, state_r = q.recent_progress[-1]["stateRows"]
        assert state_l <= 2 and state_r <= 2, (state_l, state_r)
    finally:
        q.stop()


def test_stream_stream_full_outer_join(spark):
    src_l, dfl = spark.memory_stream(pa.schema([
        ("t", pa.timestamp("us")), ("k", pa.string()),
        ("lv", pa.int64())]))
    src_r, dfr = spark.memory_stream(pa.schema([
        ("t2", pa.timestamp("us")), ("k2", pa.string()),
        ("rv", pa.int64())]))
    dfl = dfl.withWatermark("t", "0 seconds")
    dfr = dfr.withWatermark("t2", "0 seconds")
    joined = dfl.join(dfr, dfl["k"] == dfr["k2"], "full_outer") \
                .select(dfl["k"], dfl["lv"], dfr["k2"], dfr["rv"])
    q = (joined.writeStream.format("memory").queryName("s_foj")
         .outputMode("append").start())

    import datetime as dt

    def ts(s):
        return dt.datetime(2024, 1, 1, 0, 0, s)

    try:
        src_l.add_data({"t": [ts(1)], "k": ["a"], "lv": [1]})
        src_r.add_data({"t2": [ts(1), ts(2)], "k2": ["a", "c"],
                        "rv": [10, 30]})
        q.processAllAvailable()
        src_l.add_data({"t": [ts(40)], "k": ["zz"], "lv": [0]})
        src_r.add_data({"t2": [ts(40)], "k2": ["yy"], "rv": [0]})
        q.processAllAvailable()
        out = _sink_rows(spark, "s_foj")
        pairs = sorted((k if k is not None else "<null>",
                        k2 if k2 is not None else "<null>")
                       for k, k2 in zip(out["k"], out["k2"]))
        assert ("a", "a") in pairs           # inner match
        assert ("<null>", "c") in pairs      # unmatched right finalized
    finally:
        q.stop()


def test_stream_join_state_bounded_under_long_run(spark):
    """Watermark-driven trimming keeps join state bounded over many
    batches (VERDICT round-1: inner-join state grew unboundedly)."""
    src_l, dfl = spark.memory_stream(pa.schema([
        ("t", pa.timestamp("us")), ("k", pa.string()),
        ("lv", pa.int64())]))
    src_r, dfr = spark.memory_stream(pa.schema([
        ("t2", pa.timestamp("us")), ("k2", pa.string()),
        ("rv", pa.int64())]))
    dfl = dfl.withWatermark("t", "0 seconds")
    dfr = dfr.withWatermark("t2", "0 seconds")
    joined = dfl.join(dfr, dfl["k"] == dfr["k2"], "inner") \
                .select(dfl["k"], dfl["lv"], dfr["rv"])
    q = (joined.writeStream.format("memory").queryName("s_bounded")
         .outputMode("append").start())

    import datetime as dt

    try:
        for i in range(8):
            base = dt.datetime(2024, 1, 1) + dt.timedelta(minutes=i)
            src_l.add_data({"t": [base], "k": [f"k{i}"], "lv": [i]})
            src_r.add_data({"t2": [base], "k2": [f"k{i}"], "rv": [i]})
            q.processAllAvailable()
        state_l, state_r = q.recent_progress[-1]["stateRows"]
        assert state_l <= 2 and state_r <= 2, (state_l, state_r)
        out = _sink_rows(spark, "s_bounded")
        assert sorted(out["k"]) == [f"k{i}" for i in range(8)]
    finally:
        q.stop()


def test_stream_join_checkpoint_resume(spark, tmp_path):
    """Join state (__matched flags, row ids, watermark) survives a
    checkpoint restart: a finalized outer row is not re-emitted and a
    buffered row still matches after resume."""
    import datetime as dt

    ck = str(tmp_path / "ssj_ck")

    def ts(s):
        return dt.datetime(2024, 1, 1) + dt.timedelta(seconds=s)

    def build(src_l_schema_only=False):
        src_l, dfl = spark.memory_stream(pa.schema([
            ("t", pa.timestamp("us")), ("k", pa.string()),
            ("lv", pa.int64())]))
        src_r, dfr = spark.memory_stream(pa.schema([
            ("t2", pa.timestamp("us")), ("k2", pa.string()),
            ("rv", pa.int64())]))
        dfl = dfl.withWatermark("t", "0 seconds")
        dfr = dfr.withWatermark("t2", "0 seconds")
        joined = dfl.join(dfr, dfl["k"] == dfr["k2"], "left_outer") \
                    .select(dfl["k"], dfl["lv"], dfr["rv"])
        return src_l, src_r, joined

    src_l, src_r, joined = build()
    q = (joined.writeStream.format("memory").queryName("s_ssj_ck")
         .outputMode("append").option("checkpointLocation", ck).start())
    try:
        src_l.add_data({"t": [ts(1), ts(5)], "k": ["a", "b"],
                        "lv": [1, 2]})
        src_r.add_data({"t2": [ts(1)], "k2": ["a"], "rv": [10]})
        q.processAllAvailable()
    finally:
        q.stop()

    # restart with fresh sources: buffered 'b' must still be in state
    src_l2, src_r2, joined2 = build()
    q2 = (joined2.writeStream.format("memory").queryName("s_ssj_ck2")
          .outputMode("append").option("checkpointLocation", ck).start())
    try:
        src_r2.add_data({"t2": [ts(5)], "k2": ["b"], "rv": [50]})
        src_l2.add_data({"t": [ts(60)], "k": ["zz"], "lv": [0]})
        q2.processAllAvailable()
        out = _sink_rows(spark, "s_ssj_ck2")
        rows = sorted(zip(out["k"], out["lv"],
                          [v if v is not None else -1 for v in out["rv"]]))
        # buffered-from-before-restart 'b' matches the post-restart right
        # row instead of finalizing null-extended
        assert ("b", 2, 50) in rows, rows
        assert ("b", 2, -1) not in rows, rows
    finally:
        q2.stop()


def test_socket_source_streams_lines(spark):
    """TCP socket source (TextSocketMicroBatchStream role): lines pushed
    by a server arrive as streaming rows."""
    import socket
    import threading
    import time as _time

    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    conns = []

    def accept():
        c, _ = srv.accept()
        conns.append(c)
        c.sendall(b"alpha\nbeta\n")

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    df = (spark.readStream.format("socket")
          .option("host", "127.0.0.1").option("port", port).load())
    q = (df.writeStream.format("memory").queryName("sock_out")
         .outputMode("append").start())
    from spark_tpu.errors import AnalysisException

    def poll():
        # the memory sink registers its view on the first committed batch;
        # a poll racing that registration reads "view not found", not rows
        try:
            return [r["value"] for r in
                    spark.sql("SELECT * FROM sock_out").collect()]
        except AnalysisException:
            return []

    try:
        t.join(timeout=10)
        deadline = _time.monotonic() + 15
        got = []
        while _time.monotonic() < deadline:
            q.processAllAvailable()
            got = poll()
            if len(got) >= 2:
                break
            _time.sleep(0.1)
        assert sorted(got) == ["alpha", "beta"]
        conns[0].sendall(b"gamma\n")
        deadline = _time.monotonic() + 15
        while _time.monotonic() < deadline:
            q.processAllAvailable()
            got = poll()
            if len(got) >= 3:
                break
            _time.sleep(0.1)
        assert sorted(got) == ["alpha", "beta", "gamma"]
    finally:
        q.stop()
        for c in conns:
            c.close()
        srv.close()


def test_continuous_trigger_low_latency_epochs(spark, tmp_path):
    """trigger(continuous=...): tight polling with epoch-interval
    checkpoints (ContinuousExecution role) — results identical to
    micro-batch, far fewer WAL entries."""
    import os as _os
    import time as _time

    src, df = spark.memory_stream(__import__("pyarrow").schema(
        [("k", __import__("pyarrow").int64()),
         ("v", __import__("pyarrow").int64())]))
    ckpt = str(tmp_path / "cont")
    q = (df.groupBy("k").agg(F.sum("v").alias("s"))
         .writeStream.format("memory").queryName("cont_out")
         .outputMode("complete")
         .option("checkpointLocation", ckpt)
         .trigger(continuous="10 seconds")
         .start())
    try:
        for i in range(6):
            src.add_data({"k": [i % 2], "v": [i]})
            q.processAllAvailable()
        out = {r["k"]: r["s"] for r in
               spark.sql("SELECT * FROM cont_out").collect()}
        assert out == {0: 0 + 2 + 4, 1: 1 + 3 + 5}
        # 6 batches ran, but the 10s epoch admits only the FIRST WAL entry
        offsets = _os.listdir(_os.path.join(ckpt, "offsets"))
        assert len(offsets) == 1, offsets
    finally:
        q.stop()
