"""The driver-gate contract: dryrun_multichip must validate sharding on a
virtual CPU mesh regardless of accelerator health (r02 post-mortem — a TPU
whose enumeration worked but whose execution was broken by a libtpu version
skew poisoned the in-process dryrun), and get_mesh must never silently
truncate to fewer devices than asked for."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_get_mesh_raises_on_insufficient_devices():
    from spark_tpu.parallel.mesh import get_mesh

    with pytest.raises(RuntimeError, match="only .* visible"):
        get_mesh(1024)


def test_get_mesh_exact_count():
    from spark_tpu.parallel.mesh import get_mesh

    mesh = get_mesh(8)
    assert mesh.devices.size == 8


def test_dryrun_reexecs_when_env_not_pinned():
    """Simulate the broken-backend scenario: a process whose jax topology is
    1 CPU device (stand-in for 'the visible accelerator is unusable for an
    8-way mesh'). dryrun_multichip(8) must NOT fail on the local topology —
    it must re-exec a pinned 8-device CPU subprocess and pass."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    code = (
        "import sys; sys.path.insert(0, %r); "
        "import jax; jax.devices(); "  # force backend init at 1 device
        "import __graft_entry__ as g; g.dryrun_multichip(8); "
        "print('GATE_OK')" % REPO)
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "GATE_OK" in r.stdout


def test_accelerator_probe_requires_execution(monkeypatch):
    """An accelerator that 'enumerates but cannot execute' must probe
    unhealthy: the probe source executes compute, so a failing body means
    accelerator_healthy() is False."""
    import __graft_entry__ as g

    monkeypatch.setattr(
        g, "_PROBE_SRC",
        "import jax; jax.devices(); raise SystemExit(1)")
    assert g.accelerator_healthy() is False


def test_accelerator_probe_healthy_cpu(monkeypatch):
    import __graft_entry__ as g

    assert g.accelerator_healthy() is True
