"""The driver-gate contract: dryrun_multichip must validate sharding on a
virtual CPU mesh regardless of accelerator health (r02 post-mortem — a TPU
whose enumeration worked but whose execution was broken by a libtpu version
skew poisoned the in-process dryrun), and get_mesh must never silently
truncate to fewer devices than asked for."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_get_mesh_raises_on_insufficient_devices():
    from spark_tpu.parallel.mesh import get_mesh

    with pytest.raises(RuntimeError, match="only .* visible"):
        get_mesh(1024)


def test_get_mesh_exact_count():
    from spark_tpu.parallel.mesh import get_mesh

    mesh = get_mesh(8)
    assert mesh.devices.size == 8


def test_dryrun_reexecs_when_env_not_pinned():
    """Simulate the broken-backend scenario: a process whose jax topology is
    1 CPU device (stand-in for 'the visible accelerator is unusable for an
    8-way mesh'). dryrun_multichip(8) must NOT fail on the local topology —
    it must re-exec a pinned 8-device CPU subprocess and pass."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    code = (
        "import sys; sys.path.insert(0, %r); "
        "import jax; jax.devices(); "  # force backend init at 1 device
        "import __graft_entry__ as g; g.dryrun_multichip(8); "
        "print('GATE_OK')" % REPO)
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "GATE_OK" in r.stdout


# The container's real sitecustomize registers the tunnel PJRT plugin at
# interpreter start when PALLAS_AXON_POOL_IPS is set; the HANG then happens
# at jax backend init. Faithful stand-in: a meta-path hook that sleeps
# forever the moment any process with the trigger var imports jax.
_HOSTILE_SITECUSTOMIZE = """\
import os, sys
if os.environ.get('PALLAS_AXON_POOL_IPS'):
    class _WedgedTunnel:
        def find_spec(self, name, path=None, target=None):
            if name == 'jax':
                import time; time.sleep(600)
            return None
    sys.meta_path.insert(0, _WedgedTunnel())
"""


def test_dryrun_survives_hostile_driver_env(tmp_path):
    """Reproduce the r03 driver environment that timed out the gate:
    JAX_PLATFORMS=axon plus a sitecustomize whose jax init hangs forever.
    dryrun_multichip must sanitize its child so the hook never fires, and
    complete well inside the driver budget."""
    hook = tmp_path / "hostile"
    hook.mkdir()
    (hook / "sitecustomize.py").write_text(_HOSTILE_SITECUSTOMIZE)
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"
    env["JAX_PLATFORMS"] = "axon"
    env["PYTHONPATH"] = str(hook)
    env.pop("XLA_FLAGS", None)
    env.pop("SPARK_TPU_ACCEL_HEALTH", None)
    # The OUTER process must not import jax (the driver doesn't either
    # before calling the gate); dryrun_multichip itself must do the
    # sanitized re-exec.
    code = (
        "import sys; sys.path.insert(0, %r); "
        "import __graft_entry__ as g; g.dryrun_multichip(8); "
        "print('GATE_OK')" % REPO)
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=170)
    assert r.returncode == 0, (r.stderr or "")[-3000:]
    assert "GATE_OK" in r.stdout


def test_dryrun_survives_cpu_pinned_hostile_env(tmp_path):
    """The EXACT r04 driver environment that kept the gate red:
    JAX_PLATFORMS=cpu AND --xla_force_host_platform_device_count=8 are
    already exported (how a driver builds the virtual mesh), but the
    container sitecustomize still hangs jax init because
    PALLAS_AXON_POOL_IPS is set — sitecustomize runs at interpreter start
    regardless of JAX_PLATFORMS. A fast-path that trusts the CPU-pinning
    env vars and runs in-process hangs in C. dryrun_multichip must re-exec
    through its sanitized child env even when the parent looks pinned."""
    hook = tmp_path / "hostile"
    hook.mkdir()
    (hook / "sitecustomize.py").write_text(_HOSTILE_SITECUSTOMIZE)
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = str(hook)
    env.pop("SPARK_TPU_ACCEL_HEALTH", None)
    env.pop("SPARK_TPU_DRYRUN_CHILD", None)
    code = (
        "import sys; sys.path.insert(0, %r); "
        "import __graft_entry__ as g; g.dryrun_multichip(8); "
        "print('GATE_OK')" % REPO)
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=170)
    assert r.returncode == 0, (r.stderr or "")[-3000:]
    assert "GATE_OK" in r.stdout


def test_bench_cpu_fallback_emits_evidence(tmp_path):
    """bench.py against a dead accelerator must still exit 0 quickly with
    a first-class fallback record, per-config lines, and a summary line —
    the r03 failure mode was rc=124 with no evidence trail."""
    hook = tmp_path / "hostile"
    hook.mkdir()
    (hook / "sitecustomize.py").write_text(_HOSTILE_SITECUSTOMIZE)
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"
    env["JAX_PLATFORMS"] = "axon"
    env["PYTHONPATH"] = str(hook)
    env.pop("XLA_FLAGS", None)
    env.pop("SPARK_TPU_ACCEL_HEALTH", None)
    env["SPARK_TPU_BENCH_BUDGET"] = "240"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "groupby"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, (r.stderr or "")[-3000:]
    import json
    lines = [json.loads(x) for x in r.stdout.splitlines() if x.strip()]
    assert any("ACCELERATOR UNAVAILABLE" in l["metric"] for l in lines)
    assert any("geomean" in l["metric"] for l in lines), r.stdout


def test_accelerator_probe_requires_execution(monkeypatch):
    """An accelerator that 'enumerates but cannot execute' must probe
    unhealthy: the probe source executes compute, so a failing body means
    accelerator_healthy() is False."""
    import __graft_entry__ as g

    monkeypatch.setattr(
        g, "_PROBE_SRC",
        "import jax; jax.devices(); raise SystemExit(1)")
    os.environ.pop(g._HEALTH_CACHE_VAR, None)
    try:
        assert g.accelerator_healthy() is False
        # result is memoized for this process and its children
        assert os.environ[g._HEALTH_CACHE_VAR] == "0"
        monkeypatch.setattr(g, "_PROBE_SRC", "print('PROBE_OK')")
        assert g.accelerator_healthy() is False  # cached, no re-probe
    finally:
        os.environ.pop(g._HEALTH_CACHE_VAR, None)


def test_accelerator_probe_healthy_cpu(monkeypatch):
    import __graft_entry__ as g

    os.environ.pop(g._HEALTH_CACHE_VAR, None)
    try:
        assert g.accelerator_healthy() is True
    finally:
        os.environ.pop(g._HEALTH_CACHE_VAR, None)


def test_cpu_subprocess_env_sanitized():
    from __graft_entry__ import cpu_subprocess_env

    base = dict(os.environ)
    base["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"
    base["AXON_LOOPBACK_RELAY"] = "1"
    base["TPU_SKIP_MDS_QUERY"] = "1"
    base["PYTHONPATH"] = "/root/.axon_site:/some/other"
    old = os.environ.copy()
    os.environ.update(base)
    try:
        env = cpu_subprocess_env(8)
    finally:
        os.environ.clear()
        os.environ.update(old)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert "AXON_LOOPBACK_RELAY" not in env
    assert "TPU_SKIP_MDS_QUERY" not in env
    assert "/root/.axon_site" not in env["PYTHONPATH"]
    assert "/some/other" in env["PYTHONPATH"]
    # first PYTHONPATH entry is the benign sitecustomize shadow
    shim = env["PYTHONPATH"].split(os.pathsep)[0]
    assert os.path.exists(os.path.join(shim, "sitecustomize.py"))
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
