"""Observability layer (spark_tpu/obs/): always-on tracing + per-operator
metrics with kernel attribution + EXPLAIN ANALYZE drift detection.

The hard constraint under test: collection adds ZERO kernel launches —
metrics/tracing on (the default) must measure identical KernelCache
launch deltas to metrics/tracing off, fusion on and off."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(23)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 11, n),
        "v": rng.integers(-40, 90, n),
    })).createOrReplaceTempView("obs_t")
    dim = pa.table({"dk": np.arange(11, dtype=np.int64),
                    "label": [f"l{i % 3}" for i in range(11)]})
    spark.createDataFrame(dim).createOrReplaceTempView("obs_dim")
    return spark


Q_AGG = "select k, sum(v) sv, count(*) c from obs_t where v > 0 group by k"
Q_JOIN = ("select label, sum(v) sv from obs_t join obs_dim on k = dk "
          "where v > 5 group by label")


def _launch_delta(spark, sql):
    spark.sql(sql).toArrow()  # warm: compiles + caches + memos
    before = dict(KC.launches_by_kind)
    spark.sql(sql).toArrow()
    after = dict(KC.launches_by_kind)
    return {k: v - before.get(k, 0) for k, v in after.items()
            if v != before.get(k, 0)}


# ---------------------------------------------------------------------------
# overhead guard: metrics + tracing add ZERO kernel launches
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
@pytest.mark.parametrize("sql", [Q_AGG, Q_JOIN], ids=["agg", "join+agg"])
def test_metrics_and_tracing_zero_launch_overhead(data, fusion, sql):
    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", fusion)
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.conf.set("spark.tpu.ui.operatorMetrics", "true")
        spark.conf.set("spark.tpu.trace.enabled", "true")
        with_obs = _launch_delta(spark, sql)
        spark.conf.set("spark.tpu.ui.operatorMetrics", "false")
        spark.conf.set("spark.tpu.trace.enabled", "false")
        without = _launch_delta(spark, sql)
        assert with_obs == without, (
            f"observability changed kernel dispatches: {with_obs} vs "
            f"{without}")
    finally:
        for k in ("spark.tpu.fusion.enabled", "spark.tpu.fusion.minRows",
                  "spark.tpu.ui.operatorMetrics", "spark.tpu.trace.enabled"):
            spark.conf.unset(k)


# ---------------------------------------------------------------------------
# per-operator kernel attribution
# ---------------------------------------------------------------------------

def test_plan_graph_attributes_launches_per_operator(data):
    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.sql(Q_AGG).toArrow()  # warm
        df = spark.sql(Q_AGG)
        df.toArrow()
        graph = df.query_execution.plan_graph()
        launched = {nd["op"]: nd["launches"] for nd in graph
                    if nd.get("launches")}
        assert launched, "no operator carries attributed launches"
        # the fused partial aggregate owns its fused_agg dispatches
        agg = [l for op, l in launched.items()
               if "HashAggregate" in op]
        assert agg and any("fused_agg" in l or "dagg" in l or "gagg" in l
                           for l in agg), launched
        # attributed per-op totals == the global measured delta shape
        total = sum(v for l in launched.values() for v in l.values())
        assert total > 0
        # fused member re-attribution rides the graph
        fused_nodes = [nd for nd in graph if nd.get("fused")]
        assert fused_nodes and any(
            "HashAggregate[partial]" in m
            for nd in fused_nodes for m in nd["fused"])
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


def test_attribution_total_matches_global_counter(data):
    """Sum of per-operator attributed launches == global per-query delta
    (no dispatch escapes the operator scope on the local scheduler)."""
    spark = data
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.sql(Q_AGG).toArrow()  # warm
        before = KC.launches
        df = spark.sql(Q_AGG)
        df.toArrow()
        global_delta = KC.launches - before
        graph = df.query_execution.plan_graph()
        attributed = sum(v for nd in graph
                         for v in (nd.get("launches") or {}).values())
        assert attributed == global_delta
    finally:
        spark.conf.unset("spark.tpu.fusion.minRows")


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------

def test_query_lifecycle_spans_and_chrome_export(data):
    spark = data
    mark = spark.tracer.mark()
    df = spark.sql("select k + 1 kk, v from obs_t where v > 10")
    df.toArrow()
    spans = spark.tracer.since(mark)
    cats = {s["cat"] for s in spans}
    names = {s["name"] for s in spans}
    assert "phase" in cats and "operator" in cats and "stage" in cats
    assert {"parse", "analysis", "planning", "execution",
            "collect"} <= names, names
    # multi-partition operator work records per-partition lane spans
    mark2 = spark.tracer.mark()
    spark.sql("select v from obs_t").repartition(4) \
        .filter("v > 0").toArrow()
    cats2 = {s["cat"] for s in spark.tracer.since(mark2)}
    assert "partition" in cats2, cats2
    # chrome export: metadata + complete events, nested, with kernel
    # attribution args on dispatching operator spans
    doc = spark.tracer.to_chrome_trace()
    evs = doc["traceEvents"]
    complete = [e for e in evs if e.get("ph") == "X"]
    assert complete and all("ts" in e and "dur" in e for e in complete)
    assert any((e.get("args") or {}).get("launches", 0) > 0
               for e in complete), "no span carries kernel attribution"


def test_tracer_ring_keeps_latest_spans_and_marks_survive_eviction():
    """Long-lived sessions must never go permanently dark: the buffer is
    a ring of the latest maxSpans, and mark()/since() sequence numbers
    stay correct across eviction."""
    from spark_tpu.obs.tracing import Tracer

    t = Tracer(enabled=True, max_spans=5)
    for i in range(8):
        with t.span(f"s{i}"):
            pass
    assert [s[0] for s in t.spans()] == [f"s{i}" for i in range(3, 8)]
    assert t.dropped == 3
    m = t.mark()
    with t.span("tail"):
        pass
    assert [d["name"] for d in t.since(m)] == ["tail"]


def test_chrome_trace_tracks_keyed_by_ident_and_name():
    """Python reuses thread idents for ephemeral lane threads — tracks
    must not merge two differently-named threads onto one label."""
    from spark_tpu.obs.tracing import to_chrome_trace

    spans = [("a", "c", 0.0, 1.0, 99, "lane-0", None),
             ("b", "c", 2.0, 1.0, 99, "lane-1", None)]  # reused ident
    doc = to_chrome_trace(spans)
    meta = [e for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"]
    assert {m["args"]["name"] for m in meta} == {"lane-0", "lane-1"}
    tids = {e["tid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(tids) == 2


def test_tracing_disable_stops_span_collection(data):
    spark = data
    spark.conf.set("spark.tpu.trace.enabled", "false")
    try:
        mark = spark.tracer.mark()
        spark.sql("select v from obs_t where v > 0").toArrow()
        assert spark.tracer.since(mark) == []
    finally:
        spark.conf.unset("spark.tpu.trace.enabled")


# ---------------------------------------------------------------------------
# event-log round-trip: metrics + spans → HistoryReader.summary
# ---------------------------------------------------------------------------

def test_event_log_roundtrip_surfaces_kernel_and_operator_totals(
        data, tmp_path):
    from spark_tpu.exec.listener import EventLoggingListener, HistoryReader

    spark = data
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="obsapp")
    spark.listener_bus.register(el)
    try:
        spark.sql(Q_AGG).toArrow()
        spark.sql(Q_AGG).toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(el)
    h = HistoryReader(log_dir)
    app = h.applications()[0]
    s = h.summary(app)
    assert s["queries"] >= 2
    # kernel.* counters replayed from the log
    assert s["kernel"].get("kernel.launches", 0) > 0, s["kernel"]
    assert "kernel_cache.launches" in s["kernel"]
    # per-operator totals aggregated over plan graphs
    assert any("HashAggregate" in op for op in s["operators"]), \
        s["operators"]
    agg = next(v for op, v in s["operators"].items()
               if "HashAggregate" in op)
    assert agg["rows"] > 0 and agg["launches"] > 0
    # spans rode the event log and replay into the summary
    assert s["span_count"] > 0 and s["span_total_ms"] > 0
    events = h.load(app)
    done = [e for e in events if e["event"] == "querySucceeded"]
    assert all("spans" in e for e in done)
    span_names = {sp["name"] for e in done for sp in e["spans"]}
    # the full lifecycle rides the event: parse (recorded in session.sql
    # before the QueryExecution exists) through execution and collect
    assert {"parse", "execution", "collect"} <= span_names, span_names


def test_parse_span_emitted_once_per_parse(data, tmp_path):
    """Re-collecting a DataFrame must not re-report a parse that never
    ran: the parse span rides the FIRST collect's event only."""
    from spark_tpu.exec.listener import EventLoggingListener, HistoryReader

    spark = data
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="reparse")
    spark.listener_bus.register(el)
    try:
        df = spark.sql("select count(*) c from obs_t")
        df.toArrow()
        df.toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(el)
    h = HistoryReader(log_dir)
    done = [e for e in h.load(h.applications()[0])
            if e["event"] == "querySucceeded"]
    assert len(done) == 2
    counts = [sum(1 for sp in e["spans"] if sp["name"] == "parse")
              for e in done]
    assert counts == [1, 0], counts


def test_parse_span_consumed_even_when_tracing_off_at_collect(
        data, tmp_path):
    """Parse spans attach at sql() time; an untraced first collect must
    still consume them so a later re-traced collect cannot mis-report a
    stale parse."""
    from spark_tpu.exec.listener import EventLoggingListener, HistoryReader

    spark = data
    df = spark.sql("select count(*) c from obs_t")   # tracing on: attach
    spark.conf.set("spark.tpu.trace.enabled", "false")
    try:
        df.toArrow()                                 # untraced collect
    finally:
        spark.conf.unset("spark.tpu.trace.enabled")
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="stale")
    spark.listener_bus.register(el)
    try:
        df.toArrow()                                 # re-traced collect
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(el)
    h = HistoryReader(log_dir)
    done = [e for e in h.load(h.applications()[0])
            if e["event"] == "querySucceeded"]
    assert not any(sp["name"] == "parse"
                   for e in done for sp in e["spans"])


def test_live_ui_summary_matches_history_shape(data):
    from spark_tpu.exec.ui import LiveStatusStore

    spark = data
    store = LiveStatusStore("obs-live")
    spark.listener_bus.register(store)
    try:
        spark.sql(Q_AGG).toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(store)
    s = store.summary("obs-live")
    assert s["queries"] >= 1 and "kernel" in s and "operators" in s
    assert "running" in s


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

def test_explain_analyze_renders_measured_vs_predicted(data, capsys):
    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.sql(Q_AGG).explain("analyze")
        out = capsys.readouterr().out
        assert "EXPLAIN ANALYZE" in out
        assert "predicted vs measured" in out
        assert "rows=" in out and "launches=" in out
        assert "fused:" in out          # member re-attribution rendered
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_explain_analyze_tpcds_mini_zero_unexplained_drift(spark, enabled):
    """Acceptance: q3/q7 show per-operator rows/wall-ms/attributed
    launches (including inside fused stages) with zero unexplained
    drift, fusion on and off."""
    from tests.test_plan_analysis import Q3, Q7
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.fusion.enabled", enabled)
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        for sql in (Q3, Q7):
            report = spark.sql(sql).query_execution.analyzed_report()
            assert not report.has_unexplained_drift, report.render()
            assert report.prediction_exact
            assert report.predicted == report.measured
            # every executed operator carries rows + wall-ms
            executed = [nd for nd in report.nodes if nd["ms"] is not None]
            assert executed
            assert all(nd["rows"] is not None for nd in executed)
            # kernel attribution reached inside the plan
            assert any(nd["launches"] for nd in report.nodes)
            if enabled == "true":
                fused = [nd for nd in report.nodes if nd["fused"]]
                assert fused, "no fused operators on TPC-DS mini plan"
                assert all(nd["launches"] for nd in fused)
            d = report.to_dict()
            assert d["prediction_exact"] and d["measured"] == d["predicted"]
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


def test_explain_analyze_forces_metrics_when_disabled(data):
    """EXPLAIN ANALYZE drives its own runs — it must annotate operators
    even in sessions that disable operatorMetrics (bench-style), and
    restore the setting afterwards."""
    spark = data
    spark.conf.set("spark.tpu.ui.operatorMetrics", "false")
    spark.conf.set("spark.tpu.metrics.kernelAttribution", "false")
    try:
        report = spark.sql(Q_AGG).query_execution.analyzed_report()
        assert any(nd["ms"] is not None for nd in report.nodes)
        assert any(nd["launches"] for nd in report.nodes)
        assert spark.conf.get("spark.tpu.ui.operatorMetrics") is False
        assert spark.conf.get("spark.tpu.metrics.kernelAttribution") is False
    finally:
        spark.conf.unset("spark.tpu.ui.operatorMetrics")
        spark.conf.unset("spark.tpu.metrics.kernelAttribution")


def test_explain_analyze_flags_min_rows_gate(spark, data):
    """Default minRows (≫ 5k rows) routes a fused plan to the unfused
    kernels at runtime — EXPLAIN ANALYZE must surface the gate decision
    as a first-class finding, with zero unexplained drift."""
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    try:
        report = spark.sql(Q_AGG).query_execution.analyzed_report()
        assert not report.has_unexplained_drift, report.render()
        assert any(f["kind"] == "minRows-gate" for f in report.findings), \
            report.findings
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")


# ---------------------------------------------------------------------------
# per-query span scoping (concurrency-safe replacement for mark/since)
# ---------------------------------------------------------------------------

def test_query_scope_tags_spans_disjointly():
    from spark_tpu.obs.tracing import Tracer, pop_query, push_query

    t = Tracer(enabled=True)
    tok = push_query("qA")
    try:
        with t.span("a1"):
            with t.span("a2"):
                pass
    finally:
        pop_query(tok)
    tok = push_query("qB")
    try:
        with t.span("b1"):
            pass
    finally:
        pop_query(tok)
    with t.span("untagged"):
        pass
    assert {s["name"] for s in t.spans_for("qA")} == {"a1", "a2"}
    assert {s["name"] for s in t.spans_for("qB")} == {"b1"}
    assert all(s["query"] == "qA" for s in t.spans_for("qA"))


def test_concurrent_collects_get_disjoint_query_spans(data):
    """Two collects racing on ONE shared session must not cross-attribute
    event spans: each querySucceeded event carries exactly its own
    lifecycle (one collect span) and none of the other query's operator
    spans — the failure mode of the old buffer-offset mark()/since()
    slicing."""
    import threading

    spark = data
    events = []
    spark.listener_bus.register(events.append)
    barrier = threading.Barrier(2)
    errors = []

    def run(sql):
        try:
            barrier.wait(timeout=30)
            for _ in range(3):
                spark.sql(sql).toArrow()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    q_plain = "select v from obs_t where v > 10"
    threads = [threading.Thread(target=run, args=(s,))
               for s in (Q_AGG, q_plain)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(events.append)
    assert not errors, errors
    done = [e for e in events if e.event == "querySucceeded"]
    assert len(done) == 6
    for e in done:
        names = [sp["name"] for sp in e.spans]
        assert names.count("collect") == 1, (e.query_id, names)
        assert names.count("execution") == 1, (e.query_id, names)
        is_agg = "HashAggregate" in (e.plan or "")
        agg_spans = [n for n in names if "HashAggregate" in n]
        if is_agg:
            assert agg_spans, names
        else:
            assert not agg_spans, (e.query_id, names)


def test_scoped_submit_preserves_attribution_and_query_scope():
    """Satellite regression: obs scope must follow work into thread
    POOLS via a copied contextvars Context per submit — a bare submit
    silently re-buckets launches to 'unattributed' and drops the query
    tag (pool threads start with an empty context)."""
    from concurrent.futures import ThreadPoolExecutor

    from spark_tpu.obs import metrics as OM
    from spark_tpu.obs.tracing import current_query, pop_query, push_query

    rec = OM.new_op_record()
    op_token = OM.push_op(rec, "PoolOp")
    q_token = push_query("q-pool")
    try:
        with ThreadPoolExecutor(2) as pool:
            futs = [OM.scoped_submit(pool, OM.record_kernel_launch, "probe")
                    for _ in range(3)]
            for f in futs:
                f.result()
            scoped_op = OM.scoped_submit(pool, OM.current_op_name).result()
            scoped_q = OM.scoped_submit(pool, current_query).result()
            bare_op = pool.submit(OM.current_op_name).result()
    finally:
        pop_query(q_token)
        OM.pop_op(op_token)
    assert rec["kinds"] == {"probe": 3} and rec["launch_total"] == 3
    assert scoped_op == "PoolOp" and scoped_q == "q-pool"
    assert bare_op is None  # the hazard scoped_submit exists to prevent


# ---------------------------------------------------------------------------
# Perfetto flow events: phase → stage → partition-lane arrows
# ---------------------------------------------------------------------------

def _flow_edges(doc):
    """(source complete event, dest complete event) per exported flow."""
    evs = doc["traceEvents"]
    complete = [e for e in evs if e.get("ph") == "X"]

    def enclosing(fe):
        best = None
        for sp in complete:
            if sp["pid"] == fe["pid"] and sp["tid"] == fe["tid"] and \
                    sp["ts"] - 1 <= fe["ts"] <= sp["ts"] + sp["dur"] + 1:
                if best is None or sp["dur"] < best["dur"]:
                    best = sp
        return best

    starts = {e["id"]: e for e in evs if e.get("ph") == "s"}
    ends = {e["id"]: e for e in evs if e.get("ph") == "f"}
    assert set(starts) == set(ends), "unpaired flow events"
    return [(enclosing(starts[i]), enclosing(ends[i])) for i in starts]


def test_flow_events_link_execution_stage_and_lanes(data):
    spark = data
    spark.sql("select v from obs_t").repartition(4) \
        .filter("v > 0").toArrow()
    doc = spark.tracer.to_chrome_trace()
    edges = _flow_edges(doc)
    assert edges, "no flow arrows exported"
    assert all(src is not None and dst is not None for src, dst in edges), \
        "flow endpoint does not land inside a span"
    kinds = {(src["name"].split("[")[0].split("-")[0], dst["cat"])
             for src, dst in edges}
    # execution phase → stage arrows and stage → partition-lane arrows
    assert any(src["name"] == "execution" and
               dst["name"].startswith("stage-")
               for src, dst in edges), kinds
    assert any(dst["cat"] == "partition" for _, dst in edges), kinds


# ---------------------------------------------------------------------------
# cluster mode: worker-side metric/span shipping round trip
# ---------------------------------------------------------------------------

def _cq(spark):
    """Shuffle+agg over the cluster: the explicit repartition keeps a
    round-robin map stage and a hash-exchange map stage in the plan even
    on single-partition input (a partial-only aggregate would collapse
    to one local stage and never ship)."""
    import spark_tpu.api.functions as F

    return (spark.sql("select k, v from cobs_t").repartition(3)
            .groupBy("k").agg(F.sum("v").alias("sv"),
                              F.count("k").alias("c")))


def _cobs_table():
    rng = np.random.default_rng(41)
    n = 6000
    return pa.table({"k": rng.integers(0, 7, n),
                     "v": rng.integers(-30, 70, n)})


@pytest.fixture(scope="module")
def cluster_spark():
    """Session over a 2-worker local process cluster (shuffle+agg plans
    ship their map stages into worker processes). AQE off so local and
    cluster runs execute the identical static plan."""
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("obs-cluster", {
        "spark.sql.shuffle.partitions": "3",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
    })
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)
    s.createDataFrame(_cobs_table()).createOrReplaceTempView("cobs_t")
    yield s
    s.stop()


def _rollup(graph):
    """plan_graph → {(metric id, op): (rows, batches)} for executed ops."""
    return {(nd["id"], nd["op"]): (nd["rows"], nd.get("batches"))
            for nd in graph if nd.get("rows") is not None}


def test_cluster_metrics_merge_matches_local_rollup(cluster_spark):
    """Worker-shipped per-operator records must merge to the SAME rollup
    the purely-local scheduler measures: identical plan → identical
    per-node rows/batches, metric-id for metric-id."""
    from spark_tpu.api.session import TpuSession

    df = _cq(cluster_spark)
    df.toArrow()
    remote = cluster_spark._metrics.snapshot()["counters"].get(
        "scheduler.stages_remote", 0)
    assert remote >= 1, "query never shipped a stage to a worker"
    cluster_rollup = _rollup(df.query_execution.plan_graph())
    assert cluster_rollup, "cluster plan graph carries no operator rows"

    local = TpuSession("obs-local-ref", {
        "spark.sql.shuffle.partitions": "3",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
    })
    try:
        local.createDataFrame(_cobs_table()) \
            .createOrReplaceTempView("cobs_t")
        ldf = _cq(local)
        ldf.toArrow()
        local_rollup = _rollup(ldf.query_execution.plan_graph())
    finally:
        local.stop()
    assert cluster_rollup == local_rollup, (
        f"cluster rollup {cluster_rollup} != local {local_rollup}")


def test_cluster_spans_include_worker_tracks(cluster_spark):
    spark = cluster_spark
    mark = spark.tracer.mark()
    _cq(spark).toArrow()
    spans = spark.tracer.since(mark)
    worker = [s for s in spans
              if str(s.get("thread", "")).startswith("worker:")]
    assert worker, f"no worker-track spans in {sorted({s['thread'] for s in spans})}"
    cats = {s["cat"] for s in worker}
    # the task root span and the operator spans inside it both shipped
    assert "worker" in cats and "operator" in cats, cats
    # worker spans re-tagged to the driver's query scope
    assert all("query" in s for s in worker), worker[:3]


def test_cluster_attribution_total_matches_driver_plus_worker(cluster_spark):
    """No dispatch escapes attribution across the process boundary: the
    per-operator attributed-launch total equals the driver KernelCache
    delta plus the worker-shipped launch deltas."""
    spark = cluster_spark
    _cq(spark).toArrow()  # warm both worker processes' caches
    before = KC.launches
    df = _cq(spark)
    df.toArrow()
    driver_delta = KC.launches - before
    ctx = df.query_execution._last_ctx
    worker_kinds = ctx.worker_kernel_kinds or {}
    assert worker_kinds, "workers shipped no kernel-launch deltas"
    graph = df.query_execution.plan_graph()
    attributed = sum(v for nd in graph
                     for v in (nd.get("launches") or {}).values())
    assert attributed == driver_delta + sum(worker_kinds.values()), (
        f"attributed {attributed} != driver {driver_delta} + worker "
        f"{worker_kinds}")


def test_cluster_explain_analyze_no_unexplained_drift(cluster_spark):
    """Acceptance: cluster-mode EXPLAIN ANALYZE reports non-empty
    per-operator metrics, zero unexplained drift, and an attributed
    total equal to the measured driver+worker launch total."""
    report = _cq(cluster_spark).query_execution.analyzed_report()
    assert not report.has_unexplained_drift, report.render()
    executed = [nd for nd in report.nodes if nd["ms"] is not None]
    assert executed and any(nd["launches"] for nd in report.nodes), \
        report.render()
    attributed = sum(v for nd in report.nodes
                     for v in (nd.get("launches") or {}).values())
    assert attributed == sum(report.measured.values()), report.render()


def test_cluster_trace_exports_cross_process_flow_arrows(cluster_spark):
    """The exported trace draws arrows across the process boundary:
    stage → worker task (shipped flow parent) and map task →
    reduce-side fetch (deterministic shuffle-derived flow ids)."""
    spark = cluster_spark
    _cq(spark).toArrow()
    doc = spark.tracer.to_chrome_trace()
    edges = [(s, d) for s, d in _flow_edges(doc)
             if s is not None and d is not None]
    assert any(d["cat"] == "worker" for _, d in edges), \
        "no stage → worker-task flow arrow"
    assert any(d["name"].startswith("fetch[") and s["cat"] == "worker"
               for s, d in edges), "no map-task → reduce-fetch flow arrow"
