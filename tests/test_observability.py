"""Observability layer (spark_tpu/obs/): always-on tracing + per-operator
metrics with kernel attribution + EXPLAIN ANALYZE drift detection.

The hard constraint under test: collection adds ZERO kernel launches —
metrics/tracing on (the default) must measure identical KernelCache
launch deltas to metrics/tracing off, fusion on and off."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(23)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 11, n),
        "v": rng.integers(-40, 90, n),
    })).createOrReplaceTempView("obs_t")
    dim = pa.table({"dk": np.arange(11, dtype=np.int64),
                    "label": [f"l{i % 3}" for i in range(11)]})
    spark.createDataFrame(dim).createOrReplaceTempView("obs_dim")
    return spark


Q_AGG = "select k, sum(v) sv, count(*) c from obs_t where v > 0 group by k"
Q_JOIN = ("select label, sum(v) sv from obs_t join obs_dim on k = dk "
          "where v > 5 group by label")


def _launch_delta(spark, sql):
    spark.sql(sql).toArrow()  # warm: compiles + caches + memos
    before = dict(KC.launches_by_kind)
    spark.sql(sql).toArrow()
    after = dict(KC.launches_by_kind)
    return {k: v - before.get(k, 0) for k, v in after.items()
            if v != before.get(k, 0)}


# ---------------------------------------------------------------------------
# overhead guard: metrics + tracing add ZERO kernel launches
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
@pytest.mark.parametrize("sql", [Q_AGG, Q_JOIN], ids=["agg", "join+agg"])
def test_metrics_and_tracing_zero_launch_overhead(data, fusion, sql):
    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", fusion)
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.conf.set("spark.tpu.ui.operatorMetrics", "true")
        spark.conf.set("spark.tpu.trace.enabled", "true")
        with_obs = _launch_delta(spark, sql)
        spark.conf.set("spark.tpu.ui.operatorMetrics", "false")
        spark.conf.set("spark.tpu.trace.enabled", "false")
        without = _launch_delta(spark, sql)
        assert with_obs == without, (
            f"observability changed kernel dispatches: {with_obs} vs "
            f"{without}")
    finally:
        for k in ("spark.tpu.fusion.enabled", "spark.tpu.fusion.minRows",
                  "spark.tpu.ui.operatorMetrics", "spark.tpu.trace.enabled"):
            spark.conf.unset(k)


# ---------------------------------------------------------------------------
# per-operator kernel attribution
# ---------------------------------------------------------------------------

def test_plan_graph_attributes_launches_per_operator(data):
    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.sql(Q_AGG).toArrow()  # warm
        df = spark.sql(Q_AGG)
        df.toArrow()
        graph = df.query_execution.plan_graph()
        launched = {nd["op"]: nd["launches"] for nd in graph
                    if nd.get("launches")}
        assert launched, "no operator carries attributed launches"
        # the fused partial aggregate owns its fused_agg dispatches
        agg = [l for op, l in launched.items()
               if "HashAggregate" in op]
        assert agg and any("fused_agg" in l or "dagg" in l or "gagg" in l
                           for l in agg), launched
        # attributed per-op totals == the global measured delta shape
        total = sum(v for l in launched.values() for v in l.values())
        assert total > 0
        # fused member re-attribution rides the graph
        fused_nodes = [nd for nd in graph if nd.get("fused")]
        assert fused_nodes and any(
            "HashAggregate[partial]" in m
            for nd in fused_nodes for m in nd["fused"])
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


def test_attribution_total_matches_global_counter(data):
    """Sum of per-operator attributed launches == global per-query delta
    (no dispatch escapes the operator scope on the local scheduler)."""
    spark = data
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.sql(Q_AGG).toArrow()  # warm
        before = KC.launches
        df = spark.sql(Q_AGG)
        df.toArrow()
        global_delta = KC.launches - before
        graph = df.query_execution.plan_graph()
        attributed = sum(v for nd in graph
                         for v in (nd.get("launches") or {}).values())
        assert attributed == global_delta
    finally:
        spark.conf.unset("spark.tpu.fusion.minRows")


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------

def test_query_lifecycle_spans_and_chrome_export(data):
    spark = data
    mark = spark.tracer.mark()
    df = spark.sql("select k + 1 kk, v from obs_t where v > 10")
    df.toArrow()
    spans = spark.tracer.since(mark)
    cats = {s["cat"] for s in spans}
    names = {s["name"] for s in spans}
    assert "phase" in cats and "operator" in cats and "stage" in cats
    assert {"parse", "analysis", "planning", "execution",
            "collect"} <= names, names
    # multi-partition operator work records per-partition lane spans
    mark2 = spark.tracer.mark()
    spark.sql("select v from obs_t").repartition(4) \
        .filter("v > 0").toArrow()
    cats2 = {s["cat"] for s in spark.tracer.since(mark2)}
    assert "partition" in cats2, cats2
    # chrome export: metadata + complete events, nested, with kernel
    # attribution args on dispatching operator spans
    doc = spark.tracer.to_chrome_trace()
    evs = doc["traceEvents"]
    complete = [e for e in evs if e.get("ph") == "X"]
    assert complete and all("ts" in e and "dur" in e for e in complete)
    assert any((e.get("args") or {}).get("launches", 0) > 0
               for e in complete), "no span carries kernel attribution"


def test_tracer_ring_keeps_latest_spans_and_marks_survive_eviction():
    """Long-lived sessions must never go permanently dark: the buffer is
    a ring of the latest maxSpans, and mark()/since() sequence numbers
    stay correct across eviction."""
    from spark_tpu.obs.tracing import Tracer

    t = Tracer(enabled=True, max_spans=5)
    for i in range(8):
        with t.span(f"s{i}"):
            pass
    assert [s[0] for s in t.spans()] == [f"s{i}" for i in range(3, 8)]
    assert t.dropped == 3
    m = t.mark()
    with t.span("tail"):
        pass
    assert [d["name"] for d in t.since(m)] == ["tail"]


def test_chrome_trace_tracks_keyed_by_ident_and_name():
    """Python reuses thread idents for ephemeral lane threads — tracks
    must not merge two differently-named threads onto one label."""
    from spark_tpu.obs.tracing import to_chrome_trace

    spans = [("a", "c", 0.0, 1.0, 99, "lane-0", None),
             ("b", "c", 2.0, 1.0, 99, "lane-1", None)]  # reused ident
    doc = to_chrome_trace(spans)
    meta = [e for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"]
    assert {m["args"]["name"] for m in meta} == {"lane-0", "lane-1"}
    tids = {e["tid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(tids) == 2


def test_tracing_disable_stops_span_collection(data):
    spark = data
    spark.conf.set("spark.tpu.trace.enabled", "false")
    try:
        mark = spark.tracer.mark()
        spark.sql("select v from obs_t where v > 0").toArrow()
        assert spark.tracer.since(mark) == []
    finally:
        spark.conf.unset("spark.tpu.trace.enabled")


# ---------------------------------------------------------------------------
# event-log round-trip: metrics + spans → HistoryReader.summary
# ---------------------------------------------------------------------------

def test_event_log_roundtrip_surfaces_kernel_and_operator_totals(
        data, tmp_path):
    from spark_tpu.exec.listener import EventLoggingListener, HistoryReader

    spark = data
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="obsapp")
    spark.listener_bus.register(el)
    try:
        spark.sql(Q_AGG).toArrow()
        spark.sql(Q_AGG).toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(el)
    h = HistoryReader(log_dir)
    app = h.applications()[0]
    s = h.summary(app)
    assert s["queries"] >= 2
    # kernel.* counters replayed from the log
    assert s["kernel"].get("kernel.launches", 0) > 0, s["kernel"]
    assert "kernel_cache.launches" in s["kernel"]
    # per-operator totals aggregated over plan graphs
    assert any("HashAggregate" in op for op in s["operators"]), \
        s["operators"]
    agg = next(v for op, v in s["operators"].items()
               if "HashAggregate" in op)
    assert agg["rows"] > 0 and agg["launches"] > 0
    # spans rode the event log and replay into the summary
    assert s["span_count"] > 0 and s["span_total_ms"] > 0
    events = h.load(app)
    done = [e for e in events if e["event"] == "querySucceeded"]
    assert all("spans" in e for e in done)
    span_names = {sp["name"] for e in done for sp in e["spans"]}
    # the full lifecycle rides the event: parse (recorded in session.sql
    # before the QueryExecution exists) through execution and collect
    assert {"parse", "execution", "collect"} <= span_names, span_names


def test_parse_span_emitted_once_per_parse(data, tmp_path):
    """Re-collecting a DataFrame must not re-report a parse that never
    ran: the parse span rides the FIRST collect's event only."""
    from spark_tpu.exec.listener import EventLoggingListener, HistoryReader

    spark = data
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="reparse")
    spark.listener_bus.register(el)
    try:
        df = spark.sql("select count(*) c from obs_t")
        df.toArrow()
        df.toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(el)
    h = HistoryReader(log_dir)
    done = [e for e in h.load(h.applications()[0])
            if e["event"] == "querySucceeded"]
    assert len(done) == 2
    counts = [sum(1 for sp in e["spans"] if sp["name"] == "parse")
              for e in done]
    assert counts == [1, 0], counts


def test_parse_span_consumed_even_when_tracing_off_at_collect(
        data, tmp_path):
    """Parse spans attach at sql() time; an untraced first collect must
    still consume them so a later re-traced collect cannot mis-report a
    stale parse."""
    from spark_tpu.exec.listener import EventLoggingListener, HistoryReader

    spark = data
    df = spark.sql("select count(*) c from obs_t")   # tracing on: attach
    spark.conf.set("spark.tpu.trace.enabled", "false")
    try:
        df.toArrow()                                 # untraced collect
    finally:
        spark.conf.unset("spark.tpu.trace.enabled")
    log_dir = str(tmp_path / "events")
    el = EventLoggingListener(log_dir, app_id="stale")
    spark.listener_bus.register(el)
    try:
        df.toArrow()                                 # re-traced collect
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(el)
    h = HistoryReader(log_dir)
    done = [e for e in h.load(h.applications()[0])
            if e["event"] == "querySucceeded"]
    assert not any(sp["name"] == "parse"
                   for e in done for sp in e["spans"])


def test_live_ui_summary_matches_history_shape(data):
    from spark_tpu.exec.ui import LiveStatusStore

    spark = data
    store = LiveStatusStore("obs-live")
    spark.listener_bus.register(store)
    try:
        spark.sql(Q_AGG).toArrow()
        spark.listener_bus.wait_empty()
    finally:
        spark.listener_bus.unregister(store)
    s = store.summary("obs-live")
    assert s["queries"] >= 1 and "kernel" in s and "operators" in s
    assert "running" in s


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

def test_explain_analyze_renders_measured_vs_predicted(data, capsys):
    spark = data
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        spark.sql(Q_AGG).explain("analyze")
        out = capsys.readouterr().out
        assert "EXPLAIN ANALYZE" in out
        assert "predicted vs measured" in out
        assert "rows=" in out and "launches=" in out
        assert "fused:" in out          # member re-attribution rendered
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


@pytest.mark.parametrize("enabled", ["true", "false"])
def test_explain_analyze_tpcds_mini_zero_unexplained_drift(spark, enabled):
    """Acceptance: q3/q7 show per-operator rows/wall-ms/attributed
    launches (including inside fused stages) with zero unexplained
    drift, fusion on and off."""
    from tests.test_plan_analysis import Q3, Q7
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.fusion.enabled", enabled)
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    try:
        for sql in (Q3, Q7):
            report = spark.sql(sql).query_execution.analyzed_report()
            assert not report.has_unexplained_drift, report.render()
            assert report.prediction_exact
            assert report.predicted == report.measured
            # every executed operator carries rows + wall-ms
            executed = [nd for nd in report.nodes if nd["ms"] is not None]
            assert executed
            assert all(nd["rows"] is not None for nd in executed)
            # kernel attribution reached inside the plan
            assert any(nd["launches"] for nd in report.nodes)
            if enabled == "true":
                fused = [nd for nd in report.nodes if nd["fused"]]
                assert fused, "no fused operators on TPC-DS mini plan"
                assert all(nd["launches"] for nd in fused)
            d = report.to_dict()
            assert d["prediction_exact"] and d["measured"] == d["predicted"]
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
        spark.conf.unset("spark.tpu.fusion.minRows")


def test_explain_analyze_forces_metrics_when_disabled(data):
    """EXPLAIN ANALYZE drives its own runs — it must annotate operators
    even in sessions that disable operatorMetrics (bench-style), and
    restore the setting afterwards."""
    spark = data
    spark.conf.set("spark.tpu.ui.operatorMetrics", "false")
    spark.conf.set("spark.tpu.metrics.kernelAttribution", "false")
    try:
        report = spark.sql(Q_AGG).query_execution.analyzed_report()
        assert any(nd["ms"] is not None for nd in report.nodes)
        assert any(nd["launches"] for nd in report.nodes)
        assert spark.conf.get("spark.tpu.ui.operatorMetrics") is False
        assert spark.conf.get("spark.tpu.metrics.kernelAttribution") is False
    finally:
        spark.conf.unset("spark.tpu.ui.operatorMetrics")
        spark.conf.unset("spark.tpu.metrics.kernelAttribution")


def test_explain_analyze_flags_min_rows_gate(spark, data):
    """Default minRows (≫ 5k rows) routes a fused plan to the unfused
    kernels at runtime — EXPLAIN ANALYZE must surface the gate decision
    as a first-class finding, with zero unexplained drift."""
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    try:
        report = spark.sql(Q_AGG).query_execution.analyzed_report()
        assert not report.has_unexplained_drift, report.render()
        assert any(f["kind"] == "minRows-gate" for f in report.findings), \
            report.findings
    finally:
        spark.conf.unset("spark.tpu.fusion.enabled")
