"""bench.py --smoke: the benchmark harness itself is tier-1-gated — a
broken bench path would otherwise only surface in the (slow) BENCH run."""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_runs_and_reports_kernel_launches():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_TPU_BENCH_SCALE"] = "0.001"  # CI: smallest honest scale
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the tunnel from CI
    r = subprocess.run(
        [sys.executable, os.path.join(HERE, "bench.py"), "--smoke",
         "groupby", "join"],
        env=env, cwd=HERE, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    recs = [json.loads(line) for line in r.stdout.splitlines()
            if line.strip().startswith("{")]
    assert recs, r.stdout
    # dispatch-count evidence present for each measured config
    with_launches = [x for x in recs if "kernel_launches" in x]
    assert len(with_launches) >= 2, recs
    assert all(x["kernel_launches"] > 0 for x in with_launches), recs
    # summary line last
    assert "geomean" in recs[-1]["metric"]
