"""Multi-tenant serving (spark_tpu/serve/ + connect/sql_endpoint.py).

Contract under test: weighted fair pools grant contended slots in
weight proportion (deterministically — stride scheduling over a
submit/release schedule), bounded queues reject on timeout/overflow,
HBM admission holds queries back against the aggregate in-flight
reservation, per-connection cloned sessions isolate SET/temp views
while sharing the engine, concurrent collects produce scope-exact
disjoint counter deltas (zero `overlapped` profiles, attributed totals
summing to the global KernelCache delta), drain finishes in-flight
work and rejects new work with typed errors, and the serving layer
present-but-idle adds zero kernel launches.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.config import SQLConf
from spark_tpu.errors import (
    AdmissionTimeout, PoolQueueFull, ServerDraining,
)
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
from spark_tpu.serve import FairScheduler, QueryService, pool_configs
from spark_tpu.serve.loadgen import run_serve_load


def _session(name, extra=None):
    from spark_tpu import TpuSession

    # capacity 1<<11 on purpose: kernels are cached per (structure,
    # signature, CAPACITY) process-globally, and test_profile_history
    # asserts cold-compile deltas on same-shaped queries at 1<<12 — a
    # shared capacity would let this file warm its kernels and break
    # that suite under reordering (pytest-xdist, --lf, subsets)
    conf = {"spark.sql.shuffle.partitions": 2,
            "spark.tpu.batch.capacity": 1 << 11,
            "spark.tpu.fusion.minRows": "0"}
    conf.update(extra or {})
    return TpuSession(name, conf)


def _seed(s, view="srv_t", n=4000, seed=9):
    rng = np.random.default_rng(seed)
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 12, n).astype(np.int64),
        "v": rng.integers(-30, 100, n).astype(np.int64),
    })).createOrReplaceTempView(view)


QA = "select k, sum(v) s from srv_t where v > 0 group by k"
QB = "select k, v from srv_t where v > 50 order by v limit 16"


# ---------------------------------------------------------------------------
# pools: config, fairness, rejection, HBM admission
# ---------------------------------------------------------------------------

class TestFairScheduler:
    def test_pool_config_parsing(self):
        conf = SQLConf({
            "spark.tpu.scheduler.pools": "dash:2, batch , etl:0.5",
            "spark.tpu.scheduler.pool.batch.weight": "3",
            "spark.tpu.scheduler.pool.batch.maxConcurrent": "1",
            "spark.tpu.scheduler.pool.batch.queueSize": "7",
            "spark.tpu.scheduler.pool.batch.queueTimeout": "0.25",
            "spark.tpu.scheduler.pool.batch.hbmBudget": "4096",
            "spark.tpu.serve.queueSize": "9",
        })
        pools = pool_configs(conf)
        assert set(pools) == {"default", "dash", "batch", "etl"}
        assert pools["dash"].weight == 2.0
        assert pools["etl"].weight == 0.5
        assert pools["default"].weight == 1.0
        # per-pool keys override the declaration and the global default
        b = pools["batch"]
        assert (b.weight, b.max_concurrent, b.queue_size,
                b.queue_timeout_s, b.hbm_budget) == (3.0, 1, 7, 0.25,
                                                     4096)
        assert pools["dash"].queue_size == 9     # global default applies

    def test_weighted_fair_share_is_deterministic(self):
        conf = SQLConf({"spark.tpu.scheduler.pools": "a:2,b:1",
                        "spark.tpu.serve.maxConcurrent": 1})
        sched = FairScheduler(conf)
        tickets = []
        for _ in range(9):
            tickets.append(sched.submit("a"))
            tickets.append(sched.submit("b"))
        for _ in range(len(tickets)):
            running = [t for t in tickets
                       if t.granted and not t.released]
            assert len(running) == 1, "maxConcurrent=1 violated"
            sched.release(running[0])
        assert all(t.released for t in tickets)
        grants = sched.contended_grants()
        # stride scheduling: while both queues are backlogged the 2:1
        # weights yield a 2:1 grant ratio, deterministically
        assert grants["a"] + grants["b"] >= 9
        assert abs(grants["a"] - 2 * grants["b"]) <= 2, grants
        assert sched.fairness_ratio() <= 1.25
        assert sched.balanced()

    def test_idle_pool_banks_no_credit(self):
        conf = SQLConf({"spark.tpu.scheduler.pools": "a:1,b:1",
                        "spark.tpu.serve.maxConcurrent": 1})
        sched = FairScheduler(conf)
        # pool a runs alone for a while
        for _ in range(6):
            t = sched.submit("a")
            sched.wait(t, timeout=1.0)
            sched.release(t)
        # b wakes: it must NOT get 6 catch-up grants in a row
        tickets = [sched.submit(p) for p in
                   ("a", "b", "a", "b", "a", "b")]
        order = []
        for _ in range(len(tickets)):
            running = [t for t in tickets
                       if t.granted and not t.released]
            assert len(running) == 1
            order.append(running[0].pool)
            sched.release(running[0])
        assert order.count("b") == 3
        assert "a" in order[:3], \
            f"idle pool b monopolized the contended window: {order}"

    def test_queue_timeout_rejection(self):
        conf = SQLConf({"spark.tpu.serve.maxConcurrent": 1})
        sched = FairScheduler(conf)
        holder = sched.submit("default")
        sched.wait(holder, timeout=1.0)
        blocked = sched.submit("default")
        t0 = time.perf_counter()
        with pytest.raises(AdmissionTimeout):
            sched.wait(blocked, timeout=0.05)
        assert time.perf_counter() - t0 < 2.0
        st = sched.status()["pools"]["default"]
        assert st["rejected_timeout"] == 1
        sched.release(holder)
        assert sched.balanced()

    def test_queue_full_rejection(self):
        conf = SQLConf({
            "spark.tpu.serve.maxConcurrent": 1,
            "spark.tpu.scheduler.pool.default.queueSize": "1",
        })
        sched = FairScheduler(conf)
        holder = sched.submit("default")
        sched.wait(holder, timeout=1.0)
        sched.submit("default")          # fills the single queue slot
        with pytest.raises(PoolQueueFull):
            sched.submit("default")
        assert sched.status()["pools"]["default"]["rejected_full"] == 1

    def test_hbm_admission_reserves_and_releases(self):
        conf = SQLConf({"spark.tpu.memory.budget": 100})
        sched = FairScheduler(conf)
        big = sched.submit("default", hbm=70)
        sched.wait(big, timeout=1.0)
        small = sched.submit("default", hbm=50)
        with pytest.raises(AdmissionTimeout):
            sched.wait(small, timeout=0.05)   # 70+50 > 100: must wait
        tiny = sched.submit("default", hbm=20)
        sched.wait(tiny, timeout=1.0)         # 70+20 <= 100: admitted
        sched.release(tiny)
        small = sched.submit("default", hbm=50)
        sched.release(big)
        sched.wait(small, timeout=1.0)        # freed budget admits it
        sched.release(small)
        assert sched.balanced()

    def test_per_pool_hbm_budget(self):
        conf = SQLConf({
            "spark.tpu.scheduler.pools": "tight",
            "spark.tpu.scheduler.pool.tight.hbmBudget": "64",
        })
        sched = FairScheduler(conf)
        a = sched.submit("tight", hbm=50)
        sched.wait(a, timeout=1.0)
        b = sched.submit("tight", hbm=30)
        with pytest.raises(AdmissionTimeout):
            sched.wait(b, timeout=0.05)
        # the default pool has no budget of its own — unaffected
        c = sched.submit("default", hbm=10_000)
        sched.wait(c, timeout=1.0)
        sched.release(a)
        sched.release(c)
        assert sched.in_flight() == 0


# ---------------------------------------------------------------------------
# session isolation
# ---------------------------------------------------------------------------

class TestSessionIsolation:
    def test_clone_isolates_set_and_temp_views(self):
        s = _session("srv-clone")
        try:
            _seed(s)
            c1 = s.newSession()
            c2 = s.newSession()
            # parent temp views read through to every clone
            assert c1.sql(QA).toArrow().num_rows > 0
            # SET is clone-local
            c1.sql("SET spark.sql.shuffle.partitions=5")
            assert int(c1.conf.get("spark.sql.shuffle.partitions")) == 5
            assert int(c2.conf.get("spark.sql.shuffle.partitions")) == 2
            assert int(s.conf.get("spark.sql.shuffle.partitions")) == 2
            # temp views are clone-local
            c1.sql("create temporary view c1v as select 1 a")
            assert c1.catalog.tableExists("c1v")
            assert not c2.catalog.tableExists("c1v")
            assert not s.catalog.tableExists("c1v")
            # clone stop() leaves the parent serviceable
            c1.stop()
            assert s.sql(QA).toArrow().num_rows > 0
        finally:
            s.stop()

    def test_clone_results_match_parent(self):
        s = _session("srv-clone-eq")
        try:
            _seed(s)
            want = s.sql(QA).toArrow().to_pylist()
            got = s.newSession().sql(QA).toArrow().to_pylist()
            assert sorted(got, key=str) == sorted(want, key=str)
        finally:
            s.stop()

    def test_shared_mode_optin(self):
        s = _session("srv-shared")
        try:
            svc = QueryService(s)
            assert svc.open_session("shared") is s
            assert svc.open_session() is not s
            s.conf.set("spark.tpu.serve.sessionMode", "shared")
            assert svc.open_session() is s
        finally:
            s.stop()

    def test_endpoint_connection_isolation(self):
        from spark_tpu.connect.sql_endpoint import SQLEndpoint, connect

        s = _session("srv-ep")
        try:
            _seed(s)
            ep = SQLEndpoint(s).start()
            try:
                with connect("127.0.0.1", ep.port) as a, \
                        connect("127.0.0.1", ep.port) as b:
                    ca, cb = a.cursor(), b.cursor()
                    # both connections see the server's temp view
                    ca.execute(QA)
                    assert ca.rowcount > 0
                    # SET on one connection is invisible on the other
                    ca.execute("SET spark.sql.shuffle.partitions=7")
                    cb.execute("SET spark.sql.shuffle.partitions")
                    assert cb.fetchall()[0][1] == "2"
                    # temp view on one connection is invisible too
                    ca.execute("create temporary view av "
                               "as select 41 x")
                    from spark_tpu.connect.sql_endpoint import Error

                    with pytest.raises(Error):
                        cb.execute("select * from av")
                    ca.execute("select * from av")
                    assert ca.fetchall() == [(41,)]
                    # per-pool status rides the wire
                    st = a.server_status()
                    assert "default" in st["pools"]
                    assert st["sessions_opened"] >= 2
            finally:
                ep.stop()
        finally:
            s.stop()

    def test_endpoint_shared_session_optin(self):
        from spark_tpu.connect.sql_endpoint import SQLEndpoint, connect

        s = _session("srv-ep-shared",
                     {"spark.tpu.serve.sessionMode": "shared"})
        try:
            _seed(s)
            ep = SQLEndpoint(s).start()
            try:
                with connect("127.0.0.1", ep.port) as a, \
                        connect("127.0.0.1", ep.port) as b:
                    ca, cb = a.cursor(), b.cursor()
                    ca.execute("SET spark.sql.shuffle.partitions=7")
                    cb.execute("SET spark.sql.shuffle.partitions")
                    # legacy shared-session server: SET visible across
                    assert cb.fetchall()[0][1] == "7"
            finally:
                ep.stop()
            s.conf.set("spark.sql.shuffle.partitions", 2)
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# concurrent counter isolation (the PR 12 carry-over, fixed)
# ---------------------------------------------------------------------------

class TestCounterIsolation:
    def test_concurrent_collects_attribute_disjoint_deltas(self,
                                                           tmp_path):
        s = _session("srv-conc",
                     {"spark.tpu.obs.profileDir": str(tmp_path)})
        try:
            _seed(s)
            # serial baselines (warm: compile + memo probes done)
            per_query = {}
            for q in (QA, QB):
                s.sql(q).toArrow()
                df = s.sql(q)
                df.toArrow()
                per_query[q] = dict(
                    df.query_execution._last_profile["launches_by_kind"])
            before = dict(KC.launches_by_kind)
            results = {}

            def run(q, rounds=3):
                out = []
                for _ in range(rounds):
                    df = s.sql(q)
                    df.toArrow()
                    out.append(df.query_execution._last_profile)
                results[q] = out

            threads = [threading.Thread(target=run, args=(q,))
                       for q in (QA, QB)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            delta = {k: v - before.get(k, 0)
                     for k, v in KC.launches_by_kind.items()
                     if v != before.get(k, 0)}
            merged: dict = {}
            for q, profs in results.items():
                for p in profs:
                    assert p is not None
                    assert not p.get("overlapped"), \
                        "scope-exact deltas must not need the guard"
                    # each racing profile reads exactly its own serial
                    # warm launch set — zero cross-contamination
                    assert p["launches_by_kind"] == per_query[q], \
                        (q, p["launches_by_kind"], per_query[q])
                    for k, v in p["launches_by_kind"].items():
                        merged[k] = merged.get(k, 0) + v
            # and the per-query deltas SUM to the global counter delta
            assert merged == delta
        finally:
            s.stop()

    def test_concurrent_load_zero_regressions(self, tmp_path):
        s = _session("srv-conc-reg",
                     {"spark.tpu.obs.profileDir": str(tmp_path)})
        try:
            _seed(s)
            s.sql(QA).toArrow()     # cold baseline profile
            svc = QueryService(s)
            report = run_serve_load(svc, [QA], sessions=4, reps=2)
            assert not report["errors"]
            # warm concurrent replays of an identical query must never
            # raise DETERMINISTIC regressions (scope-exact deltas,
            # increase-only gate); advisory wall-drift info findings
            # are timing-dependent on a loaded box and not asserted
            df = s.sql(QA)
            df.toArrow()
            errors = [f for f in df.query_execution._last_regressions
                      if f["severity"] == "error"]
            assert errors == [], errors
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# service: admission + drain semantics
# ---------------------------------------------------------------------------

class TestServiceAndDrain:
    def test_execute_sql_routes_pools_and_commands(self):
        s = _session("srv-svc", {
            "spark.tpu.scheduler.pools": "dash:2,batch:1"})
        try:
            _seed(s)
            svc = QueryService(s)
            c = svc.open_session()
            svc.execute_sql(c, "SET spark.tpu.scheduler.pool=dash")
            out = svc.execute_sql(c, QA)
            assert out.num_rows > 0
            st = svc.status()
            assert st["pools"]["dash"]["completed"] == 1
            # SET itself never took an admission slot
            assert st["pools"]["dash"]["admitted"] == 1
        finally:
            s.stop()

    def test_over_budget_query_rejects_plan_time(self):
        s = _session("srv-budget")
        try:
            _seed(s)
            svc = QueryService(s)
            c = svc.open_session()
            c.conf.set("spark.tpu.memory.budget", 512)
            from spark_tpu.obs.resources import MemoryBudgetExceeded

            launches = KC.launches
            with pytest.raises(MemoryBudgetExceeded):
                svc.execute_sql(c, QA)
            assert KC.launches == launches, \
                "admission rejection must dispatch nothing"
            assert svc.scheduler.balanced()
        finally:
            s.stop()

    def test_drain_finishes_inflight_rejects_new(self):
        s = _session("srv-drain")
        try:
            _seed(s)
            svc = QueryService(s)
            inflight = svc.scheduler.submit("default")
            svc.scheduler.wait(inflight, timeout=1.0)
            done = {}

            def drain():
                done["ok"] = svc.drain(timeout=10.0)

            th = threading.Thread(target=drain, daemon=True)
            th.start()
            deadline = time.monotonic() + 2.0
            while not svc.scheduler.draining \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            with pytest.raises(ServerDraining):
                svc.execute_sql(s, QA)
            with pytest.raises(ServerDraining):
                svc.open_session()
            svc.scheduler.release(inflight)   # in-flight work completes
            th.join(10.0)
            assert done.get("ok") is True
            assert svc.scheduler.balanced()
        finally:
            s.stop()

    def test_endpoint_stop_drains(self):
        from spark_tpu.connect.sql_endpoint import SQLEndpoint

        s = _session("srv-ep-drain")
        try:
            _seed(s)
            ep = SQLEndpoint(s).start()
            assert ep.stop() is True
            with pytest.raises(ServerDraining):
                ep.service.execute_sql(s, QA)
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# cluster serving leg
# ---------------------------------------------------------------------------

def test_cluster_serving_leg():
    s = _session("srv-cluster", {
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
        "spark.tpu.scheduler.pools": "dash:2,batch:1",
        "spark.tpu.serve.maxConcurrent": "2",
    })
    try:
        _seed(s)
        want = sorted(s.sql(QA).toArrow().to_pylist(), key=str)
        svc = QueryService(s)
        report = run_serve_load(svc, [QA], sessions=4, reps=2,
                                pools=("dash", "batch"))
        assert not report["errors"], report["errors"]
        assert report["pools"]["dash"]["completed"] == 4
        assert report["pools"]["batch"]["completed"] == 4
        # cloned serving sessions share the one cluster and agree with
        # the parent session's answer
        c = svc.open_session()
        got = sorted(svc.execute_sql(c, QA).to_pylist(), key=str)
        assert got == want
        assert svc.drain(timeout=10.0)
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# zero-launch guard: serving layer present but idle
# ---------------------------------------------------------------------------

def test_serving_layer_idle_is_zero_launch():
    from spark_tpu.connect.sql_endpoint import SQLEndpoint

    s = _session("srv-idle")
    try:
        _seed(s)

        def warm_delta():
            s.sql(QA).toArrow()
            before = dict(KC.launches_by_kind)
            s.sql(QA).toArrow()
            return {k: v - before.get(k, 0)
                    for k, v in KC.launches_by_kind.items()
                    if v != before.get(k, 0)}

        without = warm_delta()
        svc = QueryService(s)
        ep = SQLEndpoint(s, service=svc).start()
        try:
            svc.status()
            with_serving = warm_delta()
        finally:
            ep.stop()
        assert with_serving == without, (
            f"idle serving layer changed kernel dispatches: "
            f"{with_serving} vs {without}")
    finally:
        s.stop()
