"""Persistent compile & result caches (spark_tpu/exec/persist_cache.py +
utils/diskstore.py): fingerprint-keyed warm restarts and zero-launch
repeated queries.

Contract under test: everything is OFF while spark.tpu.cache.dir is
unset (the tier-1 default); with a dir configured, a repeated identical
query answers from the on-disk Arrow payload with ZERO kernel launches
and plan_lint predicts that hit path exactly; the key folds in the leaf
data identity, so a table append/overwrite invalidates (both through
the catalog write-path hook and by construction of the key); the
on-disk LRU stays inside its byte budget; non-deterministic plans
bypass the cache; the warm-start manifest collapses whole-tier
capacity retries; and fingerprints + XLA compile-cache entries survive
into REAL fresh processes (two-subprocess leg)."""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.exec.persist_cache as pc
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
from spark_tpu.utils.diskstore import JsonlRing


def _session(name, extra=None):
    from spark_tpu import TpuSession

    # capacity 2^11, not the 2^12 the other suites use: kernel-cache
    # keys include capacity, so these tests must not pre-compile kernel
    # shapes that test_profile_history's cold-compile assertions (which
    # run later in the same process) expect to be cold
    conf = {"spark.sql.shuffle.partitions": 2,
            "spark.tpu.batch.capacity": 1 << 11,
            "spark.tpu.fusion.minRows": "0"}
    conf.update(extra or {})
    return TpuSession(name, conf)


def _seed_table(s, view="pc_t", n=4000, seed=3):
    rng = np.random.default_rng(seed)
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 9, n),
        "v": rng.integers(-20, 80, n),
    })).createOrReplaceTempView(view)


Q = "select k, sum(v) s from pc_t where v > 0 group by k"


def _launch_delta(fn):
    before = dict(KC.launches_by_kind)
    out = fn()
    return out, {k: v - before.get(k, 0)
                 for k, v in KC.launches_by_kind.items()
                 if v != before.get(k, 0)}


# ---------------------------------------------------------------------------
# shared disk store
# ---------------------------------------------------------------------------

def test_diskstore_roundtrip_ring_and_torn_tail(tmp_path):
    ring = JsonlRing(str(tmp_path / "r.jsonl"), ring=4)
    for i in range(11):
        ring.append({"i": i})
    recs = ring.load()
    # compaction keeps the NEWEST ring-worth once the file doubles it
    assert [r["i"] for r in recs][-1] == 10
    assert len(recs) <= 8 and recs == sorted(recs, key=lambda r: r["i"])
    # torn tail from a concurrent append is skipped, not fatal
    with open(ring.path, "a") as f:
        f.write('{"i": 99, "tru')
    assert [r["i"] for r in ring.load()] == [r["i"] for r in recs]
    # re-entrant locked(): an append inside a locked block must not
    # deadlock (flock is per open-file-description)
    with ring.locked():
        ring.append({"i": 100})
    assert ring.load()[-1]["i"] == 100


# ---------------------------------------------------------------------------
# default-off safety
# ---------------------------------------------------------------------------

def test_caches_inert_without_cache_dir():
    s = _session("pc-off")
    try:
        _seed_table(s)
        assert pc.cache_root(s.conf) == ""
        assert not pc.compile_cache_active(s.conf)
        assert not pc.result_cache_active(s.conf)
        assert pc.result_cache_for(s.conf) is None
        s.sql(Q).toArrow()
        _out, delta = _launch_delta(lambda: s.sql(Q).toArrow())
        # the warm second run still LAUNCHES (no result cache): the
        # exact-count suites' ground rules are untouched by default
        assert sum(delta.values()) > 0
        counters = s._metrics.snapshot()["counters"]
        assert "result_cache.hit" not in counters
        assert "result_cache.miss" not in counters
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# result cache: zero-launch hits, exact plan prediction
# ---------------------------------------------------------------------------

def test_result_cache_hit_zero_launches_and_exact_prediction(tmp_path):
    s = _session("pc-hit", {"spark.tpu.cache.dir": str(tmp_path)})
    try:
        _seed_table(s)
        first = s.sql(Q).toArrow()          # populates
        rep = s.sql(Q).query_execution.analysis_report()
        assert rep.predicted_launches == {}, rep.predicted_launches
        assert rep.exact
        assert any("RESULT CACHE HIT" in n
                   for st in rep.stages for n in st.get("notes", ()))
        again, delta = _launch_delta(lambda: s.sql(Q).toArrow())
        assert delta == {}, f"result-cache hit launched kernels: {delta}"
        assert again.equals(first)
        counters = s._metrics.snapshot()["counters"]
        assert counters.get("result_cache.hit", 0) >= 1
        assert counters.get("result_cache.store", 0) == 1
    finally:
        s.stop()


def test_result_cache_distinguishes_data_and_literals(tmp_path):
    s = _session("pc-keys", {"spark.tpu.cache.dir": str(tmp_path)})
    try:
        _seed_table(s, n=4000, seed=3)
        a = s.sql(Q).toArrow()
        # different literal -> different fingerprint -> no stale hit
        b = s.sql(Q.replace("v > 0", "v > 50")).toArrow()
        assert not a.equals(b)
        # same schema + row count, different VALUES -> different
        # data-version component -> no stale hit
        _seed_table(s, n=4000, seed=4)
        c = s.sql(Q).toArrow()
        assert not a.equals(c)
    finally:
        s.stop()


def test_result_key_survives_fingerprint_sanitizer_collisions(tmp_path):
    """The telemetry fingerprint sanitizes hex-literal-like tokens
    (obs/history._VOLATILE) — fine for profile keying, unsound as the
    sole correctness key. The result key's exact-detail component must
    keep two queries apart that differ ONLY in a sanitized-away hex
    string literal, and a redefined same-name deterministic UDF must
    not serve the old function's cached answer."""
    import pyarrow.compute as pc_  # noqa: F401  (pa only)

    import spark_tpu.api.functions as F
    from spark_tpu.types import LongType

    s = _session("pc-collide", {"spark.tpu.cache.dir": str(tmp_path)})
    try:
        s.createDataFrame(pa.table({
            "id": pa.array(["a1b2c3d4e5f6a1b2", "ffffffffffff0000"]),
            "v": pa.array([1, 2], type=pa.int64()),
        })).createOrReplaceTempView("hex_t")
        qa = "select v from hex_t where id = 'a1b2c3d4e5f6a1b2'"
        qb = "select v from hex_t where id = 'ffffffffffff0000'"
        # sanity: both literals DO collide under the sanitized
        # fingerprint — the exact-detail component is what saves us
        from spark_tpu.obs.history import _sanitize
        assert _sanitize(qa) == _sanitize(qb)
        a = s.sql(qa).toArrow()          # populates under key(qa)
        b = s.sql(qb).toArrow()
        assert a.to_pylist() == [{"v": 1}]
        assert b.to_pylist() == [{"v": 2}], \
            "sanitizer collision served the wrong query's cached rows"
        # redefined same-name deterministic UDF: new code => new key
        u1 = F.udf(lambda x: x + 1, LongType(), deterministic=True)
        df1 = s.table("hex_t").select(u1(F.col("v")).alias("u"))
        r1 = df1.toArrow().to_pylist()
        u2 = F.udf(lambda x: x + 100, LongType(), deterministic=True)
        df2 = s.table("hex_t").select(u2(F.col("v")).alias("u"))
        r2 = df2.toArrow().to_pylist()
        assert r1 == [{"u": 2}, {"u": 3}]
        assert r2 == [{"u": 101}, {"u": 102}], \
            "redefined UDF served the old function's cached answer"
        # literals SHAPED like expr-id tokens (#N) must not ride the
        # expr-id ordinal remap: '#901' vs '#902' queries are distinct
        ta = s.sql("select '#901' tag, sum(v) s from hex_t").toArrow()
        tb = s.sql("select '#902' tag, sum(v) s from hex_t").toArrow()
        assert ta.to_pylist()[0]["tag"] == "#901"
        assert tb.to_pylist()[0]["tag"] == "#902", \
            "#N-shaped literal rode the expr-id remap into a collision"
    finally:
        s.stop()


def test_result_key_distinguishes_lossy_display_params(tmp_path):
    """Several operators' display strings are lossy — HashAggregateExec
    omits AggSpec.param (percentile's q), WindowExec omits partition/
    order keys and frame bounds — so a display-keyed result cache
    served one query's rows for another. The exact-detail component
    renders full node state (_render_value), keeping them apart, while
    the expr-id ordinal remap still lets an identical re-parsed query
    hit."""
    s = _session("pc-lossy", {"spark.tpu.cache.dir": str(tmp_path)})
    try:
        s.createDataFrame(pa.table({
            "k": pa.array([i % 3 for i in range(100)], type=pa.int64()),
            "v": pa.array(list(range(100)), type=pa.int64()),
        })).createOrReplaceTempView("t")
        p50 = s.sql("select percentile(v, 0.5) p from t").toArrow()
        p90 = s.sql("select percentile(v, 0.9) p from t").toArrow()
        assert p50.to_pylist() == [{"p": 49.0}]
        assert p90.to_pylist() == [{"p": 89.0}], \
            "percentile-param collision served the cached p50 answer"
        w1 = s.sql("select sum(v) over (partition by k order by v rows "
                   "between 1 preceding and current row) w from t").toArrow()
        w3 = s.sql("select sum(v) over (partition by k order by v rows "
                   "between 3 preceding and current row) w from t").toArrow()
        assert not w1.equals(w3), \
            "window-frame collision served the cached 1-preceding answer"
        wp = s.sql("select sum(v) over (partition by k) w from t").toArrow()
        wo = s.sql("select sum(v) over (order by k) w from t").toArrow()
        assert not wp.equals(wo), \
            "window-spec collision served the cached partition-by answer"
        # identical repeated query (fresh parse, fresh expr-ids) still
        # HITS: the ordinal remap keeps the exact detail stable
        _out, delta = _launch_delta(
            lambda: s.sql("select percentile(v, 0.5) p from t").toArrow())
        assert delta == {}, f"repeat missed the result cache: {delta}"
    finally:
        s.stop()


def test_result_key_distinguishes_slices_of_one_parent(tmp_path):
    """Slices share their parent table's buffers (the offset lives on
    the Array, not the buffer), so a raw-buffer content hash would make
    two DIFFERENT-valued slices collide — and with equal length, schema,
    and identical head/tail previews (the plan-detail preview elides the
    middle), nothing else in the key separates them. The IPC-stream
    content hash must keep them apart end to end."""
    a_vals = list(range(50))
    # same first/last 5 values as `a`, different middle
    b_vals = a_vals[:5] + [x + 1000 for x in a_vals[5:45]] + a_vals[45:]
    parent = pa.table({"v": pa.array(a_vals + b_vals, type=pa.int64())})
    a, b = parent.slice(0, 50), parent.slice(50, 50)
    assert not a.equals(b)
    assert pc._arrow_content_hash(a) != pc._arrow_content_hash(b)
    # equal values built independently still share one hash (the
    # cross-process sharing direction)
    assert pc._arrow_content_hash(pa.table(
        {"v": pa.array(a_vals, type=pa.int64())})) \
        == pc._arrow_content_hash(a)
    s = _session("pc-slice", {"spark.tpu.cache.dir": str(tmp_path)})
    try:
        s.createDataFrame(a).createOrReplaceTempView("slice_t")
        ra = s.sql("select sum(v) s from slice_t").toArrow()
        assert ra.to_pylist() == [{"s": sum(a_vals)}]
        s.createDataFrame(b).createOrReplaceTempView("slice_t")
        rb = s.sql("select sum(v) s from slice_t").toArrow()
        assert rb.to_pylist() == [{"s": sum(b_vals)}], \
            "slice-aliased content hash served the other slice's rows"
    finally:
        s.stop()


def test_nondeterministic_udf_bypasses_result_cache(tmp_path):
    import spark_tpu.api.functions as F
    from spark_tpu.types import LongType

    s = _session("pc-nondet", {"spark.tpu.cache.dir": str(tmp_path)})
    try:
        _seed_table(s)
        calls = {"n": 0}

        def bump(x):
            calls["n"] += 1
            return x

        udf = F.udf(bump, LongType(), deterministic=False)
        df = s.table("pc_t").select(udf(F.col("v")).alias("u"))
        key, _deps = pc.result_key(df.query_execution.physical, s.conf)
        assert key is None, "non-deterministic plan must be uncacheable"
        # nested carriers too: the determinism gate rides the render
        # walk, so a non-deterministic expression inside an aggregate's
        # AggSpec (not a direct node attribute) is still caught
        agg = s.table("pc_t").groupBy("k") \
            .agg(F.sum(udf(F.col("v"))).alias("u"))
        key2, _d2 = pc.result_key(agg.query_execution.physical, s.conf)
        assert key2 is None, \
            "non-deterministic agg input escaped the determinism gate"
        df.toArrow()
        _out, delta = _launch_delta(
            lambda: s.table("pc_t")
            .select(udf(F.col("v")).alias("u")).toArrow())
        assert sum(delta.values()) > 0, \
            "non-deterministic repeat must re-execute"
    finally:
        s.stop()


def test_result_cache_lru_stays_in_byte_budget(tmp_path):
    budget = 64 << 10
    s = _session("pc-lru", {"spark.tpu.cache.dir": str(tmp_path),
                            "spark.tpu.cache.result.maxBytes":
                            str(budget)})
    try:
        # 13 distinct queries (distinct literals -> distinct keys), each
        # result ~6.4 KiB — under the per-entry bound (budget/8), but
        # together well past the 64 KiB budget, so the LRU must evict
        rng = np.random.default_rng(9)
        s.createDataFrame(pa.table({
            "k": rng.integers(0, 1000, 4000),
            "v": rng.integers(0, 100, 4000),
        })).createOrReplaceTempView("lru_t")
        for i in range(13):
            s.sql(f"select k, v from lru_t where v >= {i} "
                  "limit 400").toArrow()
        rc = pc.result_cache_for(s.conf)
        assert rc.total_bytes() <= budget, \
            f"{rc.total_bytes()} > budget {budget}"
        counters = s._metrics.snapshot()["counters"]
        assert counters.get("result_cache.store", 0) >= 2
    finally:
        s.stop()


def test_hit_enforces_max_rows_miss_attributed_manifest_deduped(tmp_path):
    """Review-hardening contract: (a) a result-cache HIT still enforces
    spark.tpu.collect.maxRows (the limit is not part of the key — a
    lowered limit must reject the oversized cached answer exactly like
    the executed path would); (b) the executed run's QueryProfile
    attributes its own result_cache.miss (counted after the recorder
    baseline); (c) a seeded steady-state run whose capacity outcomes
    match its seed appends NO duplicate manifest record."""
    s = _session("pc-limits", {
        "spark.tpu.cache.dir": str(tmp_path),
        "spark.tpu.obs.profileDir": str(tmp_path / "profiles"),
    })
    try:
        _seed_table(s)
        q = "select k, v from pc_t where v > 0"
        df = s.sql(q)
        out = df.toArrow()                        # miss → execute → store
        assert out.num_rows > 10
        prof = df.query_execution._last_profile or {}
        assert (prof.get("counters") or {}).get("result_cache.miss") == 1, \
            "executed profile must attribute its own result-cache miss"
        s.conf.set("spark.tpu.collect.maxRows", "10")
        with pytest.raises(RuntimeError, match="maxRows"):
            s.sql(q).toArrow()                    # hit path, same key
        s.conf.unset("spark.tpu.collect.maxRows")
        # (c): record_manifest skips an append whose outcomes equal the
        # prior seed record — capacity CHANGES are recorded, repeats not
        fp = {"fingerprint": "fp-dedup", "stages": []}
        pc.record_manifest(s.conf, fp, {"tier": "whole"}, [8], None)
        rec = pc.manifest_seed(s.conf, "fp-dedup")
        assert rec and rec["join_caps"] == [8]
        pc.record_manifest(s.conf, fp, {"tier": "whole"}, [8], None,
                           prior=rec)
        records = [r for r in pc._manifest(s.conf).load()
                   if r.get("fp") == "fp-dedup"]
        assert len(records) == 1, "identical seeded outcome re-appended"
        pc.record_manifest(s.conf, fp, {"tier": "whole"}, [16], None,
                           prior=rec)             # a CHANGE does append
        assert pc.manifest_seed(s.conf, "fp-dedup")["join_caps"] == [16]
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# invalidation through the catalog write path
# ---------------------------------------------------------------------------

def test_result_cache_invalidated_on_append_and_overwrite(tmp_path):
    wh = tmp_path / "warehouse"
    s = _session("pc-inval", {
        "spark.tpu.cache.dir": str(tmp_path / "cache"),
        "spark.sql.warehouse.dir": str(wh),
    })
    try:
        base = pa.table({"k": np.arange(6) % 3,
                         "v": np.arange(6, dtype=np.int64)})
        s.createDataFrame(base).write.mode("overwrite") \
            .saveAsTable("sales")
        q = "select k, sum(v) s from sales group by k"
        a = s.sql(q).toArrow()                      # populates
        rc = pc.result_cache_for(s.conf)
        assert rc.total_bytes() > 0
        _hit, delta = _launch_delta(lambda: s.sql(q).toArrow())
        assert delta == {}, "warm-up: repeat must hit before the write"
        # APPEND through the catalog write path: the entry dies (hook)
        # AND the file identity in the key changes (construction)
        s.createDataFrame(pa.table({
            "k": np.array([0, 1], dtype=np.int64),
            "v": np.array([100, 200], dtype=np.int64),
        })).write.insertInto("sales")
        b = s.sql(q).toArrow()
        assert not b.equals(a), "append must be visible — stale hit!"
        assert {r["k"]: r["s"] for r in s.sql(q).collect()} == \
            {0: 3 + 100, 1: 5 + 200, 2: 7}
        # OVERWRITE: again a fresh answer
        s.createDataFrame(pa.table({
            "k": np.zeros(2, dtype=np.int64),
            "v": np.array([7, 8], dtype=np.int64),
        })).write.mode("overwrite").saveAsTable("sales")
        c = s.sql(q).toArrow()
        assert {r["k"]: r["s"] for r in s.sql(q).collect()} == {0: 15}
        assert not c.equals(b)
        counters = s._metrics.snapshot()["counters"]
        assert counters.get("result_cache.store", 0) >= 2
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# warm-start manifest: whole-tier capacity seeding
# ---------------------------------------------------------------------------

def test_whole_query_capacity_seed_collapses_retries(tmp_path):
    s = _session("pc-seed", {
        "spark.tpu.cache.dir": str(tmp_path),
        "spark.tpu.cache.result.enabled": "false",
        "spark.tpu.compile.tier": "whole",
        "spark.sql.adaptive.enabled": "false",
    })
    try:
        _seed_table(s)
        s.createDataFrame(pa.table({
            "k": np.repeat(np.arange(9), 3), "tag": np.arange(27),
        })).createOrReplaceTempView("pc_dim")
        jq = ("select p.k, count(*) n from pc_t p join pc_dim d "
              "on p.k = d.k group by p.k")

        def run():
            c0 = dict(s._metrics.snapshot()["counters"])
            out = s.sql(jq).toArrow()
            c1 = dict(s._metrics.snapshot()["counters"])
            return out, {
                k: c1.get(k, 0) - c0.get(k, 0)
                for k in ("whole_query.dispatches",
                          "whole_query.capacity_retries",
                          "cache.capacity_seeded")}

        cold_out, cold = run()
        assert cold["whole_query.capacity_retries"] >= 1, \
            f"3x-expanding join never overflowed: {cold}"
        # the manifest recorded the final caps under this fingerprint
        fp = s.sql(jq).query_execution.plan_fingerprint()["fingerprint"]
        rec = pc.manifest_seed(s.conf, fp)
        assert rec and rec.get("join_caps"), rec
        # "warm restart" semantics: every execute re-derives join_caps
        # from scratch, so even in-process the seed is what collapses
        # the ladder — one dispatch, zero retries, identical answer
        warm_out, warm = run()
        assert warm["whole_query.capacity_retries"] == 0, warm
        assert warm["whole_query.dispatches"] == 1, warm
        assert warm["cache.capacity_seeded"] == 1, warm
        assert warm_out.equals(cold_out)
        # plan_lint mirrors the seeded attempt count
        rep = s.sql(jq).query_execution.analysis_report()
        assert rep.predicted_launches.get("whole_query") == 1
        assert rep.exact
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# cross-process durability (two REAL subprocesses)
# ---------------------------------------------------------------------------

_CHILD = r'''
import json, os, sys
import numpy as np, pyarrow as pa
from spark_tpu import TpuSession
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
import spark_tpu.exec.persist_cache as pc

s = TpuSession("pc-child", {
    "spark.tpu.cache.dir": sys.argv[1],
    "spark.tpu.cache.result.enabled": "false",
    "spark.sql.shuffle.partitions": 2,
    "spark.tpu.batch.capacity": 1 << 12,
    "spark.tpu.fusion.minRows": "0",
})
rng = np.random.default_rng(3)
s.createDataFrame(pa.table({
    "k": rng.integers(0, 9, 4000), "v": rng.integers(-20, 80, 4000),
})).createOrReplaceTempView("pc_t")
df = s.sql("select k, sum(v) s from pc_t where v > 0 group by k")
out = df.toArrow()
print("CHILD " + json.dumps({
    "fingerprint": df.query_execution.plan_fingerprint()["fingerprint"],
    "compiles": KC.misses,
    "disk": pc.disk_counters(),
    "disk_hit_compiles": KC.disk_hit_compiles,
    "rows": out.num_rows,
}))
'''


def test_fingerprint_and_compile_cache_across_subprocesses(tmp_path):
    """The satellite's durability proof: a cold subprocess populates the
    XLA disk cache; a FRESH subprocess re-runs the same query with the
    identical fingerprint and ZERO true cold XLA compiles (every
    backend compile served from disk)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def child(tag):
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD, str(tmp_path)],
            env=env, cwd=root, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, timeout=300)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("CHILD ")]
        assert proc.returncode == 0 and lines, \
            f"{tag} child failed: {proc.stderr[-500:]}"
        return json.loads(lines[-1][len("CHILD "):])

    cold = child("cold")
    warm = child("warm")
    assert cold["fingerprint"] == warm["fingerprint"], \
        "fingerprint unstable across processes — persistent keys dead"
    assert cold["disk"]["compile.disk_miss"] >= 1
    assert warm["disk"]["compile.disk_miss"] == 0, \
        f"warm restart paid true cold compiles: {warm['disk']}"
    assert warm["disk"]["compile.disk_hit"] >= 1
    assert warm["disk_hit_compiles"] >= 1, \
        "no kernel classified as disk-served on the warm restart"
    assert warm["rows"] == cold["rows"]
