"""Connect remote API tests (reference: Spark Connect —
SparkConnectServiceSuite, python/pyspark/sql/tests/connect/). The core
contracts: (1) a THIN client with zero engine imports drives the server
from another process; (2) remote results are identical to in-process
execution, TPC-DS q3 included; (3) sessions are isolated."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def connect():
    """In-process server + client pair (fast path for API tests)."""
    from spark_tpu.connect.client import ConnectSession
    from spark_tpu.connect.server import ConnectServer

    server = ConnectServer({"spark.sql.shuffle.partitions": 2})
    addr = server.start()
    session = ConnectSession(addr, server.token)
    yield server, session
    session.close()
    server.stop()


def test_sql_roundtrip(connect):
    _, s = connect
    t = pa.table({"k": [1, 2, 1, 3], "v": [1.0, 2.0, 3.0, 4.0]})
    s.createDataFrame(t, "ct")
    rows = s.sql(
        "SELECT k, sum(v) AS s FROM ct GROUP BY k ORDER BY k").collect()
    assert rows == [{"k": 1, "s": 4.0}, {"k": 2, "s": 2.0},
                    {"k": 3, "s": 4.0}]


def test_dataframe_ops_build_remote_plan(connect):
    _, s = connect
    t = pa.table({"x": list(range(100))})
    df = s.createDataFrame(t)
    out = df.filter("x % 10 = 3").selectExpr("x", "x * 2 AS y").limit(4)
    got = out.collect()
    assert got == [{"x": 3, "y": 6}, {"x": 13, "y": 26},
                   {"x": 23, "y": 46}, {"x": 33, "y": 66}]
    assert df.count() == 100


def test_schema_and_explain(connect, capsys):
    _, s = connect
    df = s.sql("SELECT 1 AS a, 'x' AS b")
    fields = df.schema()
    assert [f[0] for f in fields] == ["a", "b"]
    df.explain()
    assert "Physical Plan" in capsys.readouterr().out


def test_create_view_from_plan(connect):
    _, s = connect
    s.createDataFrame(pa.table({"n": [1, 2, 3, 4]}), "cv_src")
    s.table("cv_src").filter("n > 2").createOrReplaceTempView("cv_big")
    assert s.sql("SELECT count(*) AS c FROM cv_big").collect() == [{"c": 2}]


def test_analysis_error_carries_server_detail(connect):
    from spark_tpu.connect.client import ConnectError

    _, s = connect
    with pytest.raises(ConnectError, match="nonexistent_table_xyz"):
        s.sql("SELECT * FROM nonexistent_table_xyz").collect()


def test_session_isolation(connect):
    from spark_tpu.connect.client import ConnectSession

    server, s1 = connect
    s2 = ConnectSession(server.address, server.token)
    try:
        s1.createDataFrame(pa.table({"z": [1]}), "iso_t")
        assert s1.sql("SELECT * FROM iso_t").collect() == [{"z": 1}]
        from spark_tpu.connect.client import ConnectError

        with pytest.raises(ConnectError, match="iso_t"):
            s2.sql("SELECT * FROM iso_t").collect()
    finally:
        s2.close()


# ---------------------------------------------------------------------------
# The headline contract: separate client process, zero engine imports,
# TPC-DS q3 identical to in-process execution.
# ---------------------------------------------------------------------------

_CLIENT_SCRIPT = r"""
import json, sys
sys.path.insert(0, {repo!r})
from spark_tpu.connect.client import ConnectSession

addr, token, data_dir, q3 = sys.argv[1:5]
import pyarrow.parquet as pq
import os
s = ConnectSession(addr, token)
for name in ("date_dim", "store_sales", "item"):
    t = pq.read_table(os.path.join(data_dir, name + ".parquet"))
    s.createDataFrame(t, name)
out = s.sql(open(q3).read()).toArrow()
print(json.dumps(out.to_pylist(), default=str))

# the purity pin: a Connect client process must never load the engine
engine_mods = [m for m in sys.modules
               if m.startswith(("jax", "spark_tpu.api", "spark_tpu.plan",
                                "spark_tpu.physical", "spark_tpu.expr",
                                "spark_tpu.sql", "spark_tpu.exec"))]
assert not engine_mods, f"engine leaked into thin client: {{engine_mods}}"
s.close()
"""


def test_q3_client_process_matches_inprocess(tmp_path, spark):
    import pyarrow.parquet as pq

    from spark_tpu.connect.server import ConnectServer
    from tests.tpcds.datagen import _Gen
    from tests.tpcds.oracle import strip_trailing_limit

    g = _Gen(0.25, 17)
    for t in ("date_dim", "time_dim", "item", "customer_address",
              "customer_demographics", "household_demographics",
              "income_band", "customer", "store", "warehouse",
              "ship_mode", "reason", "call_center", "catalog_page",
              "web_site", "web_page", "promotion", "store_sales"):
        getattr(g, t)()
    data_dir = tmp_path / "tpcds"
    data_dir.mkdir()
    for name in ("date_dim", "store_sales", "item"):
        pq.write_table(g.tables[name], str(data_dir / f"{name}.parquet"))
    qfile = tmp_path / "q3.sql"
    qfile.write_text(strip_trailing_limit(
        open(os.path.join(REPO, "tests", "tpcds", "queries",
                          "q3.sql")).read()))

    # in-process oracle run
    for name in ("date_dim", "store_sales", "item"):
        spark.createDataFrame(g.tables[name]).createOrReplaceTempView(name)
    expected = spark.sql(qfile.read_text()).toArrow().to_pylist()

    server = ConnectServer({"spark.sql.shuffle.partitions": 2})
    addr = server.start()
    try:
        script = tmp_path / "client.py"
        script.write_text(_CLIENT_SCRIPT.format(repo=REPO))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # client needs no jax at all
        r = subprocess.run(
            [sys.executable, str(script), addr, server.token,
             str(data_dir), str(qfile)],
            capture_output=True, text=True, timeout=300, env=env)
        assert r.returncode == 0, r.stderr[-3000:]
        got = json.loads(r.stdout.strip().splitlines()[-1])
    finally:
        server.stop()

    def norm(rows):
        return [tuple(str(v) for v in row.values()) for row in rows]

    assert norm(got) == norm(expected)
    assert len(got) > 0
