"""Standalone deploy: master + worker daemons as SEPARATE processes
(no shared Python state), executor placement, worker-churn recovery
(reference: core/deploy/master/Master.scala, worker/Worker.scala,
client/StandaloneAppClient.scala)."""

import os
import pickle
import secrets
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_daemon(module: str, args: list, announce: str,
                  secret: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""     # daemons never touch the tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_TPU_MASTER_SECRET"] = secret
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", module, *args,
         "--announce-file", announce],
        env=env, cwd=REPO)


def _read_announce(path: str, timeout: float = 30.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        time.sleep(0.1)
    raise TimeoutError(f"no announce file at {path}")


@pytest.fixture()
def standalone(tmp_path):
    """A master and two worker daemons, each its own OS process."""
    secret = secrets.token_hex(16)
    procs = []
    try:
        m = _spawn_daemon("spark_tpu.deploy.master", [],
                          str(tmp_path / "master.addr"), secret)
        procs.append(m)
        master_addr = _read_announce(str(tmp_path / "master.addr"))
        for i in range(2):
            w = _spawn_daemon("spark_tpu.deploy.worker", [master_addr],
                              str(tmp_path / f"worker{i}.addr"), secret)
            procs.append(w)
            _read_announce(str(tmp_path / f"worker{i}.addr"))
        yield {"master_addr": master_addr, "secret": secret,
               "procs": procs}
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_master_places_executors_and_replaces_lost_worker(standalone):
    """The master's schedule loop: two requested executors placed on the
    worker fleet; killing a worker DAEMON re-places its executor on the
    survivor (Master.scala:744 schedule after worker timeout)."""
    from spark_tpu.deploy.standalone import StandaloneCluster
    from spark_tpu.net.transport import RpcClient

    cluster = StandaloneCluster(
        f"grpc://{standalone['master_addr']}", standalone["secret"],
        num_executors=2, app_name="placement")
    try:
        assert cluster.num_alive() == 2
        assert cluster.run_task(lambda x: x * 3, 14) == 42
        # kill one EXECUTOR process: its worker daemon reaps the child,
        # its next heartbeat reports the deficit, and the master's
        # reconcile loop launches a replacement
        victim = next(iter(cluster._workers.values()))
        os.kill(victim.pid, signal.SIGKILL)
        deadline = time.monotonic() + 10
        while cluster.num_alive() > 1 and time.monotonic() < deadline:
            # poke the dead executor so the driver notices the loss
            try:
                cluster.run_task(lambda x: x, 0)
            except Exception:
                pass
            time.sleep(0.2)
        cluster.wait_for_executors(2, timeout=60)
        assert cluster.run_task(lambda x: x + 1, 41) == 42
        # the master's state endpoint converges on the replaced fleet
        # (worker heartbeats report launches on a 1s tick)
        with RpcClient(standalone["master_addr"],
                       standalone["secret"]) as c:
            deadline = time.monotonic() + 15
            while True:
                state = pickle.loads(
                    c.call("master_state", b"", timeout=10))
                placed = sum(sum(w["apps"].values())
                             for w in state["workers"])
                if placed >= 2 or time.monotonic() > deadline:
                    break
                time.sleep(0.3)
        assert len(state["workers"]) == 2
        assert state["apps"] and state["apps"][0]["desired"] == 2
        assert placed >= 2, state
    finally:
        cluster.stop()


def test_tpcds_q3_completes_despite_executor_kill_midquery(standalone):
    """The VERDICT's end-to-end bar: a real app (TPC-DS q3) against a
    standalone master with two remote workers; an executor dies
    mid-query; the query still returns correct rows (driver task retry
    + master re-placement)."""
    from tests.tpcds.datagen import gen_tpcds_full

    import spark_tpu.exec.cluster_sql as CS
    from spark_tpu.api.session import TpuSession
    from spark_tpu.deploy.standalone import StandaloneCluster

    spark = TpuSession("q3-standalone",
                       {"spark.sql.shuffle.partitions": "3"})
    cluster = StandaloneCluster(
        f"grpc://{standalone['master_addr']}", standalone["secret"],
        num_executors=2, app_name="q3")
    spark.attachSqlCluster(cluster)

    tables = gen_tpcds_full(scale=0.01)
    for name in ("date_dim", "store_sales", "item"):
        spark.createDataFrame(tables[name]).createOrReplaceTempView(name)

    state = {"killed": False}
    orig = CS.ClusterDAGScheduler._run_remote

    def kill_one_executor_after_first_map(self, stage):
        status = orig(self, stage)
        if not state["killed"]:
            state["killed"] = True
            w = cluster._workers[status.executor_id]
            if w.pid:
                os.kill(w.pid, signal.SIGKILL)
        return status

    CS.ClusterDAGScheduler._run_remote = kill_one_executor_after_first_map
    try:
        sql = open(os.path.join(
            REPO, "tests", "tpcds", "queries", "q3.sql")).read()
        t = spark.sql(sql).toArrow()
        assert state["killed"], "kill hook never fired"
        # correctness against the single-process engine
        CS.ClusterDAGScheduler._run_remote = orig
        spark.detachSqlCluster()
        expect = spark.sql(sql).toArrow()

        def rows(tab):
            return sorted(tuple(r.values()) for r in tab.to_pylist())

        assert rows(t) == rows(expect)
    finally:
        CS.ClusterDAGScheduler._run_remote = orig
        spark.stop()
        cluster.stop()
