"""ML pipeline tests (reference: mllib test suites; sklearn-style oracles)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.ml import (
    BinaryClassificationEvaluator, CrossValidator, KMeans, LinearRegression,
    LogisticRegression, MulticlassClassificationEvaluator, NaiveBayes,
    ParamGridBuilder, Pipeline, RegressionEvaluator, StandardScaler,
    StringIndexer, VectorAssembler,
)


@pytest.fixture()
def regression_df(spark):
    rng = np.random.default_rng(0)
    n = 500
    x1 = rng.normal(0, 1, n)
    x2 = rng.normal(0, 1, n)
    y = 3.0 * x1 - 2.0 * x2 + 0.5 + rng.normal(0, 0.01, n)
    df = spark.createDataFrame(pa.table({"x1": x1, "x2": x2, "label": y}))
    return VectorAssembler(inputCols=["x1", "x2"]).transform(df)


def test_linear_regression_normal(regression_df):
    model = LinearRegression().fit(regression_df)
    assert abs(model.coefficients[0] - 3.0) < 0.01
    assert abs(model.coefficients[1] + 2.0) < 0.01
    assert abs(model.intercept - 0.5) < 0.01
    pred = model.transform(regression_df)
    rmse = RegressionEvaluator().evaluate(pred)
    assert rmse < 0.02


def test_linear_regression_gd(regression_df):
    model = LinearRegression(solver="gd", maxIter=2000).fit(regression_df)
    assert abs(model.coefficients[0] - 3.0) < 0.1


def test_logistic_regression(spark):
    rng = np.random.default_rng(1)
    n = 600
    x1 = rng.normal(0, 1, n)
    x2 = rng.normal(0, 1, n)
    label = (2 * x1 - x2 > 0).astype(np.float64)
    df = VectorAssembler(inputCols=["x1", "x2"]).transform(
        spark.createDataFrame(pa.table({"x1": x1, "x2": x2, "label": label})))
    model = LogisticRegression(maxIter=500).fit(df)
    pred = model.transform(df)
    acc = MulticlassClassificationEvaluator().evaluate(pred)
    assert acc > 0.95
    auc = BinaryClassificationEvaluator().evaluate(pred)
    assert auc > 0.98


def test_kmeans(spark):
    rng = np.random.default_rng(2)
    a = rng.normal((0, 0), 0.2, (100, 2))
    b = rng.normal((5, 5), 0.2, (100, 2))
    X = np.concatenate([a, b])
    df = VectorAssembler(inputCols=["x", "y"]).transform(
        spark.createDataFrame(pa.table({"x": X[:, 0], "y": X[:, 1]})))
    model = KMeans(k=2).fit(df)
    centers = sorted(model.clusterCenters.tolist())
    assert abs(centers[0][0] - 0) < 0.5
    assert abs(centers[1][0] - 5) < 0.5
    pred = model.transform(df).toArrow().to_pydict()["prediction"]
    assert len(set(pred[:100])) == 1 and len(set(pred[100:])) == 1


def test_naive_bayes(spark):
    rng = np.random.default_rng(3)
    a = rng.normal(0, 1, (200, 2))
    b = rng.normal(4, 1, (200, 2))
    X = np.concatenate([a, b])
    y = np.array([0.0] * 200 + [1.0] * 200)
    df = VectorAssembler(inputCols=["f1", "f2"]).transform(
        spark.createDataFrame(pa.table(
            {"f1": X[:, 0], "f2": X[:, 1], "label": y})))
    model = NaiveBayes().fit(df)
    acc = MulticlassClassificationEvaluator().evaluate(model.transform(df))
    assert acc > 0.95


def test_pipeline_with_scaler(spark):
    rng = np.random.default_rng(4)
    n = 300
    x1 = rng.normal(100, 50, n)  # badly scaled
    y = (x1 > 100).astype(np.float64)
    df = spark.createDataFrame(pa.table({"x1": x1, "label": y}))
    pipe = Pipeline(stages=(
        VectorAssembler(inputCols=["x1"], outputCol="raw"),
        StandardScaler(inputCol="raw", outputCol="features"),
        LogisticRegression(maxIter=300),
    ))
    model = pipe.fit(df)
    pred = model.transform(df)
    acc = MulticlassClassificationEvaluator().evaluate(pred)
    assert acc > 0.97


def test_string_indexer(spark):
    df = spark.createDataFrame(pa.table(
        {"cat": ["b", "a", "b", "c", "b", "a"]}))
    model = StringIndexer(inputCol="cat", outputCol="idx").fit(df)
    assert model.labels[0] == "b"  # most frequent first
    out = model.transform(df).toArrow().to_pydict()
    assert out["idx"][0] == 0.0


def test_cross_validator(spark):
    rng = np.random.default_rng(5)
    n = 200
    x = rng.normal(0, 1, n)
    y = (x > 0).astype(np.float64)
    df = VectorAssembler(inputCols=["x"]).transform(
        spark.createDataFrame(pa.table({"x": x, "label": y})))
    cv = CrossValidator(
        estimator=LogisticRegression(maxIter=100),
        estimatorParamMaps=ParamGridBuilder()
        .addGrid("regParam", [0.0, 10.0]).build(),
        evaluator=MulticlassClassificationEvaluator(),
        numFolds=3)
    model = cv.fit(df)
    assert len(model.avgMetrics) == 2
    assert model.avgMetrics[0] > model.avgMetrics[1]  # heavy reg is worse


def test_decision_tree_classifier(spark):
    rng = np.random.default_rng(6)
    n = 400
    x1 = rng.uniform(-1, 1, n)
    x2 = rng.uniform(-1, 1, n)
    label = ((x1 > 0.2) ^ (x2 > -0.3)).astype(np.float64)  # axis-aligned
    from spark_tpu.ml import DecisionTreeClassifier

    df = VectorAssembler(inputCols=["x1", "x2"]).transform(
        spark.createDataFrame(pa.table({"x1": x1, "x2": x2, "label": label})))
    model = DecisionTreeClassifier(maxDepth=4).fit(df)
    acc = MulticlassClassificationEvaluator().evaluate(model.transform(df))
    assert acc > 0.95


def test_random_forest_regressor(spark):
    rng = np.random.default_rng(7)
    n = 500
    x = rng.uniform(0, 10, n)
    y = np.where(x < 5, 1.0, 3.0) + rng.normal(0, 0.05, n)
    from spark_tpu.ml import RandomForestRegressor

    df = VectorAssembler(inputCols=["x"]).transform(
        spark.createDataFrame(pa.table({"x": x, "label": y})))
    model = RandomForestRegressor(numTrees=10, maxDepth=3).fit(df)
    rmse = RegressionEvaluator().evaluate(model.transform(df))
    assert rmse < 0.3


def test_als_recovers_structure(spark):
    rng = np.random.default_rng(8)
    nu, ni, k = 30, 20, 3
    U = rng.normal(0, 1, (nu, k))
    V = rng.normal(0, 1, (ni, k))
    R = U @ V.T
    users, items, ratings = [], [], []
    for u in range(nu):
        for i in rng.choice(ni, size=12, replace=False):
            users.append(u)
            items.append(int(i))
            ratings.append(float(R[u, i]))
    from spark_tpu.ml import ALS

    df = spark.createDataFrame(pa.table({
        "user": users, "item": items, "rating": ratings}))
    model = ALS(rank=3, maxIter=15, regParam=0.01).fit(df)
    pred = model.transform(df).toArrow().to_pydict()["prediction"]
    err = np.abs(np.array(pred) - np.array(ratings)).mean()
    assert err < 0.1
    recs = model.recommend_for_user(0, 5)
    assert len(recs) == 5


def test_bucketizer_and_discretizer(spark):
    from spark_tpu.ml import Bucketizer, QuantileDiscretizer

    df = spark.createDataFrame(pa.table({"v": [0.1, 0.4, 0.6, 0.9]}))
    b = Bucketizer(inputCol="v", outputCol="bkt",
                   splits=(0.0, 0.5, 1.0))
    out = b.transform(df).toArrow().to_pydict()
    assert out["bkt"] == [0.0, 0.0, 1.0, 1.0]

    qd = QuantileDiscretizer(inputCol="v", outputCol="q", numBuckets=2)
    model = qd.fit(df)
    out2 = model.transform(df).toArrow().to_pydict()
    assert len(set(out2["q"])) == 2


def test_one_hot_encoder(spark):
    from spark_tpu.ml import OneHotEncoder

    df = spark.createDataFrame(pa.table({"c": ["a", "b", "c", "a"]}))
    model = OneHotEncoder(inputCol="c", outputCol="oh", dropLast=True).fit(df)
    out = model.transform(df).toArrow().to_pydict()
    assert out["oh_a"] == [1.0, 0.0, 0.0, 1.0]
    assert out["oh_b"] == [0.0, 1.0, 0.0, 0.0]
    assert "oh_c" not in out  # dropLast


def test_pca(spark):
    from spark_tpu.ml import PCA

    rng = np.random.default_rng(9)
    t = rng.normal(0, 3, 300)
    x = t + rng.normal(0, 0.05, 300)
    y = 2 * t + rng.normal(0, 0.05, 300)   # rank-1 structure
    df = VectorAssembler(inputCols=["x", "y"]).transform(
        spark.createDataFrame(pa.table({"x": x, "y": y})))
    model = PCA(inputCol="features", outputCol="p", k=1).fit(df)
    out = model.transform(df).toArrow().to_pydict()
    z = np.array(out["p_0"])
    # first component captures nearly all variance
    total_var = np.var(x) + np.var(y)
    assert np.var(z) / total_var > 0.99


def test_gbt_regressor(spark):
    from spark_tpu.ml import GBTRegressor

    rng = np.random.default_rng(10)
    x = rng.uniform(0, 10, 600)
    y = np.sin(x) * 2 + 0.1 * x + rng.normal(0, 0.05, 600)
    df = VectorAssembler(inputCols=["x"]).transform(
        spark.createDataFrame(pa.table({"x": x, "label": y})))
    model = GBTRegressor(maxIter=40, maxDepth=3, stepSize=0.3).fit(df)
    rmse = RegressionEvaluator().evaluate(model.transform(df))
    assert rmse < 0.3


def test_gbt_classifier(spark):
    from spark_tpu.ml import GBTClassifier

    rng = np.random.default_rng(11)
    x1 = rng.uniform(-1, 1, 500)
    x2 = rng.uniform(-1, 1, 500)
    label = ((x1 * x1 + x2 * x2) < 0.5).astype(np.float64)  # nonlinear ring
    df = VectorAssembler(inputCols=["x1", "x2"]).transform(
        spark.createDataFrame(pa.table({"x1": x1, "x2": x2,
                                        "label": label})))
    model = GBTClassifier(maxIter=30, maxDepth=3).fit(df)
    acc = MulticlassClassificationEvaluator().evaluate(model.transform(df))
    assert acc > 0.93


def test_fpgrowth(spark):
    from spark_tpu.ml import FPGrowth

    df = spark.createDataFrame(pa.table({
        "items": ["bread milk", "bread butter", "milk butter bread",
                  "bread milk", "butter"]}))
    model = FPGrowth(minSupport=0.4, minConfidence=0.6).fit(df)
    sets = {tuple(k): v for k, v in model.freqItemsets()}
    assert sets[("bread",)] == 4
    assert sets[("bread", "milk")] == 3
    rules = model.associationRules()
    assert any(r[0] == ["milk"] and r[1] == ["bread"] and r[2] == 1.0
               for r in rules)
    pred = model.transform(df).toArrow().to_pydict()["prediction"]
    assert "bread" in pred[4]  # butter → bread suggested


# ---------------------------------------------------------------------------
# r4 breadth: text pipeline, SVC, MLP, GMM, isotonic, scalers
# ---------------------------------------------------------------------------

def test_text_pipeline_tfidf_classification(spark):
    """Tokenizer → StopWordsRemover → HashingTF → IDF → LogisticRegression
    end to end (the reference's canonical text pipeline example)."""
    import pyarrow as pa

    from spark_tpu.ml import (
        HashingTF, IDF, LogisticRegression, Pipeline, StopWordsRemover,
        Tokenizer,
    )

    docs = ["spark is great and fast", "tpu math compiles fast",
            "slow mail arrived late again", "the mail office was slow"]
    labels = [1.0, 1.0, 0.0, 0.0]
    df = spark.createDataFrame(pa.table({"text": docs, "label": labels}))
    pipe = Pipeline(stages=[
        Tokenizer(inputCol="text", outputCol="tokens"),
        StopWordsRemover(inputCol="tokens", outputCol="filtered"),
        HashingTF(inputCol="filtered", outputCol="tf", numFeatures=64),
        IDF(inputCol="tf", outputCol="tfidf"),
        LogisticRegression(featuresCol="tfidf", labelCol="label",
                           maxIter=300),
    ])
    model = pipe.fit(df)
    out = model.transform(df).toArrow()
    assert out.column("prediction").to_pylist() == labels


def test_count_vectorizer_and_ngram(spark):
    import pyarrow as pa

    from spark_tpu.ml import CountVectorizer, NGram, Tokenizer

    df = spark.createDataFrame(pa.table({
        "text": ["a b a c", "b c b", "a a a"]}))
    toks = Tokenizer(inputCol="text", outputCol="t").transform(df)
    cv = CountVectorizer(inputCol="t", outputCol="tf", vocabSize=10).fit(toks)
    assert set(cv.vocabulary) == {"a", "b", "c"}
    out = cv.transform(toks).toArrow()
    mat = out.column("tf").to_pylist()
    ai = cv.vocabulary.index("a")
    assert [row[ai] for row in mat] == [2.0, 0.0, 3.0]
    ng = NGram(inputCol="t", outputCol="bi", n=2).transform(toks).toArrow()
    assert ng.column("bi").to_pylist()[0] == ["a b", "b a", "a c"]


def test_linear_svc_separable(spark):
    import numpy as np
    import pyarrow as pa

    from spark_tpu.ml import LinearSVC

    rng = np.random.default_rng(0)
    n = 200
    x1 = rng.normal(0, 1, n)
    x2 = rng.normal(0, 1, n)
    y = (x1 + x2 > 0).astype(np.float64)
    df = spark.createDataFrame(pa.table({"x1": x1, "x2": x2, "label": y}))
    from spark_tpu.ml import VectorAssembler

    df = VectorAssembler(inputCols=("x1", "x2"),
                         outputCol="features").transform(df)
    m = LinearSVC(maxIter=300).fit(df)
    pred = m.transform(df).toArrow().column("prediction").to_pylist()
    acc = np.mean(np.asarray(pred) == y)
    assert acc >= 0.95, acc


def test_mlp_learns_xor(spark):
    import numpy as np
    import pyarrow as pa

    from spark_tpu.ml import MultilayerPerceptronClassifier, VectorAssembler

    rng = np.random.default_rng(1)
    n = 400
    a = rng.integers(0, 2, n)
    b = rng.integers(0, 2, n)
    y = (a ^ b).astype(np.float64)
    df = spark.createDataFrame(pa.table({
        "a": a.astype(np.float64) + rng.normal(0, 0.05, n),
        "b": b.astype(np.float64) + rng.normal(0, 0.05, n),
        "label": y}))
    df = VectorAssembler(inputCols=("a", "b"),
                         outputCol="features").transform(df)
    m = MultilayerPerceptronClassifier(
        layers=[2, 8, 2], maxIter=500, stepSize=0.05).fit(df)
    pred = m.transform(df).toArrow().column("prediction").to_pylist()
    assert np.mean(np.asarray(pred) == y) >= 0.95


def test_gaussian_mixture_separates_blobs(spark):
    import numpy as np
    import pyarrow as pa

    from spark_tpu.ml import GaussianMixture, VectorAssembler

    rng = np.random.default_rng(2)
    n = 150
    x = np.concatenate([rng.normal(-4, 0.5, n), rng.normal(4, 0.5, n)])
    z = np.concatenate([rng.normal(-4, 0.5, n), rng.normal(4, 0.5, n)])
    df = spark.createDataFrame(pa.table({"x": x, "z": z}))
    df = VectorAssembler(inputCols=("x", "z"),
                         outputCol="features").transform(df)
    m = GaussianMixture(k=2, maxIter=50).fit(df)
    pred = np.asarray(
        m.transform(df).toArrow().column("prediction").to_pylist())
    # each half should be (almost) pure one cluster
    first, second = pred[:n], pred[n:]
    purity = max((first == 0).mean() + (second == 1).mean(),
                 (first == 1).mean() + (second == 0).mean()) / 2
    assert purity >= 0.98


def test_bisecting_kmeans(spark):
    import numpy as np
    import pyarrow as pa

    from spark_tpu.ml import BisectingKMeans, VectorAssembler

    rng = np.random.default_rng(3)
    pts = np.concatenate([rng.normal(c, 0.3, 50) for c in (-6, 0, 6)])
    df = spark.createDataFrame(pa.table({"x": pts}))
    df = VectorAssembler(inputCols=("x",),
                         outputCol="features").transform(df)
    m = BisectingKMeans(k=3).fit(df)
    pred = np.asarray(
        m.transform(df).toArrow().column("prediction").to_pylist())
    assert len(set(pred[:50])) == 1
    assert len({pred[0], pred[60], pred[120]}) == 3


def test_isotonic_regression_monotone(spark):
    import numpy as np
    import pyarrow as pa

    from spark_tpu.ml import IsotonicRegression

    x = np.arange(20, dtype=np.float64)
    y = x + np.sin(x) * 2  # noisy but increasing trend
    df = spark.createDataFrame(pa.table({"features": x, "label": y}))
    m = IsotonicRegression().fit(df)
    pred = np.asarray(
        m.transform(df).toArrow().column("prediction").to_pylist())
    assert np.all(np.diff(pred) >= -1e-9)  # monotone
    assert abs(pred.mean() - y.mean()) < 1.0


def test_imputer_and_robust_scaler(spark):
    import numpy as np
    import pyarrow as pa

    from spark_tpu.ml import Imputer, RobustScaler, VectorAssembler

    df = spark.createDataFrame(pa.table({
        "v": [1.0, 2.0, None, 4.0, 100.0]}))
    imp = Imputer(inputCols=("v",), outputCols=("vf",)).fit(df)
    got = imp.transform(df).toArrow().column("vf").to_pylist()
    assert got[2] == pytest.approx((1 + 2 + 4 + 100) / 4)
    df2 = VectorAssembler(inputCols=("vf",), outputCol="features") \
        .transform(imp.transform(df))
    rs = RobustScaler().fit(df2)
    out = rs.transform(df2)
    scaled = out.toArrow().column("scaled_vf").to_pylist()
    assert scaled[1] == pytest.approx(0.0, abs=1e-9) or \
        abs(np.median(scaled)) < 1e-9  # centered on the median
