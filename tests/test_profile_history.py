"""Query flight recorder (spark_tpu/obs/history.py): plan fingerprints,
persistent run profiles, deterministic perf-regression detection — plus
the PR's satellites (chaos obs salvage, degrade-path attribution).

Contract under test: the recorder is pure close-time host work (zero
kernel launches, fusion on or off), fingerprints are stable across runs
of the same query and sensitive to literals/schemas/tiers, the store
round-trips and stays bounded, and regression findings fire exactly when
a deterministic counter EXCEEDS the stored baseline — never on a warm
re-run of an identical query."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.obs.history import (
    ProfileStore, detect_regressions, plan_fingerprint, query_key,
)
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


def _session(name, extra=None):
    from spark_tpu import TpuSession

    conf = {"spark.sql.shuffle.partitions": 2,
            "spark.tpu.batch.capacity": 1 << 12,
            "spark.tpu.fusion.minRows": "0"}
    conf.update(extra or {})
    return TpuSession(name, conf)


def _seed_table(s, view="fr_t", n=4000):
    rng = np.random.default_rng(3)
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 9, n),
        "v": rng.integers(-20, 80, n),
    })).createOrReplaceTempView(view)


Q = "select k, sum(v) s from fr_t where v > 0 group by k"


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def test_fingerprint_stability_and_sensitivity():
    s = _session("fp-test")
    try:
        _seed_table(s)

        def fp(sql):
            return s.sql(sql).query_execution.plan_fingerprint()

        a = fp(Q)
        b = fp(Q)
        assert a["fingerprint"] == b["fingerprint"], \
            "same query twice must fingerprint identically"
        assert a["stages"] and all(st["fingerprint"]
                                   for st in a["stages"]), \
            "per-stage sub-fingerprints missing"
        # literal sensitivity
        c = fp("select k, sum(v) s from fr_t where v > 1 group by k")
        assert c["fingerprint"] != a["fingerprint"]
        # schema sensitivity (different input column type)
        s.createDataFrame(pa.table({
            "k": np.arange(40, dtype=np.int64),
            "v": np.arange(40).astype(np.float64),
        })).createOrReplaceTempView("fr_f")
        d = fp("select k, sum(v) s from fr_f where v > 0 group by k")
        assert d["fingerprint"] != a["fingerprint"]
        # tier sensitivity: the FULL fingerprint flips with the tier
        # (compile-cache key), the structural query key does NOT
        # (regression baselines survive strategy changes)
        qk_a = query_key(s.sql(Q).query_execution.optimized, s.conf)
        s.conf.set("spark.tpu.compile.tier", "operator")
        e = fp(Q)
        qk_e = query_key(s.sql(Q).query_execution.optimized, s.conf)
        s.conf.unset("spark.tpu.compile.tier")
        assert e["fingerprint"] != a["fingerprint"]
        assert qk_e == qk_a, "query key must be tier-insensitive"
    finally:
        s.stop()


def test_fingerprint_capacity_is_part_of_the_key():
    s = _session("fp-cap")
    try:
        _seed_table(s)
        a = s.sql(Q).query_execution.plan_fingerprint()
        s.conf.set("spark.tpu.batch.capacity", 1 << 13)
        b = s.sql(Q).query_execution.plan_fingerprint()
        s.conf.set("spark.tpu.batch.capacity", 1 << 12)
        assert a["fingerprint"] != b["fingerprint"]
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# profile round-trip + store bounds
# ---------------------------------------------------------------------------

def test_profile_roundtrip_through_store(tmp_path):
    s = _session("fr-store", {"spark.tpu.obs.profileDir": str(tmp_path)})
    try:
        _seed_table(s)
        s.sql(Q).toArrow()
        df = s.sql(Q)
        df.toArrow()
        qe = df.query_execution
        assert qe._last_profile is not None
        assert qe._last_regressions == [], \
            "identical warm re-run must not regress"
        store = ProfileStore(str(tmp_path))
        qk = qe._last_profile["query_key"]
        profs = store.profiles(qk)
        assert len(profs) == 2
        assert {p["fingerprint"] for p in profs} == \
            {qe._last_profile["fingerprint"]}
        cold, warm = profs
        assert cold["launches_by_kind"], "profile lost its launch deltas"
        assert warm["launches_by_kind"] == \
            qe._last_profile["launches_by_kind"]
        assert cold["compiles"] > 0 and warm["compiles"] == 0, \
            "cold/warm compile deltas inverted"
        assert warm["ops"] and any(op["rows"] for op in warm["ops"]), \
            "per-operator records missing from the profile"
        assert warm["wall_ms"] > 0 and "execution" in warm["phases"]
        assert warm["hbm"].get("peak", 0) > 0
        assert (warm.get("tier") or {}).get("tier") in (
            "whole", "stage", "operator")
        # reader APIs: one fingerprint, resolvable back to its profiles
        fps = store.fingerprints()
        assert len(fps) == 1
        fp = next(iter(fps))
        assert fps[fp]["profiles"] == 2
        assert len(store.profiles_for_fingerprint(fp)) == 2
    finally:
        s.stop()


def test_store_ring_stays_bounded(tmp_path):
    store = ProfileStore(str(tmp_path), ring=4)
    for i in range(11):
        store.append({"query_key": "qk1", "fingerprint": "fp1",
                      "ts": float(i), "wall_ms": 1.0})
    profs = store.profiles("qk1")
    assert len(profs) <= 8, "ring never compacted"
    assert profs[-1]["ts"] == 10.0, "compaction dropped the newest"
    # newest-N survive: the oldest entries are the ones evicted
    assert min(p["ts"] for p in profs) > 0.0


# ---------------------------------------------------------------------------
# regression detection
# ---------------------------------------------------------------------------

def _prof(kinds=None, compiles=0, counters=None, wall=10.0, hbm=1000):
    return {"launches_by_kind": kinds or {"pipeline": 2, "fused_agg": 1},
            "compiles": compiles, "counters": counters or {},
            "wall_ms": wall, "hbm": {"peak": hbm}}


def test_detect_regressions_unit():
    base = [_prof(compiles=3), _prof()]  # cold then warm
    # identical warm run: silent
    assert detect_regressions(_prof(), base) == []
    # fewer launches (improvement): silent
    assert detect_regressions(
        _prof(kinds={"pipeline": 1, "fused_agg": 1}), base) == []
    # launch increase + new kind: error findings, one per kind
    regs = detect_regressions(
        _prof(kinds={"pipeline": 4, "fused_agg": 1, "gagg": 2}), base)
    assert {f["severity"] for f in regs} == {"error"}
    assert {f["kind"] for f in regs} == {"obs.regression"}
    assert len(regs) == 2
    # retry counter consumed: error
    regs = detect_regressions(
        _prof(counters={"scheduler.stage_retries": 1}), base)
    assert any("stage_retries" in f["metric"] for f in regs)
    assert all(f["severity"] == "error" for f in regs)
    # wall drift: advisory info, never error
    regs = detect_regressions(_prof(wall=100.0), base)
    assert regs and all(f["severity"] == "info" for f in regs)
    # empty history: nothing to compare
    assert detect_regressions(_prof(wall=9e9), []) == []
    # profiles recorded under concurrent load are baseline-eligible
    # (PR 15: deltas are scope-exact per-query ledger values, so there
    # is no contamination to quarantine — even a legacy profile still
    # carrying the retired `overlapped` mark enters the baseline)
    legacy = [dict(_prof(kinds={"pipeline": 99}), overlapped=True)]
    regs = detect_regressions(_prof(kinds={"pipeline": 100}), legacy)
    assert regs and all(f["severity"] == "error" for f in regs)
    assert detect_regressions(_prof(kinds={"pipeline": 99}), legacy) == []


def test_sanitizer_keeps_decimal_literals():
    from spark_tpu.obs.history import _sanitize

    # 13-digit epoch-millis literal is query identity — must survive
    assert "1700000000000" in _sanitize("Filter(ts > lit(1700000000000))")
    # hex ids (uuid fragments) and expr ids are volatile — must not
    s = _sanitize("scan cache-9f86d081884c k#12 ids=(3, 4) at 0x7f01")
    assert "9f86d081884c" not in s and "#12" not in s
    assert "ids=(3, 4)" not in s and "0x7f01" not in s


def test_regression_fires_on_forced_tier_flip(tmp_path):
    s = _session("fr-flip", {"spark.tpu.obs.profileDir": str(tmp_path)})
    try:
        _seed_table(s)
        s.sql(Q).toArrow()
        s.sql(Q).toArrow()
        s.conf.set("spark.tpu.compile.tier", "operator")
        df = s.sql(Q)
        df.toArrow()
        s.conf.unset("spark.tpu.compile.tier")
        regs = df.query_execution._last_regressions
        errors = [f for f in regs if f["severity"] == "error"]
        assert errors, f"tier flip raised no error regression: {regs}"
        assert any("launches" in f["metric"] for f in errors)
        # findings reached the live store (EXPLAIN ANALYZE's source)
        live = s.live_obs.findings_for(
            df.query_execution._last_ctx.query_id)
        assert any(f.get("kind") == "obs.regression" for f in live)
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# obs contract: the recorder adds zero kernel launches
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
def test_recorder_zero_launch_overhead(tmp_path, fusion):
    s = _session("fr-overhead", {"spark.tpu.fusion.enabled": fusion})
    try:
        _seed_table(s)

        def delta():
            s.sql(Q).toArrow()  # warm
            before = dict(KC.launches_by_kind)
            s.sql(Q).toArrow()
            return {k: v - before.get(k, 0)
                    for k, v in KC.launches_by_kind.items()
                    if v != before.get(k, 0)}

        without = delta()
        s.conf.set("spark.tpu.obs.profileDir", str(tmp_path))
        with_recorder = delta()
        s.conf.unset("spark.tpu.obs.profileDir")
        assert with_recorder == without, (
            f"flight recorder changed kernel dispatches (fusion={fusion}): "
            f"{with_recorder} vs {without}")
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# cluster: merged profile equals the local shape; chaos salvage
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster_session(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fr_cluster_profiles")
    s = _session("fr-cluster", {
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
        "spark.tpu.obs.profileDir": str(tmp),
    })
    _seed_table(s)
    yield s, str(tmp)
    s.stop()


def _agg_df(s):
    import spark_tpu.api.functions as F

    return (s.table("fr_t").repartition(2).groupBy("k")
            .agg(F.sum("v").alias("s")))


def test_cluster_profile_merges_worker_obs(cluster_session, tmp_path):
    s, profile_dir = cluster_session
    _agg_df(s).toArrow()
    df = _agg_df(s)
    df.toArrow()
    cluster_prof = df.query_execution._last_profile
    assert cluster_prof is not None and cluster_prof["cluster"] is True
    assert cluster_prof["launches_by_kind"], \
        "cluster profile lost the merged driver+worker launch deltas"
    assert df.query_execution._last_regressions == []
    # same query in a LOCAL session: the merged cluster profile must
    # have the local profile's shape — same structural query key, same
    # record fields, per-operator rows present both sides
    local = _session("fr-local", {
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.obs.profileDir": str(tmp_path)})
    try:
        _seed_table(local)
        ldf = _agg_df(local)
        ldf.toArrow()
        local_prof = ldf.query_execution._last_profile
    finally:
        local.stop()
    assert cluster_prof["query_key"] == local_prof["query_key"], \
        "cluster planning changed the structural query identity"
    assert set(cluster_prof) >= set(local_prof) - {"wasted", "findings"}
    root_rows = {p["ops"][0]["rows"] for p in (cluster_prof, local_prof)
                 if p["ops"]}
    assert len(root_rows) == 1, \
        f"merged per-operator rows diverge from local: {root_rows}"


def test_failed_attempt_obs_salvaged(cluster_session):
    from spark_tpu.utils import faults

    s, profile_dir = cluster_session
    df0 = s.table("fr_t").repartition(2)
    df0.collect()  # warm (and a clean baseline profile)
    s.conf.set("spark.tpu.faults.enabled", "true")
    s.conf.set("spark.tpu.faults.seed", "7")
    s.conf.set("spark.tpu.faults.points", "worker.task=once")
    faults.configure(s.conf)
    try:
        df = s.table("fr_t").repartition(2)
        rows = df.collect()
        assert len(rows) == 4000  # failover produced the right answer
        ctx = df.query_execution._last_ctx
        assert ctx.failed_attempt_obs, \
            "failed attempt's obs was discarded with the error"
        entry = ctx.failed_attempt_obs[0]
        assert entry["executor"] and "INJECTED" in entry["error"].upper() \
            or "worker.task" in entry["error"]
        assert "kernel_kinds" in entry and "spans" in entry
        # the wasted work reached the profile and the live findings
        prof = df.query_execution._last_profile
        assert prof.get("wasted"), "profile lost the wasted-attempt record"
        live = s.live_obs.findings_for(ctx.query_id)
        assert any(f.get("kind") == "obs.wasted-work" for f in live)
        # salvage counter is a deterministic-counter regression signal
        regs = df.query_execution._last_regressions
        assert any("task_failures_salvaged" in str(f.get("metric"))
                   for f in regs)
    finally:
        faults.reset()
        s.conf.set("spark.tpu.faults.enabled", "false")
        s.conf.unset("spark.tpu.faults.points")
        faults.configure(s.conf)
        s._sql_cluster.health.reset()


# ---------------------------------------------------------------------------
# degrade-path attribution (PR 11 follow-on (d))
# ---------------------------------------------------------------------------

def test_degraded_whole_tier_renders_member_attribution(tmp_path):
    import spark_tpu.api.functions as F
    from spark_tpu.utils import faults

    s = _session("fr-degrade", {
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.compile.tier": "whole",
        "spark.tpu.obs.profileDir": str(tmp_path),
    })
    try:
        _seed_table(s)

        def q():
            return (s.table("fr_t").repartition(2).groupBy("k")
                    .agg(F.sum("v").alias("s")))

        healthy = q()
        healthy.toArrow()
        healthy_graph = healthy.query_execution.plan_graph()
        # healthy whole run: single wrapper node owns the dispatch and
        # re-attributes through fused members (no inner child rows)
        wq = [nd for nd in healthy_graph if nd["op"] == "WholeQueryExec"]
        assert wq and wq[0].get("fused"), \
            "healthy whole-tier run lost its fused-member view"
        s.conf.set("spark.tpu.faults.enabled", "true")
        s.conf.set("spark.tpu.faults.points",
                   "kernel.dispatch=once@whole_query")
        faults.configure(s.conf)
        df = q()
        df.toArrow()
        faults.reset()
        graph = df.query_execution.plan_graph()
        inner = [nd for nd in graph
                 if nd["op"] not in ("WholeQueryExec", "AQE")]
        assert inner, "degraded run did not render the inner plan"
        assert any(nd["rows"] for nd in inner), \
            "inner operators carry no measured rows after degrade"
        assert any(nd.get("launches") for nd in inner), \
            "inner operators carry no attributed launches after degrade"
        wq = [nd for nd in graph if nd["op"] == "WholeQueryExec"]
        assert wq and not wq[0].get("fused"), \
            "degraded wrapper still renders fused members (duplication)"
        # the profile records the degrade and the per-member records
        prof = df.query_execution._last_profile
        assert (prof.get("tier") or {}).get("degraded") is True
        assert "runtime_degraded" in str(
            (prof.get("tier") or {}).get("details"))
        assert len(prof["ops"]) > 1, \
            "degraded profile is not comparable to a stage-tier profile"
    finally:
        faults.reset()
        s.stop()


# ---------------------------------------------------------------------------
# perfcheck comparator (the CI gate's pure logic)
# ---------------------------------------------------------------------------

def test_perfcheck_compare_flags_counter_drift():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "perfcheck", os.path.join(os.path.dirname(__file__), "..",
                                  "dev", "perfcheck.py"))
    pc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pc)
    base = {"queries": {"qk": {"detail": "agg", "compiles_steady": 0,
                               "launches": {"pipeline": 2},
                               "counters": {}}}}
    clean = {"qk": {"detail": "agg", "compiles_steady": 0,
                    "launches": {"pipeline": 2}, "counters": {}}}
    regs, notes = pc.compare(clean, base)
    assert regs == []
    worse = {"qk": {"detail": "agg", "compiles_steady": 1,
                    "launches": {"pipeline": 3, "gagg": 1},
                    "counters": {"scheduler.stage_retries": 1}}}
    regs, _ = pc.compare(worse, base)
    assert len(regs) == 4  # 2 kinds + compiles + retry counter
    regs, _ = pc.compare({}, base)
    assert regs and "missing" in regs[0]
    better = {"qk": {"detail": "agg", "compiles_steady": 0,
                     "launches": {"pipeline": 1}, "counters": {}}}
    regs, notes = pc.compare(better, base)
    assert regs == [] and notes, "improvement must pass with a note"
