"""RDD layer tests (reference: core/src/test RDD suites)."""

import os

import pytest

from spark_tpu.rdd import RDD, RDDContext


@pytest.fixture(scope="module")
def sc():
    ctx = RDDContext(parallelism=4)
    yield ctx
    ctx.stop()


def test_map_filter_collect(sc):
    r = sc.parallelize(range(100), 4)
    out = r.map(lambda x: x * 2).filter(lambda x: x % 10 == 0).collect()
    assert out == [x * 2 for x in range(100) if (x * 2) % 10 == 0]


def test_flatmap_count(sc):
    r = sc.parallelize(["a b", "c d e"], 2)
    assert r.flatMap(str.split).count() == 5


def test_reduce_fold_aggregate(sc):
    r = sc.parallelize(range(1, 101), 7)
    assert r.reduce(lambda a, b: a + b) == 5050
    assert r.fold(0, lambda a, b: a + b) == 5050
    n, s = r.aggregate((0, 0), lambda z, x: (z[0] + 1, z[1] + x),
                       lambda a, b: (a[0] + b[0], a[1] + b[1]))
    assert (n, s) == (100, 5050)
    assert r.sum() == 5050
    assert r.max() == 100 and r.min() == 1
    assert abs(r.mean() - 50.5) < 1e-9


def test_reduce_by_key(sc):
    r = sc.parallelize([("a", 1), ("b", 2), ("a", 3), ("c", 4), ("b", 5)], 3)
    out = dict(r.reduceByKey(lambda a, b: a + b).collect())
    assert out == {"a": 4, "b": 7, "c": 4}


def test_group_by_key(sc):
    r = sc.parallelize([("x", i) for i in range(10)], 4)
    out = r.groupByKey().collect()
    assert len(out) == 1
    assert sorted(out[0][1]) == list(range(10))


def test_join(sc):
    a = sc.parallelize([("k1", 1), ("k2", 2)], 2)
    b = sc.parallelize([("k2", "x"), ("k3", "y")], 2)
    assert a.join(b).collect() == [("k2", (2, "x"))]
    left = dict(a.leftOuterJoin(b).collect())
    assert left == {"k1": (1, None), "k2": (2, "x")}
    full = dict(a.fullOuterJoin(b).collect())
    assert full == {"k1": (1, None), "k2": (2, "x"), "k3": (None, "y")}


def test_sort_by_key(sc):
    import random

    data = list(range(200))
    random.Random(0).shuffle(data)
    r = sc.parallelize([(x, x) for x in data], 5)
    out = [k for k, _ in r.sortByKey().collect()]
    assert out == sorted(data)
    out_desc = [k for k, _ in r.sortByKey(False).collect()]
    assert out_desc == sorted(data, reverse=True)


def test_distinct_union_zip(sc):
    r = sc.parallelize([1, 2, 2, 3, 3, 3], 3)
    assert sorted(r.distinct().collect()) == [1, 2, 3]
    u = r.union(sc.parallelize([9], 1))
    assert sorted(u.collect()) == [1, 2, 2, 3, 3, 3, 9]
    z = sc.parallelize([1, 2], 2).zip(sc.parallelize(["a", "b"], 2))
    assert z.collect() == [(1, "a"), (2, "b")]


def test_repartition_coalesce(sc):
    r = sc.parallelize(range(100), 8)
    assert sorted(r.repartition(3).collect()) == list(range(100))
    assert r.repartition(3).num_partitions() == 3
    c = r.coalesce(2)
    assert c.num_partitions() == 2
    assert sorted(c.collect()) == list(range(100))


def test_cache_and_checkpoint(sc, tmp_path):
    calls = []

    def f(x):
        calls.append(x)
        return x

    r = sc.parallelize(range(10), 2).map(f).cache()
    r.collect()
    n1 = len(calls)
    r.collect()
    assert len(calls) == n1  # cached, no recompute

    sc.setCheckpointDir(str(tmp_path))
    r2 = sc.parallelize(range(5), 1).map(lambda x: x * 3)
    r2.checkpoint()
    assert r2.parents == []
    assert r2.collect() == [0, 3, 6, 9, 12]


def test_broadcast_accumulator(sc):
    b = sc.broadcast({"m": 10})
    acc = sc.accumulator(0)
    r = sc.parallelize(range(10), 4)
    out = r.map(lambda x: x * b.value["m"]).collect()
    assert out == [x * 10 for x in range(10)]
    r.foreach(lambda x: acc.add(x))
    assert acc.value == 45


def test_take_top_countbyvalue(sc):
    r = sc.parallelize([5, 3, 8, 1, 9, 3], 3)
    assert r.take(2) == [5, 3]
    assert r.top(2) == [9, 8]
    assert r.countByValue()[3] == 2


def test_text_file_roundtrip(sc, tmp_path):
    p = str(tmp_path / "out")
    sc.parallelize(["alpha", "beta", "gamma"], 2).saveAsTextFile(p)
    back = sc.textFile(p + "/part-*" if False else p)
    assert sorted(back.collect()) == ["alpha", "beta", "gamma"]


def test_pipe(sc):
    r = sc.parallelize(["a", "b"], 1)
    assert r.pipe("cat").collect() == ["a", "b"]


def test_combine_by_key(sc):
    r = sc.parallelize([("a", 1), ("a", 2), ("b", 3)], 2)
    out = dict(r.combineByKey(lambda v: [v],
                              lambda c, v: c + [v],
                              lambda c1, c2: c1 + c2).collect())
    assert sorted(out["a"]) == [1, 2]
    assert out["b"] == [3]


def test_sample_deterministic(sc):
    r = sc.parallelize(range(1000), 4)
    s1 = r.sample(False, 0.1, seed=1).collect()
    s2 = r.sample(False, 0.1, seed=1).collect()
    assert s1 == s2
    assert 50 < len(s1) < 200
