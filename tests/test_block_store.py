"""Unified tiered block store (reference:
core/storage/BlockManager.scala, memory/MemoryStore.scala
evictBlocksToFreeSpace, DiskStore.scala): host-RAM LRU under a budget,
eviction to disk, drop + recompute-from-lineage beyond disk."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.config import SQLConf
from spark_tpu.exec.block_store import BlockManager
from spark_tpu.exec.context import Metrics


def _bm(mem=1000, disk=3000, tmp=None):
    conf = SQLConf({"spark.tpu.cache.memoryBudgetBytes": mem,
                    "spark.tpu.cache.diskBudgetBytes": disk})
    return BlockManager(conf, spill_dir=tmp, metrics=Metrics())


def test_put_get_host_tier(tmp_path):
    bm = _bm(tmp=str(tmp_path))
    bm.put("a", b"x" * 100)
    assert bm.get("a") == b"x" * 100
    assert bm.stats()["host_blocks"] == 1
    assert bm.metrics.counters["cache.host_hits"] == 1


def test_lru_eviction_to_disk(tmp_path):
    bm = _bm(mem=250, tmp=str(tmp_path))
    bm.put("a", b"a" * 100)
    bm.put("b", b"b" * 100)
    bm.put("c", b"c" * 100)    # evicts a (LRU) to disk
    st = bm.stats()
    assert st["host_blocks"] == 2 and st["disk_blocks"] == 1
    assert bm.metrics.counters["cache.evictions_to_disk"] == 1
    # a still readable — from disk, promoted back to host (evicting b)
    assert bm.get("a") == b"a" * 100
    assert bm.metrics.counters["cache.disk_hits"] == 1
    assert bm.stats()["disk_blocks"] == 1   # b took a's place on disk


def test_access_refreshes_lru_order(tmp_path):
    bm = _bm(mem=250, tmp=str(tmp_path))
    bm.put("a", b"a" * 100)
    bm.put("b", b"b" * 100)
    assert bm.get("a")          # a is now most-recent
    bm.put("c", b"c" * 100)     # must evict b, not a
    assert bm.stats()["host_blocks"] == 2
    assert bm.get("a") == b"a" * 100
    assert bm.metrics.counters["cache.evictions_to_disk"] == 1
    assert bm.metrics.counters["cache.host_hits"] >= 2


def test_drop_beyond_disk_budget(tmp_path):
    bm = _bm(mem=150, disk=250, tmp=str(tmp_path))
    for name in "abcde":
        bm.put(name, name.encode() * 100)
    # 5 × 100B through a 150B host + 250B disk → drops happened
    assert bm.metrics.counters["cache.blocks_dropped"] >= 1
    st = bm.stats()
    assert st["host_bytes"] <= 150 and st["disk_bytes"] <= 250
    # dropped blocks read as miss (recompute-from-lineage signal)
    assert bm.get("a") is None
    assert bm.metrics.counters["cache.misses"] >= 1


def test_oversized_block_goes_straight_to_disk(tmp_path):
    bm = _bm(mem=100, disk=10_000, tmp=str(tmp_path))
    bm.put("big", b"z" * 5000)
    assert bm.stats()["host_blocks"] == 0
    assert bm.get("big") == b"z" * 5000   # still served (from disk)


def test_remove_and_clear(tmp_path):
    bm = _bm(tmp=str(tmp_path))
    bm.put("a", b"1" * 10)
    bm.put("b", b"2" * 10)
    bm.remove("a")
    assert bm.get("a") is None
    bm.clear()
    assert bm.stats()["host_blocks"] == 0


def test_device_tier_unpins_lru_over_budget(tmp_path):
    bm = _bm(tmp=str(tmp_path))
    bm.device_budget = 250
    owner = {1: "batch1", 2: "batch2", 3: "batch3"}
    bm.pin_device("d1", owner, 1, 100)
    bm.pin_device("d2", owner, 2, 100)
    bm.pin_device("d3", owner, 3, 100)   # over budget → d1 unpinned
    assert 1 not in owner                 # device buffers released
    assert 2 in owner and 3 in owner
    assert bm.metrics.counters["cache.device_unpinned"] == 1
    assert bm.stats()["device_bytes"] == 200


# ---------------------------------------------------------------------------
# End-to-end: df.cache() through the tiered store
# ---------------------------------------------------------------------------

@pytest.fixture()
def spark():
    from spark_tpu.api.session import TpuSession

    s = TpuSession("blockstore", {
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.cache.memoryBudgetBytes": 70_000,
        "spark.tpu.cache.diskBudgetBytes": 130_000,
    })
    yield s
    s.stop()


def _table(seed, n=2000):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 50, n),
                     "v": rng.integers(0, 1000, n)})


def test_cache_twice_the_budget_completes_with_evictions(spark):
    """The VERDICT bar: caching ~2× the configured budget must complete
    (evicting/dropping, recomputing from lineage on miss) instead of
    pinning unbounded memory — and every cached frame stays correct."""
    dfs, expected = [], []
    for i in range(8):          # 8 × ~30KB through 20KB RAM + 40KB disk
        df = spark.createDataFrame(_table(i)).filter("v >= 0")
        df.cache()
        expected.append(sorted((r["k"], r["v"]) for r in df.collect()))
        dfs.append(df)
    m = spark._metrics.snapshot()["counters"]
    assert m.get("cache.evictions_to_disk", 0) >= 1, m
    assert m.get("cache.blocks_dropped", 0) >= 1, m
    # every frame still answers correctly through a NEW query over the
    # cached subtree (dropped blocks recompute from lineage)
    for df, want in zip(dfs, expected):
        got = sorted((r["k"], r["v"])
                     for r in df.filter("v >= -1").collect())
        assert got == want
    m = spark._metrics.snapshot()["counters"]
    assert m.get("cache.recomputed_from_lineage", 0) >= 1, m


def test_cached_plan_substitution_hits_store(spark):
    df = spark.createDataFrame(_table(42)).groupBy("k").count()
    df.cache()
    base = spark._metrics.snapshot()["counters"].get("cache.host_hits", 0)
    got = {r["k"]: r["count"]
           for r in df.filter("count >= 0").collect()}
    t = _table(42)
    want: dict = {}
    for k in t["k"].to_pylist():
        want[k] = want.get(k, 0) + 1
    assert got == want
    after = spark._metrics.snapshot()["counters"].get("cache.host_hits", 0)
    assert after > base

    df.unpersist()
    assert spark.block_manager.stats()["host_blocks"] == 0
