"""DSv2 pushdown SPI (reference: sql/catalyst connector/read/
SupportsPushDownFilters.java, SupportsPushDownLimit.java,
SupportsPushDownAggregates.java + V2ScanRelationPushDown): the JDBC
source must provably execute WHERE / LIMIT / aggregation REMOTELY —
asserted on the generated SQL."""

import sqlite3

import pytest

from spark_tpu.io.sources import JDBCSource


@pytest.fixture()
def db(tmp_path):
    path = str(tmp_path / "push.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE emp (id INTEGER, dept TEXT, pay REAL)")
    rows = [(i, "eng" if i % 3 else "ops", 100.0 + i) for i in range(50)]
    conn.executemany("INSERT INTO emp VALUES (?,?,?)", rows)
    conn.commit()
    conn.close()
    return path, rows


def _jdbc_df(spark, path, **opts):
    return spark.read.jdbc(f"jdbc:sqlite:{path}", "emp", **opts)


def _scan_sources(df):
    from spark_tpu.physical.operators import ScanExec

    return [n.source for n in df.query_execution.physical.iter_nodes()
            if isinstance(n, ScanExec)]


class TestFilterPushdown:
    def test_where_executes_remotely(self, spark, db):
        path, rows = db
        df = _jdbc_df(spark, path).filter("id >= 40").filter("dept = 'eng'")
        got = sorted(r["id"] for r in df.collect())
        want = sorted(i for i, d, _ in rows if i >= 40 and d == "eng")
        assert got == want
        src = _scan_sources(df)[0]
        assert '"id" >= 40' in src.last_sql, src.last_sql
        assert '"dept" = \'eng\'' in src.last_sql, src.last_sql

    def test_in_list_pushdown(self, spark, db):
        path, _ = db
        df = _jdbc_df(spark, path).filter("id in (1, 2, 3)")
        assert sorted(r["id"] for r in df.collect()) == [1, 2, 3]
        src = _scan_sources(df)[0]
        assert '"id" IN (1, 2, 3)' in src.last_sql, src.last_sql

    def test_residual_stays_in_engine(self, spark, db):
        """A predicate the source cannot translate (col-vs-col) stays an
        engine filter while the translatable one still pushes."""
        path, rows = db
        df = _jdbc_df(spark, path).filter("id >= 45 and pay > id")
        got = sorted(r["id"] for r in df.collect())
        want = sorted(i for i, _, p in rows if i >= 45 and p > i)
        assert got == want
        src = _scan_sources(df)[0]
        assert '"id" >= 45' in src.last_sql
        assert "pay >" not in src.last_sql  # col-vs-col not pushed

    def test_string_literal_escaping(self, spark, db):
        path, _ = db
        df = _jdbc_df(spark, path).filter("dept = 'o''ps'")
        assert df.collect() == []
        src = _scan_sources(df)[0]
        assert '"dept" = \'o\'\'ps\'' in src.last_sql


class TestLimitPushdown:
    def test_limit_executes_remotely(self, spark, db):
        path, _ = db
        df = _jdbc_df(spark, path).limit(5)
        assert len(df.collect()) == 5
        src = _scan_sources(df)[0]
        assert src.last_sql.endswith("LIMIT 5"), src.last_sql

    def test_filter_then_limit_compose(self, spark, db):
        path, rows = db
        df = _jdbc_df(spark, path).filter("id >= 10").limit(3)
        assert len(df.collect()) == 3
        src = _scan_sources(df)[0]
        assert '"id" >= 10' in src.last_sql and "LIMIT 3" in src.last_sql


class TestAggregationPushdown:
    def test_group_by_executes_remotely(self, spark, db):
        path, rows = db
        import spark_tpu.api.functions as F

        df = _jdbc_df(spark, path).groupBy("dept") \
            .agg(F.sum("pay"), F.count("id"))
        out = {r["dept"]: r for r in df.collect()}
        import collections

        cnt = collections.Counter(d for _, d, _ in rows)
        assert {k: v["count(id)"] for k, v in out.items()} == dict(cnt)
        for dept in cnt:
            want = sum(p for _, d, p in rows if d == dept)
            assert abs(out[dept]["sum(pay)"] - want) < 1e-6
        src = _scan_sources(df)[0]
        assert 'GROUP BY "dept"' in src.last_sql, src.last_sql
        assert 'sum("pay")' in src.last_sql and 'count("id")' in src.last_sql

    def test_global_agg_pushdown(self, spark, db):
        path, rows = db
        import spark_tpu.api.functions as F

        df = _jdbc_df(spark, path).groupBy().agg(F.max("pay"))
        assert df.collect()[0]["max(pay)"] == max(p for *_, p in rows)
        src = _scan_sources(df)[0]
        assert 'max("pay")' in src.last_sql and "GROUP BY" not in src.last_sql

    def test_agg_over_pushed_filter(self, spark, db):
        path, rows = db
        import spark_tpu.api.functions as F

        df = _jdbc_df(spark, path).filter("dept = 'eng'") \
            .groupBy("dept").agg(F.count("id"))
        assert df.collect()[0]["count(id)"] == sum(
            1 for _, d, _ in rows if d == "eng")
        src = _scan_sources(df)[0]
        assert 'WHERE "dept" = \'eng\'' in src.last_sql
        assert 'GROUP BY "dept"' in src.last_sql

    def test_partitioned_scan_declines_agg(self, spark, db):
        """A range-partitioned JDBC scan must NOT push a whole-query
        aggregate (each split would aggregate independently)."""
        path, rows = db
        import spark_tpu.api.functions as F

        df = _jdbc_df(spark, path, column="id",
                      numPartitions=4).groupBy("dept") \
            .agg(F.count("id"))
        import collections

        cnt = collections.Counter(d for _, d, _ in rows)
        assert {r["dept"]: r["count(id)"]
                for r in df.collect()} == dict(cnt)
        src = _scan_sources(df)[0]
        assert "GROUP BY" not in (src.last_sql or "")
