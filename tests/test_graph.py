"""Graph/Pregel tests (reference: graphx test suites)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.graph import Graph


def test_degrees():
    g = Graph.from_edges([1, 1, 2], [2, 3, 3])
    assert list(g.out_degrees()) == [2, 1, 0]
    assert list(g.in_degrees()) == [0, 1, 2]


def test_pagerank_star():
    # star: everyone links to hub 0
    g = Graph.from_edges([1, 2, 3, 4], [0, 0, 0, 0])
    pr = g.page_rank(num_iter=30)
    assert pr[0] > pr[1]
    assert abs(pr[1] - pr[4]) < 1e-9


def test_pagerank_cycle_uniform():
    g = Graph.from_edges([0, 1, 2], [1, 2, 0])
    pr = g.page_rank(num_iter=50)
    assert abs(pr[0] - pr[1]) < 1e-6
    assert abs(pr[0] - 1.0) < 1e-3  # normalized to sum n


def test_connected_components():
    g = Graph.from_edges([1, 2, 10, 11], [2, 3, 11, 12])
    cc = g.connected_components()
    assert cc[1] == cc[2] == cc[3] == 1
    assert cc[10] == cc[11] == cc[12] == 10


def test_triangle_count():
    # triangle 0-1-2 plus a dangling edge 2-3
    g = Graph.from_edges([0, 1, 2, 2], [1, 2, 0, 3])
    tc = g.triangle_count()
    assert tc[0] == tc[1] == tc[2] == 1
    assert tc[3] == 0


def test_shortest_paths():
    g = Graph.from_edges([0, 1, 2], [1, 2, 3])
    sp = g.shortest_paths([0])
    assert sp[0][0] == 0
    assert sp[1][0] == 1
    assert sp[3][0] == 3


def test_from_dataframes(spark):
    v = spark.createDataFrame(pa.table({"id": [1, 2, 3]}))
    e = spark.createDataFrame(pa.table({"src": [1, 2], "dst": [2, 3]}))
    g = Graph.from_dataframes(v, e)
    cc = g.connected_components()
    assert len(set(cc.values())) == 1


def test_custom_pregel():
    # max-value propagation
    import jax

    g = Graph.from_edges([0, 1, 2], [1, 2, 0])
    init = np.array([5, 9, 1], dtype=np.int64)

    def superstep(state, src, dst):
        import jax.numpy as jnp

        msg = jax.ops.segment_max(state[src], dst, num_segments=3)
        return jnp.maximum(state, msg)

    out = g.pregel(init, superstep, max_iterations=5)
    assert list(out) == [9, 9, 9]
