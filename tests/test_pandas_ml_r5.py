"""r5 pandas/ML breadth parity: rolling/expanding vs real pandas,
groupby.apply, to_datetime + dt accessor, MultiIndex via set_index and
groupby keys; implicit ALS and parallel CrossValidator (reference:
python/pyspark/pandas window.py/groupby.py/datetimes.py,
ml/recommendation/ALS.scala implicitPrefs, ml/tuning/CrossValidator)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture()
def ps(spark):
    import spark_tpu.pandas as ps_mod

    return ps_mod


@pytest.fixture()
def pdf():
    rng = np.random.default_rng(5)
    return pd.DataFrame({
        "g": ["a", "a", "b", "b", "a", "b"],
        "h": ["x", "y", "x", "y", "x", "y"],
        "v": [1.0, 2.0, 3.0, np.nan, 5.0, 6.0],
        "w": rng.integers(0, 10, 6).astype("int64"),
    })


class TestRollingExpanding:
    @pytest.mark.parametrize("fn", ["sum", "mean", "min", "max", "count"])
    def test_rolling_matches_pandas(self, ps, pdf, fn):
        df = ps.from_pandas(pdf)
        got = getattr(df["v"].rolling(3), fn)()
        want = getattr(pdf["v"].rolling(3), fn)()
        np.testing.assert_allclose(got.to_numpy(dtype=float),
                                   want.to_numpy(dtype=float))

    def test_rolling_min_periods(self, ps, pdf):
        df = ps.from_pandas(pdf)
        got = df["v"].rolling(3, min_periods=1).sum()
        want = pdf["v"].rolling(3, min_periods=1).sum()
        np.testing.assert_allclose(got.to_numpy(dtype=float),
                                   want.to_numpy(dtype=float))

    @pytest.mark.parametrize("fn", ["sum", "mean", "max"])
    def test_expanding_matches_pandas(self, ps, pdf, fn):
        df = ps.from_pandas(pdf)
        got = getattr(df["v"].expanding(), fn)()
        want = getattr(pdf["v"].expanding(), fn)()
        np.testing.assert_allclose(got.to_numpy(dtype=float),
                                   want.to_numpy(dtype=float))

    def test_rolling_std(self, ps, pdf):
        df = ps.from_pandas(pdf)
        got = df["w"].rolling(2).std()
        want = pdf["w"].rolling(2).std()
        np.testing.assert_allclose(got.to_numpy(dtype=float),
                                   want.to_numpy(dtype=float))


class TestGroupbyApplyAndMultiIndex:
    def test_groupby_apply_frame_fn(self, ps, pdf):
        df = ps.from_pandas(pdf)

        def top1(g):
            return g.nlargest(1, "w")

        got = df.groupby("g").apply(top1).to_pandas()
        want = pd.concat([top1(grp) for _, grp in pdf.groupby("g")])
        got_s = got.sort_values(["g", "w"]).reset_index(drop=True)
        want_s = want.sort_values(["g", "w"]).reset_index(drop=True)
        pd.testing.assert_frame_equal(
            got_s[["g", "h", "v", "w"]], want_s[["g", "h", "v", "w"]])

    def test_groupby_apply_scalar_fn(self, ps, pdf):
        df = ps.from_pandas(pdf)
        got = df.groupby("g").apply(lambda g: g["w"].sum()).to_pandas()
        want = pdf.groupby("g")["w"].sum()
        got_map = dict(zip(got["g"], got["value"]))
        assert got_map == want.to_dict()

    def test_groupby_multikey_agg_yields_multiindex(self, ps, pdf):
        df = ps.from_pandas(pdf)
        got = df.groupby(["g", "h"]).agg({"w": "sum"}).to_pandas()
        want = pdf.groupby(["g", "h"]).agg(w=("w", "sum"))
        assert isinstance(got.index, pd.MultiIndex)
        assert got["w"].sort_index().to_dict() == \
            want["w"].sort_index().to_dict()

    def test_set_index_reset_index(self, ps, pdf):
        df = ps.from_pandas(pdf)
        got = df.set_index(["g", "h"]).to_pandas()
        assert isinstance(got.index, pd.MultiIndex)
        assert list(got.index.names) == ["g", "h"]
        back = df.set_index("g").reset_index().to_pandas()
        assert "g" in back.columns


class TestToDatetime:
    def test_cast_strings(self, ps, spark):
        df = ps.from_pandas(pd.DataFrame(
            {"s": ["2020-01-02 03:04:05", "2021-06-07 08:09:10"]}))
        ts = ps.to_datetime(df["s"])
        vals = ts.to_pandas()
        assert vals.iloc[0] == pd.Timestamp("2020-01-02 03:04:05")

    def test_dt_accessor(self, ps):
        df = ps.from_pandas(pd.DataFrame(
            {"s": ["2020-03-02 13:04:05"]}))
        ts = ps.to_datetime(df["s"])
        assert ts.dt.year.to_pandas().iloc[0] == 2020
        assert ts.dt.month.to_pandas().iloc[0] == 3
        assert ts.dt.day.to_pandas().iloc[0] == 2
        assert ts.dt.hour.to_pandas().iloc[0] == 13
        # 2020-03-02 is a Monday → pandas dayofweek 0
        assert ts.dt.dayofweek.to_pandas().iloc[0] == 0

    def test_host_format_parse(self, ps):
        df = ps.from_pandas(pd.DataFrame({"s": ["02/29/2020"]}))
        ts = ps.to_datetime(df["s"], format="%m/%d/%Y")
        assert ts.to_pandas().iloc[0] == pd.Timestamp("2020-02-29")


class TestImplicitALS:
    def test_implicit_ranks_observed_above_unobserved(self, spark):
        from spark_tpu.ml.recommendation import ALS

        # two user cliques with disjoint item sets
        rows = []
        for u in range(4):
            for i in range(4):
                if (u < 2) == (i < 2):
                    rows.append((u, i, 3.0))
        df = spark.createDataFrame(pa.table({
            "user": [r[0] for r in rows],
            "item": [r[1] for r in rows],
            "rating": [r[2] for r in rows]}))
        m = ALS(rank=4, maxIter=10, implicitPrefs=True, alpha=10.0,
                regParam=0.05).fit(df)
        # observed pairs score near 1; cross-clique pairs near 0
        all_pairs = pa.table({
            "user": [0, 0, 3, 3], "item": [1, 3, 2, 0]})
        scored = m.transform(spark.createDataFrame(all_pairs)).collect()
        s = {(r["user"], r["item"]): r["prediction"] for r in scored}
        assert s[(0, 1)] > 0.5 and s[(3, 2)] > 0.5     # observed clique
        assert s[(0, 3)] < 0.5 and s[(3, 0)] < 0.5     # cross-clique

    def test_explicit_unchanged(self, spark):
        from spark_tpu.ml.recommendation import ALS

        df = spark.createDataFrame(pa.table({
            "user": [0, 0, 1, 1], "item": [0, 1, 0, 1],
            "rating": [5.0, 1.0, 1.0, 5.0]}))
        m = ALS(rank=2, maxIter=15).fit(df)
        out = {(r["user"], r["item"]): r["prediction"]
               for r in m.transform(df).collect()}
        assert abs(out[(0, 0)] - 5.0) < 1.0
        assert abs(out[(0, 1)] - 1.0) < 1.0


class TestParallelCrossValidator:
    def test_parallel_matches_serial(self, spark):
        from spark_tpu.ml.evaluation import RegressionEvaluator
        from spark_tpu.ml.regression import LinearRegression
        from spark_tpu.ml.tuning import CrossValidator, ParamGridBuilder

        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, 60)
        y = 3 * x + rng.normal(0, 0.1, 60)
        df = spark.createDataFrame(pa.table({"x": x, "label": y}))
        df = df.withColumn("features", df["x"])
        df._ml_features = ["x"]
        grid = ParamGridBuilder().addGrid(
            "regParam", [0.01, 0.1, 1.0]).build()
        ev = RegressionEvaluator(metricName="rmse")
        lr = LinearRegression(featuresCol="features", labelCol="label")

        serial = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                                evaluator=ev, numFolds=3).fit(df)
        par = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                             evaluator=ev, numFolds=3,
                             parallelism=4).fit(df)
        np.testing.assert_allclose(serial.avgMetrics, par.avgMetrics,
                                   rtol=1e-8)
