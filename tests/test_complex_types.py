"""Struct/Map type tests end-to-end (reference:
sqlcat/expressions/complexTypeCreator.scala, complexTypeExtractors.scala,
UnsafeMapData.java roles — here nested values dictionary-encode with
device gather LUTs for field/key access)."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F


@pytest.fixture()
def nested(spark):
    t = pa.table({
        "id": [1, 2, 3],
        "person": pa.array(
            [{"name": "ann", "age": 31}, {"name": "bob", "age": 25}, None],
            pa.struct([("name", pa.string()), ("age", pa.int64())])),
        "tags": pa.array([[("x", 1), ("y", 2)], [("x", 9)], []],
                         pa.map_(pa.string(), pa.int64())),
    })
    df = spark.createDataFrame(t)
    df.createOrReplaceTempView("ct_nested")
    return df


def test_struct_field_access_sql(spark, nested):
    out = spark.sql("SELECT id, person.name, person.age FROM ct_nested "
                    "ORDER BY id").toArrow().to_pydict()
    assert out["name"] == ["ann", "bob", None]
    assert out["age"] == [31, 25, None]


def test_struct_field_access_dsl(spark, nested):
    out = nested.select(
        nested["id"], nested["person"].getField("age").alias("a")) \
        .orderBy("id").toArrow().to_pydict()
    assert out["a"] == [31, 25, None]


def test_struct_in_predicate_and_groupby(spark, nested):
    out = spark.sql("SELECT id FROM ct_nested WHERE person.age > 28") \
        .toArrow().to_pydict()
    assert out["id"] == [1]
    out = spark.sql("SELECT person.name AS nm, count(*) n FROM ct_nested "
                    "GROUP BY person.name ORDER BY nm NULLS FIRST") \
        .toArrow().to_pydict()
    assert out["nm"] == [None, "ann", "bob"]


def test_struct_ctor(spark, nested):
    out = spark.sql("SELECT named_struct('x', id, 'y', id * 2) ns "
                    "FROM ct_nested ORDER BY id").toArrow().to_pylist()
    assert out[0]["ns"] == {"x": 1, "y": 2}
    out = spark.sql("SELECT struct(id, person.name) st FROM ct_nested "
                    "ORDER BY id LIMIT 1").toArrow().to_pylist()
    assert out[0]["st"] == {"id": 1, "name": "ann"}


def test_map_access(spark, nested):
    out = spark.sql("SELECT id, tags['x'] x, element_at(tags, 'y') y "
                    "FROM ct_nested ORDER BY id").toArrow().to_pydict()
    assert out["x"] == [1, 9, None]
    assert out["y"] == [2, None, None]


def test_map_functions(spark, nested):
    out = spark.sql("SELECT map_keys(tags) mk, map_values(tags) mv, "
                    "size(tags) sz, map_contains_key(tags, 'y') hy "
                    "FROM ct_nested ORDER BY id").toArrow().to_pydict()
    assert out["mk"] == [["x", "y"], ["x"], []]
    assert out["mv"] == [[1, 2], [9], []]
    assert out["sz"] == [2, 1, 0]
    assert out["hy"] == [True, False, False]


def test_map_ctor_and_roundtrip(spark, nested):
    t = spark.sql("SELECT map('a', id, 'b', id + 1) m FROM ct_nested "
                  "ORDER BY id").toArrow()
    assert t.column("m").to_pylist()[0] == [("a", 1), ("b", 2)]


def test_explode_map_keys(spark, nested):
    out = spark.sql("SELECT id, explode(map_keys(tags)) k FROM ct_nested "
                    "ORDER BY id, k").toArrow().to_pydict()
    assert list(zip(out["id"], out["k"])) == [(1, "x"), (1, "y"), (2, "x")]


def test_struct_roundtrip_through_shuffle(spark, nested):
    # structs survive a repartition exchange (dictionary ships with batch)
    out = nested.repartition(3).select("id", "person") \
        .orderBy("id").toArrow().to_pylist()
    assert out[0]["person"] == {"name": "ann", "age": 31}
    assert out[2]["person"] is None


def test_order_by_hidden_struct_field(spark, nested):
    out = spark.sql("SELECT id FROM ct_nested "
                    "ORDER BY person.age NULLS LAST, id") \
        .toArrow().to_pydict()
    assert out["id"] == [2, 1, 3]


def test_struct_date_timestamp_fields(spark):
    import datetime as dt

    t = pa.table({
        "id": [1, 2],
        "ev": pa.array(
            [{"d": dt.date(2020, 1, 5), "ts": dt.datetime(2020, 1, 5, 12)},
             {"d": dt.date(2021, 3, 1), "ts": dt.datetime(2021, 3, 1, 8)}],
            pa.struct([("d", pa.date32()), ("ts", pa.timestamp("us"))])),
    })
    spark.createDataFrame(t).createOrReplaceTempView("ct_ev")
    out = spark.sql("SELECT id, ev.d, year(ev.d) y, hour(ev.ts) h "
                    "FROM ct_ev ORDER BY id").toArrow().to_pydict()
    assert out["y"] == [2020, 2021]
    assert out["h"] == [12, 8]
    assert out["d"] == [dt.date(2020, 1, 5), dt.date(2021, 3, 1)]


def test_getitem_on_unresolved_column(spark, nested):
    out = nested.select(F.col("tags")["x"].alias("x")) \
        .toArrow().to_pydict()
    assert out["x"] == [1, 9, None]


def test_nonliteral_map_key_clear_error(spark, nested):
    from spark_tpu.errors import AnalysisException

    with pytest.raises(AnalysisException, match="literal key"):
        spark.sql("SELECT tags[id] FROM ct_nested").toArrow()


def test_map_key_order_insensitive_groupby(spark):
    # {'x':1,'y':2} and {'y':2,'x':1} are the SAME map value
    t1 = pa.table({"m": pa.array([[("x", 1), ("y", 2)]],
                                 pa.map_(pa.string(), pa.int64()))})
    t2 = pa.table({"m": pa.array([[("y", 2), ("x", 1)]],
                                 pa.map_(pa.string(), pa.int64()))})
    df = spark.createDataFrame(t1).union(spark.createDataFrame(t2))
    out = df.groupBy("m").agg(F.count("*").alias("n")).toArrow().to_pydict()
    assert out["n"] == [2]
    # the representative key must survive the exchange with its dictionary
    assert sorted(out["m"][0]) == [("x", 1), ("y", 2)]
