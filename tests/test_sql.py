"""SQL layer tests (role of the reference's SQLQueryTestSuite golden files —
inline expected results here; golden-file harness in test_golden.py)."""

import pyarrow as pa
import pytest

from spark_tpu.errors import AnalysisException, ParseException


@pytest.fixture()
def store(spark):
    sales = spark.createDataFrame(pa.table({
        "item": [1, 2, 3, 1, 2, 1, 4],
        "qty": [10, 20, 30, 40, 50, 60, 5],
        "price": [1.5, 2.0, 0.5, 1.5, 2.0, 1.5, 9.9],
    }))
    items = spark.createDataFrame(pa.table({
        "id": [1, 2, 3],
        "name": ["apple", "banana", "cherry"],
    }))
    sales.createOrReplaceTempView("sales")
    items.createOrReplaceTempView("items")
    return spark


def q(spark, text):
    return spark.sql(text).toArrow().to_pydict()


def test_basic_select(store):
    out = q(store, "SELECT item, qty FROM sales WHERE qty >= 30 ORDER BY qty")
    assert out["item"] == [3, 1, 2, 1]
    assert out["qty"] == [30, 40, 50, 60]


def test_join_agg_having(store):
    out = q(store, """
        SELECT i.name, SUM(s.qty * s.price) AS revenue, COUNT(*) AS n
        FROM sales s JOIN items i ON s.item = i.id
        GROUP BY i.name HAVING SUM(s.qty) > 40
        ORDER BY revenue DESC""")
    assert out["name"] == ["apple", "banana"]
    assert out["revenue"] == [165.0, 140.0]
    assert out["n"] == [3, 2]


def test_left_join_nulls(store):
    out = q(store, """SELECT s.item, i.name FROM sales s
                      LEFT JOIN items i ON s.item = i.id
                      WHERE s.qty = 5""")
    assert out["name"] == [None]


def test_semi_anti(store):
    out = q(store, """SELECT item FROM sales s LEFT ANTI JOIN items i
                      ON s.item = i.id""")
    assert out["item"] == [4]
    out2 = q(store, """SELECT DISTINCT item FROM sales s LEFT SEMI JOIN items i
                       ON s.item = i.id ORDER BY item""")
    assert out2["item"] == [1, 2, 3]


def test_union_distinct_and_all(store):
    out = q(store, "SELECT item FROM sales UNION SELECT id FROM items "
                   "ORDER BY item")
    assert out["item"] == [1, 2, 3, 4]
    out2 = q(store, "SELECT item FROM sales UNION ALL SELECT id FROM items")
    assert len(out2["item"]) == 10


def test_cte(store):
    out = q(store, """WITH big AS (SELECT * FROM sales WHERE qty >= 30)
                      SELECT count(*) AS c, min(qty) AS mn FROM big""")
    assert out["c"] == [4]
    assert out["mn"] == [30]


def test_subquery_in_from(store):
    out = q(store, """SELECT t.s FROM
                      (SELECT item, sum(qty) AS s FROM sales GROUP BY item) t
                      WHERE t.s > 50 ORDER BY t.s""")
    assert out["s"] == [70, 110]


def test_case_expressions(store):
    out = q(store, """SELECT item,
                        CASE WHEN qty < 20 THEN 'low'
                             WHEN qty < 50 THEN 'mid'
                             ELSE 'high' END AS band
                      FROM sales ORDER BY item, qty""")
    assert out["band"] == ["low", "mid", "high", "mid", "high", "mid", "low"]


def test_simple_case(store):
    out = q(store, "SELECT CASE item WHEN 1 THEN 'one' ELSE 'other' END AS c "
                   "FROM sales WHERE qty = 10")
    assert out["c"] == ["one"]


def test_in_between_like(store):
    assert q(store, "SELECT count(*) AS c FROM sales WHERE item IN (1, 3)")["c"] == [4]
    assert q(store, "SELECT count(*) AS c FROM sales WHERE qty BETWEEN 20 AND 50")["c"] == [4]
    assert q(store, "SELECT count(*) AS c FROM items WHERE name LIKE '%an%'")["c"] == [1]


def test_arithmetic_and_functions(store):
    out = q(store, """SELECT abs(-3) AS a, round(2.567, 2) AS r,
                             floor(2.7) AS f, ceil(2.1) AS c,
                             power(2, 10) AS p""")
    assert out["a"] == [3]
    assert abs(out["r"][0] - 2.57) < 1e-9
    assert out["f"] == [2]
    assert out["c"] == [3]
    assert out["p"] == [1024.0]


def test_division_by_zero_null(store):
    out = q(store, "SELECT 1 / 0 AS d, 5 % 0 AS m")
    assert out["d"] == [None]
    assert out["m"] == [None]


def test_values_clause(spark):
    out = q(spark, "SELECT col1 + col2 AS s FROM (VALUES (1, 2), (3, 4))")
    assert out["s"] == [3, 7]


def test_select_without_from(spark):
    out = q(spark, "SELECT 1 + 1 AS two, 'x' AS s")
    assert out["two"] == [2]
    assert out["s"] == ["x"]


def test_order_by_ordinal_and_group_by_ordinal(store):
    out = q(store, "SELECT item, sum(qty) FROM sales GROUP BY 1 ORDER BY 1")
    assert out["item"] == [1, 2, 3, 4]


def test_date_literal(spark):
    out = q(spark, "SELECT year(DATE '2021-03-15') AS y, "
                   "month(DATE '2021-03-15') AS m")
    assert out["y"] == [2021]
    assert out["m"] == [3]


def test_cast_syntax(spark):
    out = q(spark, "SELECT CAST('42' AS INT) AS i, CAST(3.9 AS INT) AS t, "
                   "CAST('2020-01-02' AS DATE) AS d")
    assert out["i"] == [42]
    assert out["t"] == [3]
    assert str(out["d"][0]) == "2020-01-02"


def test_parse_error(spark):
    with pytest.raises(ParseException):
        spark.sql("SELEC 1")


def test_unresolved_column_error(store):
    with pytest.raises(AnalysisException):
        store.sql("SELECT nope FROM sales").toArrow()


def test_missing_aggregation_error(store):
    with pytest.raises(AnalysisException):
        store.sql("SELECT item, qty FROM sales GROUP BY item").toArrow()


def test_string_comparison_lt(store):
    out = q(store, "SELECT name FROM items WHERE name < 'b' ORDER BY name")
    assert out["name"] == ["apple"]


def test_concat_pipe(store):
    out = q(store, "SELECT 'x' || name AS n FROM items ORDER BY n")
    assert out["n"] == ["xapple", "xbanana", "xcherry"]


def test_nested_subquery_aliasing(store):
    out = q(store, """
      SELECT a.name, a.total FROM (
        SELECT i.name AS name, SUM(s.qty) AS total
        FROM sales s JOIN items i ON s.item = i.id GROUP BY i.name
      ) a WHERE a.total >= 70 ORDER BY a.total""")
    assert out["name"] == ["banana", "apple"]
    assert out["total"] == [70, 110]


def test_non_equi_inner_join(store):
    out = q(store, """SELECT count(*) AS c FROM items a JOIN items b
                      ON a.id < b.id""")
    assert out["c"] == [3]  # (1,2),(1,3),(2,3)


def test_mixed_equi_and_residual_join(store):
    out = q(store, """SELECT s.item, s.qty FROM sales s JOIN items i
                      ON s.item = i.id AND s.qty > 25
                      ORDER BY s.item, s.qty""")
    assert out["qty"] == [40, 60, 50, 30]


def test_empty_relation_propagation(store):
    # WHERE false collapses to an empty relation; joins/unions fold away
    out = q(store, """SELECT s.item FROM sales s
                      JOIN (SELECT id FROM items WHERE false) t
                      ON s.item = t.id""")
    assert out["item"] == []
    out2 = q(store, "SELECT item FROM sales WHERE false "
                    "UNION ALL SELECT id FROM items ORDER BY item")
    assert out2["item"] == [1, 2, 3]


def test_nested_union_flattening(store):
    out = q(store, """SELECT 1 AS v UNION ALL SELECT 2
                      UNION ALL SELECT 3 UNION ALL SELECT 4""")
    assert sorted(out["v"]) == [1, 2, 3, 4]


def test_non_equi_left_outer_join(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({"x": [1, 5, 9]})) \
        .createOrReplaceTempView("neq_a")
    spark.createDataFrame(pa.table({"y": [3, 6]})) \
        .createOrReplaceTempView("neq_b")
    out = spark.sql("""
        SELECT x, y FROM neq_a LEFT JOIN neq_b ON x < y
        ORDER BY x, y""").toArrow().to_pydict()
    assert list(zip(out["x"], out["y"])) == \
        [(1, 3), (1, 6), (5, 6), (9, None)]


def test_left_outer_join_with_residual(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "k": [1, 1, 2], "v": [10, 20, 30]})) \
        .createOrReplaceTempView("res_a")
    spark.createDataFrame(pa.table({
        "k": [1, 2], "w": [15, 25]})) \
        .createOrReplaceTempView("res_b")
    out = spark.sql("""
        SELECT v, w FROM res_a LEFT JOIN res_b
        ON res_a.k = res_b.k AND v < w
        ORDER BY v""").toArrow().to_pydict()
    # v=10 matches (k=1, w=15); v=20 has no qualifying row; v=30 neither
    assert list(zip(out["v"], out["w"])) == \
        [(10, 15), (20, None), (30, None)]


def test_join_reorder_star_schema(spark):
    import numpy as np
    import pyarrow as pa

    n = 1000
    spark.createDataFrame(pa.table({
        "fk1": np.arange(n) % 10, "fk2": np.arange(n) % 5,
        "v": np.ones(n)})).createOrReplaceTempView("ro_fact")
    spark.createDataFrame(pa.table({
        "k1": np.arange(10), "n1": [f"a{i}" for i in range(10)]})) \
        .createOrReplaceTempView("ro_d1")
    spark.createDataFrame(pa.table({
        "k2": np.arange(5), "n2": [f"b{i}" for i in range(5)]})) \
        .createOrReplaceTempView("ro_d2")
    df = spark.sql("""SELECT n1, n2, sum(v) AS sv FROM ro_fact, ro_d1, ro_d2
                      WHERE fk1 = k1 AND fk2 = k2 GROUP BY n1, n2""")
    out = df.toArrow().to_pydict()
    assert len(out["sv"]) == 10  # 10 (k1 mod) × joint with k2 mod 5 pairs
    assert sum(out["sv"]) == n
    # the smallest relation (ro_d2, 5 rows) must seed the join chain
    txt = df.query_execution.optimized.tree_string()
    join_lines = [l for l in txt.splitlines() if "Join" in l
                  or "LocalRelation" in l]
    assert any("Join" in l for l in join_lines)


def test_join_runtime_filter_correctness(spark):
    import numpy as np
    import pyarrow as pa

    spark.conf.set("spark.tpu.join.runtimeFilter", True)
    spark.conf.set("spark.tpu.join.runtimeFilter.minCapacity", 1)
    try:
        rng = np.random.default_rng(3)
        n = 3000
        spark.createDataFrame(pa.table({
            "k": rng.integers(0, 3_000_000, n), "v": np.ones(n)})) \
            .createOrReplaceTempView("rf_f")
        # sparse keys over a wide span: forces the sort-probe path so the
        # range filter actually runs (dense spans use direct addressing)
        spark.createDataFrame(pa.table({
            "k2": 1000 + 99991 * np.arange(30), "w": np.arange(30.0)})) \
            .createOrReplaceTempView("rf_d")
        q = "SELECT count(*) AS c, sum(w) AS s FROM rf_f JOIN rf_d ON k = k2"
        on = spark.sql(q).collect()
        spark.conf.set("spark.tpu.join.runtimeFilter", False)
        off = spark.sql(q).collect()
        assert tuple(on[0].values()) == tuple(off[0].values())
        # semi join path
        spark.conf.set("spark.tpu.join.runtimeFilter", True)
        q2 = ("SELECT count(*) AS c FROM rf_f "
              "WHERE k IN (SELECT k2 FROM rf_d)")
        on2 = spark.sql(q2).collect()
        spark.conf.set("spark.tpu.join.runtimeFilter", False)
        off2 = spark.sql(q2).collect()
        assert tuple(on2[0].values()) == tuple(off2[0].values())
    finally:
        spark.conf.set("spark.tpu.join.runtimeFilter", False)
        spark.conf.set("spark.tpu.join.runtimeFilter.minCapacity", 1 << 20)


def test_ctas_with_materialized_cte(spark):
    """CREATE TABLE/VIEW AS with a multiply-instantiated expensive CTE:
    the command path must resolve WithCTE materializations exactly like
    session.sql does (r4 regression — placeholder relations leaked)."""
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "k": list(range(20)), "v": [1.0] * 20})) \
        .createOrReplaceTempView("ctas_src")
    spark.sql("""
        CREATE OR REPLACE TEMP VIEW ctas_out AS
        WITH big AS (SELECT a.k, sum(a.v) s FROM ctas_src a
                     JOIN ctas_src b ON a.k = b.k
                     JOIN ctas_src c ON a.k = c.k GROUP BY a.k)
        SELECT count(*) AS c FROM big x JOIN big y ON x.k = y.k""")
    assert spark.sql("SELECT * FROM ctas_out").toArrow() \
        .column("c")[0].as_py() == 20


def test_session_variables(spark):
    """DECLARE/SET/DROP VARIABLE with column-wins resolution
    (reference: SQL session variables, CreateVariable/ResolveSetVariable)."""
    import pyarrow as pa

    spark.sql("DECLARE VARIABLE sv_threshold INT DEFAULT 25")
    spark.createDataFrame(pa.table({"age": [20, 30, 40]})) \
        .createOrReplaceTempView("sv_people")
    q = "SELECT count(*) c FROM sv_people WHERE age > sv_threshold"
    assert spark.sql(q).toArrow().column("c")[0].as_py() == 2
    spark.sql("SET VARIABLE sv_threshold = 35")
    assert spark.sql(q).toArrow().column("c")[0].as_py() == 1
    # subquery assignment
    spark.sql("SET VAR sv_threshold = (SELECT max(age) FROM sv_people)")
    assert spark.sql("SELECT sv_threshold AS t").toArrow() \
        .column("t")[0].as_py() == 40
    # a real column with the variable's name wins over the variable
    spark.createDataFrame(pa.table({"sv_threshold": [7]})) \
        .createOrReplaceTempView("sv_shadow")
    assert spark.sql("SELECT sv_threshold AS t FROM sv_shadow").toArrow() \
        .column("t")[0].as_py() == 7
    spark.sql("DROP TEMPORARY VARIABLE sv_threshold")
    import pytest as _pytest

    with _pytest.raises(Exception, match="sv_threshold"):
        spark.sql("SELECT sv_threshold AS t").toArrow()
