"""Window function tests (reference: sql/core window suites /
DataFrameWindowFunctionsSuite)."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.api.window import Window


@pytest.fixture()
def sales(spark):
    df = spark.createDataFrame(pa.table({
        "dept": ["a", "a", "a", "b", "b", "c"],
        "emp": ["e1", "e2", "e3", "e4", "e5", "e6"],
        "sal": [100, 200, 200, 50, 75, 10],
    }))
    df.createOrReplaceTempView("emp_sales")
    return df


def _d(df):
    return df.toArrow().to_pydict()


def test_row_number_rank_dense(sales):
    w = Window.partitionBy("dept").orderBy(F.col("sal").desc())
    out = _d(sales.select(
        "dept", "emp", "sal",
        F.row_number().over(w).alias("rn"),
        F.rank().over(w).alias("rk"),
        F.dense_rank().over(w).alias("dr"),
    ).orderBy("dept", "sal", "emp"))
    # dept a sorted desc by sal: e2(200), e3(200), e1(100)
    rows = {(d, e): (rn, rk, dr) for d, e, rn, rk, dr in
            zip(out["dept"], out["emp"], out["rn"], out["rk"], out["dr"])}
    assert rows[("a", "e1")] == (3, 3, 2)
    assert rows[("a", "e2")][1:] == (1, 1)   # rank/dense of a 200 row
    assert rows[("a", "e3")][1:] == (1, 1)
    assert sorted([rows[("a", "e2")][0], rows[("a", "e3")][0]]) == [1, 2]
    assert rows[("b", "e5")] == (1, 1, 1)
    assert rows[("b", "e4")] == (2, 2, 2)
    assert rows[("c", "e6")] == (1, 1, 1)


def test_running_sum(sales):
    w = Window.partitionBy("dept").orderBy("sal")
    out = _d(sales.select(
        "dept", "sal", F.sum("sal").over(w).alias("rs"),
    ).orderBy("dept", "sal"))
    assert out["rs"][:3] == [100, 500, 500]  # peers (200,200) share total
    assert out["rs"][3:5] == [50, 125]
    assert out["rs"][5] == [10][0]


def test_partition_total(sales):
    w = Window.partitionBy("dept")
    out = _d(sales.select("dept",
                          F.sum("sal").over(w).alias("total"))
             .distinct().orderBy("dept"))
    assert out["total"] == [500, 125, 10]


def test_lag_lead(sales):
    w = Window.partitionBy("dept").orderBy("sal")
    out = _d(sales.select(
        "dept", "sal",
        F.lag("sal").over(w).alias("prev"),
        F.lead("sal").over(w).alias("next"),
    ).orderBy("dept", "sal", "emp"))
    assert out["prev"][:3] == [None, 100, 200]
    assert out["next"][2] is None or out["next"][1] is not None


def test_window_sql(sales, spark):
    out = _d(spark.sql("""
        SELECT dept, emp, sal,
               row_number() OVER (PARTITION BY dept ORDER BY sal DESC) AS rn,
               sum(sal) OVER (PARTITION BY dept) AS total
        FROM emp_sales ORDER BY dept, rn"""))
    assert out["rn"][:3] == [1, 2, 3]
    assert out["total"][:3] == [500, 500, 500]
    assert out["total"][3:5] == [125, 125]


def test_ntile_percent_rank(spark):
    df = spark.createDataFrame(pa.table({"v": list(range(1, 9))}))
    w = Window.orderBy("v")
    out = _d(df.select("v",
                       F.ntile(4).over(w).alias("q"),
                       F.percent_rank().over(w).alias("pr"))
             .orderBy("v"))
    assert out["q"] == [1, 1, 2, 2, 3, 3, 4, 4]
    assert out["pr"][0] == 0.0
    assert abs(out["pr"][-1] - 1.0) < 1e-12


def test_window_after_join_shuffle(spark):
    a = spark.createDataFrame(pa.table({
        "k": [1, 1, 2, 2, 3], "v": [10, 20, 30, 40, 50]}))
    w = Window.partitionBy("k").orderBy("v")
    out = _d(a.repartition(4).select(
        "k", "v", F.row_number().over(w).alias("rn")).orderBy("k", "v"))
    assert out["rn"] == [1, 2, 1, 2, 1]


def test_rows_frame_moving_average(spark):
    import pyarrow as pa
    from spark_tpu.api.window import Window

    df = spark.createDataFrame(pa.table({
        "g": ["a"] * 5, "t": [1, 2, 3, 4, 5],
        "v": [10.0, 20.0, 30.0, 40.0, 50.0]}))
    w = Window.partitionBy("g").orderBy("t").rowsBetween(-1, 1)
    out = _d(df.select("t", F.sum("v").over(w).alias("ms"),
                       F.avg("v").over(w).alias("ma")).orderBy("t"))
    assert out["ms"] == [30.0, 60.0, 90.0, 120.0, 90.0]
    assert out["ma"] == [15.0, 20.0, 30.0, 40.0, 45.0]


def test_rows_frame_sql(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "t": [1, 2, 3, 4], "v": [1, 2, 3, 4]})) \
        .createOrReplaceTempView("wf")
    out = spark.sql("""
        SELECT t, sum(v) OVER (ORDER BY t
            ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS s
        FROM wf ORDER BY t""").toArrow().to_pydict()
    assert out["s"] == [1, 3, 6, 9]


def test_rows_frame_unbounded_following(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({"t": [1, 2, 3], "v": [5, 6, 7]})) \
        .createOrReplaceTempView("wf2")
    out = spark.sql("""
        SELECT t, sum(v) OVER (ORDER BY t
            ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS s
        FROM wf2 ORDER BY t""").toArrow().to_pydict()
    assert out["s"] == [18, 13, 7]


def test_window_over_aggregate_single_query(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "store": [1, 1, 1, 2, 2],
        "item": [10, 11, 12, 10, 11],
        "rev": [5.0, 9.0, 7.0, 4.0, 8.0]})) \
        .createOrReplaceTempView("woa")
    out = spark.sql("""
        SELECT * FROM (
          SELECT store, item, SUM(rev) AS r,
                 rank() OVER (PARTITION BY store ORDER BY SUM(rev) DESC) AS rnk
          FROM woa GROUP BY store, item) t
        WHERE rnk <= 2 ORDER BY store, rnk""").toArrow().to_pydict()
    assert out["store"] == [1, 1, 2, 2]
    assert out["item"] == [11, 12, 11, 10]
    assert out["rnk"] == [1, 2, 1, 2]


def test_value_range_frame(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "g": ["a"] * 5, "t": [1, 2, 4, 7, 8], "v": [10, 20, 30, 40, 50]})) \
        .createOrReplaceTempView("vr")
    out = spark.sql("""
        SELECT t, sum(v) OVER (PARTITION BY g ORDER BY t
            RANGE BETWEEN 2 PRECEDING AND CURRENT ROW) AS s
        FROM vr ORDER BY t""").toArrow().to_pydict()
    # t=1:[1] → 10; t=2:[1,2] → 30; t=4:[2,4] → 50; t=7:[7] → 40; t=8:[7,8]
    assert out["s"] == [10, 30, 50, 40, 90]


def test_value_range_frame_api(spark):
    import pyarrow as pa
    from spark_tpu.api.window import Window

    df = spark.createDataFrame(pa.table({
        "t": [0, 5, 10, 30], "v": [1.0, 2.0, 4.0, 8.0]}))
    w = Window.orderBy("t").rangeBetween(-10, 10)
    out = df.select("t", F.avg("v").over(w).alias("a")) \
        .orderBy("t").toArrow().to_pydict()
    # t=0: window [−10,10] → {0,5,10} avg 7/3; t=30: only itself
    assert abs(out["a"][0] - 7 / 3) < 1e-9
    assert out["a"][3] == 8.0


def test_rows_frame_min_max(spark):
    import numpy as np
    import pandas as pd
    import pyarrow as pa
    from spark_tpu.api.window import Window

    rng = np.random.default_rng(7)
    n = 200
    pdf = pd.DataFrame({
        "g": rng.integers(0, 5, n),
        "t": np.arange(n),
        "v": rng.integers(-50, 50, n).astype("int64"),
    })
    df = spark.createDataFrame(pa.table(pdf))
    w = Window.partitionBy("g").orderBy("t").rowsBetween(-3, 2)
    out = _d(df.select("g", "t",
                       F.min("v").over(w).alias("lo"),
                       F.max("v").over(w).alias("hi")).orderBy("g", "t"))
    ordered = pdf.sort_values(["g", "t"])
    exp_lo, exp_hi = [], []  # brute-force oracle
    for _, grp in ordered.groupby("g"):
        vs = grp["v"].tolist()
        for i in range(len(vs)):
            win = vs[max(0, i - 3): i + 3]
            exp_lo.append(min(win))
            exp_hi.append(max(win))
    assert out["lo"] == exp_lo
    assert out["hi"] == exp_hi


def test_range_value_frame_min(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "t": [1, 2, 5, 6, 10], "v": [9, 3, 7, 1, 5]})) \
        .createOrReplaceTempView("wrv")
    out = spark.sql("""
        SELECT t, min(v) OVER (ORDER BY t
            RANGE BETWEEN 2 PRECEDING AND CURRENT ROW) AS m
        FROM wrv ORDER BY t""").toArrow().to_pydict()
    # windows by VALUE of t: t=1→{9}; t=2→{9,3}; t=5→{7}; t=6→{7,1}; t=10→{5}
    assert out["m"] == [9, 3, 7, 1, 5]
