"""Whole-query compilation (physical/whole_query.py) + compile-tier model.

Acceptance gates:
  * whole / stage / operator tiers produce IDENTICAL results on the
    differential suite (agg, join+agg, repartition+agg, sorted q3);
  * the whole tier executes as ONE jitted dispatch per step (warm run:
    {"whole_query": 1}) with zero host shuffle round-trips;
  * plan_lint's launch model predicts EXACTLY for all three tiers, with
    the tier decision and fallback reason surfaced in explain("analysis");
  * the tier chooser launches nothing and falls back tier-by-tier (HBM
    budget exceeded / unsupported operators -> stage);
  * obs contract: attributed launch totals == global counters under the
    whole-query program, zero extra launches from the chooser.

Satellites covered here: dictionary-domain UDF evaluation (once per
distinct value, mapped over codes), RunInfo propagation through
pass-through pipeline outputs (ragg on filter->agg chains), and the mesh
quota-retry restaging fix (retries reuse device-resident base planes).
"""

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def tiers(spark):
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    yield spark
    for k in ("spark.tpu.compile.tier", "spark.tpu.fusion.minRows",
              "spark.tpu.compile.whole.minRows", "spark.tpu.memory.budget",
              "spark.tpu.fusion.enabled"):
        spark.conf.unset(k)


@pytest.fixture()
def data(spark):
    rng = np.random.default_rng(11)
    n = 5000
    spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
        "f": rng.random(n),
        "s": [f"cat{i % 5}" for i in range(n)],
    })).createOrReplaceTempView("wq_t")
    dim = pa.table({
        "dk": np.arange(13, dtype=np.int64),
        "label": [f"lab{i % 3}" for i in range(13)],
    })
    spark.createDataFrame(dim).createOrReplaceTempView("wq_dim")
    return spark


Q_AGG = ("select k, sum(v * 2) sv, count(*) c, min(v) mn, max(v+1) mx, "
         "avg(f) af from wq_t where v > 0 group by k")
Q_JOIN_AGG = ("select label, sum(v) sv, count(*) c from wq_t "
              "join wq_dim on k = dk where v > 10 group by label")
Q3 = """
    SELECT dt.d_year, item.i_brand_id AS brand_id,
           SUM(ss_ext_sales_price) AS sum_agg
    FROM date_dim dt, store_sales, item
    WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
      AND store_sales.ss_item_sk = item.i_item_sk
      AND item.i_manufact_id = 28 AND dt.d_moy = 11
    GROUP BY dt.d_year, item.i_brand_id"""
Q3_SORTED = Q3 + "\n    ORDER BY d_year, brand_id"


def _rows(df, by):
    t = df.toArrow().to_pandas()
    return t.sort_values(by).reset_index(drop=True)


def _measured(build):
    build().toArrow()  # warm
    before = dict(KC.launches_by_kind)
    build().toArrow()
    return {k: v - before.get(k, 0) for k, v in KC.launches_by_kind.items()
            if v != before.get(k, 0)}


# ---------------------------------------------------------------------------
# differential suite: identical results across the three tiers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("query,by", [
    (Q_AGG, ["k"]),
    (Q_JOIN_AGG, ["label"]),
])
def test_tier_differential(tiers, data, query, by):
    import pandas as pd

    data.conf.set("spark.tpu.compile.tier", "stage")
    ref = _rows(data.sql(query), by)
    for tier in ("whole", "operator"):
        data.conf.set("spark.tpu.compile.tier", tier)
        out = _rows(data.sql(query), by)
        pd.testing.assert_frame_equal(ref, out, check_dtype=False)


def test_tier_differential_repartition_agg(tiers, data):
    import pandas as pd

    def q():
        return (data.sql("select * from wq_t").repartition(5, "k")
                .groupBy("k").count())

    data.conf.set("spark.tpu.compile.tier", "stage")
    ref = _rows(q(), ["k"])
    for tier in ("whole", "operator"):
        data.conf.set("spark.tpu.compile.tier", tier)
        pd.testing.assert_frame_equal(ref, _rows(q(), ["k"]),
                                      check_dtype=False)


def test_tier_differential_sorted_q3(tiers, spark):
    """Sorted q3: broadcast-join spine + group agg + range-exchange sort,
    ALL lowered into one program under the whole tier — results identical
    INCLUDING the total order (the in-program gather + global sort
    replaces range partitioning + per-partition sorts)."""
    import pandas as pd

    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.compile.tier", "stage")
    ref = spark.sql(Q3_SORTED).toArrow().to_pandas().reset_index(drop=True)
    for tier in ("whole", "operator"):
        spark.conf.set("spark.tpu.compile.tier", tier)
        out = spark.sql(Q3_SORTED).toArrow().to_pandas() \
            .reset_index(drop=True)
        pd.testing.assert_frame_equal(ref, out, check_dtype=False)


# ---------------------------------------------------------------------------
# one dispatch per step + exact predictions for every tier
# ---------------------------------------------------------------------------

def test_whole_tier_single_dispatch_per_step(tiers, spark):
    """Acceptance: TPC-DS mini q3 under the whole tier is ONE jitted
    dispatch per step — no host shuffle round-trip, no per-stage kernels
    of any kind on the warm run."""
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.compile.tier", "whole")
    measured = _measured(lambda: spark.sql(Q3))
    assert measured == {"whole_query": 1}, measured


@pytest.mark.parametrize("tier", ["whole", "stage", "operator"])
def test_prediction_exact_all_tiers(tiers, data, tier):
    data.conf.set("spark.tpu.compile.tier", tier)
    for q in (Q_AGG, Q_JOIN_AGG):
        df = data.sql(q)
        report = df.query_execution.analysis_report()
        assert report.exact, report.inexact_reasons
        measured = _measured(lambda: data.sql(q))
        assert report.predicted_launches == measured, (
            tier, report.predicted_launches, measured)
        assert (report.tier or {}).get("tier") == tier, report.tier


@pytest.mark.parametrize("tier", ["whole", "stage", "operator"])
def test_q3_prediction_exact_all_tiers(tiers, spark, tier):
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.compile.tier", tier)
    df = spark.sql(Q3)
    report = df.query_execution.analysis_report()
    assert report.exact, report.inexact_reasons
    measured = _measured(lambda: spark.sql(Q3))
    assert report.predicted_launches == measured, (
        tier, report.predicted_launches, measured)


def test_whole_tier_join_retry_predicted(tiers, spark):
    """q7's fact-probe joins overflow the initial output buckets: the
    program re-dispatches with bumped capacities and the analyzer's
    round-by-round mirror (truncated upstream traces included) predicts
    the retry dispatches EXACTLY."""
    from tpcds_mini import register_tpcds

    register_tpcds(spark)
    spark.conf.set("spark.tpu.compile.tier", "whole")
    q7 = """SELECT i.i_category, AVG(ss_quantity) AS agg1, COUNT(*) AS cnt
        FROM store_sales ss JOIN item i ON ss.ss_item_sk = i.i_item_sk
        JOIN date_dim d ON ss.ss_sold_date_sk = d.d_date_sk
        WHERE d.d_year = 1999 GROUP BY i.i_category"""
    report = spark.sql(q7).query_execution.analysis_report()
    assert report.exact, report.inexact_reasons
    assert report.predicted_launches.get("whole_query", 0) >= 2, \
        report.predicted_launches
    measured = _measured(lambda: spark.sql(q7))
    assert report.predicted_launches == measured


# ---------------------------------------------------------------------------
# tier chooser: fallbacks + obs contract
# ---------------------------------------------------------------------------

def test_tier_fallback_hbm_budget(tiers, data):
    """Forced whole tier still respects the memory admission: a budget the
    fully-resident working set exceeds (but the per-stage peak fits)
    falls back to the stage tier with the reason surfaced in
    explain('analysis'), and the query still runs there."""
    from spark_tpu.physical.whole_query import _estimate_resident_bytes

    data.conf.set("spark.tpu.compile.tier", "stage")
    qe = data.sql(Q_AGG).query_execution
    stage_peak = qe.analysis_report().predicted_peak_hbm
    whole_est = _estimate_resident_bytes(qe.physical, data.conf)
    assert stage_peak and whole_est and stage_peak < whole_est, (
        stage_peak, whole_est)
    budget = (stage_peak + whole_est) // 2
    data.conf.set("spark.tpu.compile.tier", "whole")
    data.conf.set("spark.tpu.memory.budget", str(budget))
    df = data.sql(Q_AGG)
    phys = df.query_execution.physical
    assert type(phys).__name__ != "WholeQueryExec"
    report = df.query_execution.analysis_report()
    assert (report.tier or {}).get("tier") == "stage", report.tier
    assert "memory.budget" in (report.tier or {}).get("reason", ""), \
        report.tier
    # still runs correctly on the fallback tier
    assert df.toArrow().num_rows > 0


def test_tier_fallback_unsupported_operator(tiers, data):
    """A plan with an operator outside the whole-query lowering set
    (SampleExec: per-batch position-dependent) falls back to stage with
    the structural reason recorded."""
    data.conf.set("spark.tpu.compile.tier", "whole")
    df = data.sql("select * from wq_t").sample(0.5, seed=3)
    phys = df.query_execution.physical
    assert type(phys).__name__ != "WholeQueryExec"
    report = df.query_execution.analysis_report()
    assert (report.tier or {}).get("tier") == "stage", report.tier
    assert "whole-query fallback" in (report.tier or {}).get("reason", "")


def test_fusion_off_never_whole(tiers, data):
    """spark.tpu.fusion.enabled=false is the operator-at-a-time
    differential oracle: the tier chooser must never collapse the plan
    into a whole-query program there (even forced), or fusion-on/off
    differentials would compare whole vs whole."""
    data.conf.set("spark.tpu.fusion.enabled", "false")
    for tier in ("auto", "whole"):
        data.conf.set("spark.tpu.compile.tier", tier)
        data.conf.set("spark.tpu.compile.whole.minRows", "0")
        df = (data.sql("select * from wq_t").repartition(5, "k")
              .groupBy("k").count())
        assert type(df.query_execution.physical).__name__ != \
            "WholeQueryExec", tier
        report = df.query_execution.analysis_report()
        assert "fusion.enabled" in (report.tier or {}).get("reason", ""), \
            report.tier


def test_auto_tier_volume_floor(tiers, data):
    """auto keeps small queries on the stage tier (the compile-
    amortization floor, the whole-query generalization of minRows) and
    flips to whole when the floor admits a plan WITH exchange
    round-trips to eliminate; exchange-free plans always stay staged
    (stage fusion is already one dispatch per batch there)."""
    data.conf.set("spark.tpu.compile.tier", "auto")

    def q():
        return (data.sql("select * from wq_t").repartition(5, "k")
                .groupBy("k").count())

    df = q()
    assert type(df.query_execution.physical).__name__ != "WholeQueryExec"
    report = df.query_execution.analysis_report()
    assert "floor" in (report.tier or {}).get("reason", ""), report.tier
    data.conf.set("spark.tpu.compile.whole.minRows", "0")
    df = q()
    assert type(df.query_execution.physical).__name__ == "WholeQueryExec"
    report = df.query_execution.analysis_report()
    assert (report.tier or {}).get("tier") == "whole"
    # exchange-free plan: auto declines whole even with the floor at 0
    df = data.sql(Q_AGG)
    assert type(df.query_execution.physical).__name__ != "WholeQueryExec"
    report = df.query_execution.analysis_report()
    assert "no exchange round-trips" in (report.tier or {}).get(
        "reason", ""), report.tier


def test_tier_chooser_launches_nothing(tiers, data):
    """The cost model is pure host metadata: planning + analysis under
    any tier dispatches zero kernels and performs no device sync."""
    for tier in ("auto", "whole", "stage", "operator"):
        data.conf.set("spark.tpu.compile.tier", tier)
        before = KC.launches
        df = data.sql(Q_AGG)
        df.query_execution.physical       # plan (tier decision included)
        df.query_execution.analysis_report()
        assert KC.launches == before, tier


def test_whole_tier_attribution_matches_global(tiers, data):
    """obs contract: the whole program's single dispatch attributes to
    WholeQueryExec (re-attributed to members via fused_members), and the
    attributed total equals the global launch counter delta."""
    data.conf.set("spark.tpu.compile.tier", "whole")
    data.sql(Q_AGG).toArrow()  # warm
    before = KC.launches
    df = data.sql(Q_AGG)
    df.toArrow()
    global_delta = KC.launches - before
    graph = df.query_execution.plan_graph()
    attributed = sum(v for nd in graph
                     for v in (nd.get("launches") or {}).values())
    assert attributed == global_delta
    assert global_delta == 1
    fused = [nd for nd in graph if nd.get("fused")]
    assert fused and any("HashAggregate" in m or "Aggregate" in m
                         for nd in fused for m in nd["fused"]), graph


def test_whole_tier_explain_surfaces_decision(tiers, data, capsys):
    data.conf.set("spark.tpu.compile.tier", "whole")
    data.sql(Q_AGG).explain("analysis")
    out = capsys.readouterr().out
    assert "compilation tier: whole" in out
    assert "WHOLE-QUERY program" in out
    assert "whole_query" in out


def test_operator_tier_boundary_explained(tiers, data):
    data.conf.set("spark.tpu.compile.tier", "operator")
    report = data.sql(Q_AGG).query_execution.analysis_report()
    assert any("OPERATOR" in b for b in report.fusion_boundaries), \
        report.fusion_boundaries


def test_whole_tier_memory_model_bounds_measured(tiers, data):
    """The whole-query memory model (fully-resident sum) upper-bounds the
    measured per-query ledger watermark."""
    data.conf.set("spark.tpu.compile.tier", "whole")
    from spark_tpu.obs.resources import GLOBAL_LEDGER

    df = data.sql(Q_AGG)
    report = df.query_execution.analysis_report()
    assert report.predicted_peak_hbm and report.predicted_peak_hbm > 0
    df.toArrow()
    qrec = GLOBAL_LEDGER.query_record(
        getattr(df.query_execution._last_ctx, "query_id", None))
    if qrec and qrec.get("peak_bytes"):
        assert report.predicted_peak_hbm >= qrec["peak_bytes"] // 4, (
            report.predicted_peak_hbm, qrec)


# ---------------------------------------------------------------------------
# satellite: dictionary-domain UDF evaluation
# ---------------------------------------------------------------------------

def test_udf_dict_domain_filter(tiers, data):
    """A non-host-evaluable predicate (a Python UDF) over a dictionary-
    encoded string column evaluates once per DISTINCT value and maps over
    codes: |dict| calls, not |rows|; encoding off restores the per-row
    oracle with identical results."""
    from spark_tpu.api import functions as F

    calls = [0]

    def is_even_cat(v):
        calls[0] += 1
        return v is not None and int(v[3:]) % 2 == 0

    from spark_tpu.types import boolean

    pred = F.udf(is_even_cat, boolean)
    df = data.table("wq_t")
    q = df.filter(pred(F.col("s"))).select("k", "v", "s")
    base = data._metrics.snapshot()["counters"].get(
        "udf.dict_domain_evals", 0)
    out = q.toArrow().to_pandas().sort_values(["k", "v"]) \
        .reset_index(drop=True)
    n_calls_encoded = calls[0]
    assert data._metrics.snapshot()["counters"].get(
        "udf.dict_domain_evals", 0) > base
    # 5 distinct values per batch, a handful of batches — nowhere near
    # the ~5000 per-row calls
    assert n_calls_encoded <= 5 * 4, n_calls_encoded

    calls[0] = 0
    data.conf.set("spark.tpu.encoding.enabled", "false")
    try:
        df2 = data.table("wq_t")
        ref = df2.filter(pred(F.col("s"))).select("k", "v", "s").toArrow() \
            .to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
        assert calls[0] >= len(ref)  # per-row oracle
    finally:
        data.conf.unset("spark.tpu.encoding.enabled")
    import pandas as pd

    pd.testing.assert_frame_equal(ref, out, check_dtype=False)


def test_udf_dict_domain_skips_filtered_values(tiers, spark):
    """The lane evaluates the LIVE distinct codes only: a dictionary
    value that exists solely in rows an upstream filter dropped must
    never reach the UDF (a partial UDF guarded by that filter would
    crash on it under the full-dictionary domain)."""
    from spark_tpu.api import functions as F
    from spark_tpu.types import float64

    t = pa.table({"s": (["aa", "bbb", ""] * 200)})
    spark.createDataFrame(t).createOrReplaceTempView("wq_guard")
    inv_len = F.udf(lambda v: 1.0 / len(v), float64)
    df = spark.table("wq_guard").filter("length(s) > 0")
    out = df.select(inv_len(F.col("s")).alias("r")).toArrow().to_pandas()
    assert len(out) == 400
    assert sorted(set(round(x, 4) for x in out["r"])) == [
        round(1 / 3, 4), 0.5]


def test_udf_dict_domain_null_lane(tiers, spark):
    """Invalid rows take the dedicated null lane (the UDF sees None once),
    matching per-row semantics."""
    from spark_tpu.api import functions as F
    from spark_tpu.types import string

    t = pa.table({"s": pa.array(["a", None, "b", "a", None]),
                  "i": pa.array(np.arange(5, dtype=np.int64))})
    spark.createDataFrame(t).createOrReplaceTempView("wq_nulls")

    def tag(v):
        return "NULL" if v is None else v.upper() + "!"

    u = F.udf(tag, string)
    df = spark.table("wq_nulls")
    out = df.select(F.col("i"), u(F.col("s")).alias("t")).toArrow().to_pandas() \
        .sort_values("i")["t"].tolist()
    assert out == ["A!", "NULL", "B!", "A!", "NULL"]


def test_udf_plan_model_exact_with_dict_lane(tiers, data):
    """plan_lint models PythonEvalExec: one argument-pipeline dispatch per
    batch per UDF, layout/value model passing through — predictions stay
    EXACT, with the per-distinct lane noted."""
    from spark_tpu.api import functions as F
    from spark_tpu.types import boolean

    pred = F.udf(lambda v: v is not None and v.endswith("1"), boolean)

    def q():
        df = data.table("wq_t")
        return df.select(F.col("k"), F.col("s"),
                         pred(F.col("s")).alias("hit")) \
            .groupBy("k").count()

    report = q().query_execution.analysis_report()
    assert report.exact, report.inexact_reasons
    assert any("dictionary-domain lane" in n
               for s in report.stages for n in s["notes"]), \
        [n for s in report.stages for n in s["notes"]]
    measured = _measured(q)
    assert report.predicted_launches == measured, (
        report.predicted_launches, measured)
    # a FILTER on the UDF output is value-opaque: the model must degrade
    # honestly, never claim exactness over an untraced span
    flt = (data.table("wq_t")
           .select(F.col("k"), pred(F.col("s")).alias("hit"))
           .filter("hit").groupBy("k").count())
    rep2 = flt.query_execution.analysis_report()
    assert not rep2.exact and rep2.inexact_reasons


# ---------------------------------------------------------------------------
# satellite: RunInfo through pass-through pipeline outputs
# ---------------------------------------------------------------------------

def test_ragg_fires_through_filter_pipeline(tiers, spark):
    """A sorted sparse key aggregated through a filter/project chain takes
    the sorted-run (ragg) kernel — pass-through outputs inherit ingest
    RunInfo — and the analyzer predicts it exactly (gated stage tier:
    default minRows routes to the shared kernels where ragg lives)."""
    spark.conf.unset("spark.tpu.fusion.minRows")  # default gate ON
    n = 3000
    k = np.sort(np.random.default_rng(5).integers(0, 10 ** 9, n))
    v = np.arange(n, dtype=np.int64)
    spark.createDataFrame(pa.table({"k": k, "v": v})) \
        .createOrReplaceTempView("wq_sorted")
    q = ("select k, sum(v) sv, count(*) c from wq_sorted "
         "where v > 100 group by k")
    report = spark.sql(q).query_execution.analysis_report()
    assert report.exact, report.inexact_reasons
    assert report.predicted_launches.get("ragg", 0) >= 1, \
        report.predicted_launches
    measured = _measured(lambda: spark.sql(q))
    assert report.predicted_launches == measured
    # the decoded oracle agrees on values
    import pandas as pd

    got = spark.sql(q).toArrow().to_pandas().sort_values("k") \
        .reset_index(drop=True)
    spark.conf.set("spark.tpu.encoding.enabled", "false")
    try:
        ref = spark.sql(q).toArrow().to_pandas().sort_values("k") \
            .reset_index(drop=True)
    finally:
        spark.conf.unset("spark.tpu.encoding.enabled")
    pd.testing.assert_frame_equal(ref, got, check_dtype=False)


# ---------------------------------------------------------------------------
# satellite: mesh quota-retry restaging
# ---------------------------------------------------------------------------

def test_mesh_quota_retry_reuses_staged_planes(tiers, spark, monkeypatch):
    """A skewed mesh exchange overflows its quota: the retry reuses the
    device-resident base planes (one base staging at first overflow,
    ZERO further host->device restages), the ledger stays balanced, and
    the launch prediction stays exact — retries included."""
    import spark_tpu.parallel.mesh_exchange as ME

    n = 6000
    spark.createDataFrame(pa.table({
        "k": np.full(n, 5, np.int64),
        "v": np.arange(n, dtype=np.int64),
    })).createOrReplaceTempView("wq_skew")

    pad_calls = [0]
    base_calls = [0]
    orig_pad = ME._pad_shards
    orig_base = ME._pad_base

    def count_pad(*a, **k):
        pad_calls[0] += 1
        return orig_pad(*a, **k)

    def count_base(*a, **k):
        base_calls[0] += 1
        return orig_base(*a, **k)

    monkeypatch.setattr(ME, "_pad_shards", count_pad)
    monkeypatch.setattr(ME, "_pad_base", count_base)

    def q():
        return spark.sql("select k, v from wq_skew").repartition(4, "k")

    report = q().query_execution.analysis_report()
    attempts = report.predicted_launches.get("mesh_stage", 0)
    assert attempts >= 2, report.predicted_launches  # quota retried
    out = q().toArrow()
    assert out.num_rows == n
    # host-side padding ran for attempt 1 only; every retry embedded the
    # persisted base planes in-program
    first_attempt_pads = pad_calls[0]
    assert base_calls[0] >= 1, "base planes never staged"
    pad_calls[0] = 0
    base_calls[0] = 0
    measured = _measured(q)
    assert report.predicted_launches == measured, (
        report.predicted_launches, measured)
    # warm runs still pad only the first attempt (two runs in _measured)
    assert pad_calls[0] <= first_attempt_pads * 2
    from spark_tpu.obs.resources import GLOBAL_LEDGER

    assert GLOBAL_LEDGER.verify() == [], \
        "device ledger unbalanced after retry"
