"""AQE partition-coalescing tests (reference: CoalesceShufflePartitionsSuite)."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.physical.adaptive import plan_merge_groups


def test_plan_merge_groups():
    assert plan_merge_groups([1, 1, 1, 10, 1], 3) == [[0, 1, 2], [3], [4]]
    assert plan_merge_groups([5, 5], 3) == [[0], [1]]
    assert plan_merge_groups([0, 0, 0], 3) == [[0, 1, 2]]


def test_coalesced_agg_correct(spark):
    # tiny shuffle partitions → coalesced into one, results unchanged
    spark.conf.set("spark.sql.adaptive.advisoryPartitionSizeInBytes",
                   1 << 30)
    try:
        df = spark.range(0, 1000, 1, 8)
        out = (df.groupBy((F.col("id") % 5).alias("m"))
               .agg(F.count("*").alias("c")).orderBy("m")
               .toArrow().to_pydict())
        assert out["c"] == [200] * 5
        snap = spark._metrics.snapshot()
        assert snap["counters"].get("aqe.partitions_coalesced", 0) > 0
    finally:
        spark.conf.unset("spark.sql.adaptive.advisoryPartitionSizeInBytes")


def test_coalesced_join_correct(spark):
    spark.conf.set("spark.sql.adaptive.advisoryPartitionSizeInBytes",
                   1 << 30)
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)  # force shuffle
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(50)), "v": list(range(50))}))
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 100, 2)), "w": list(range(50))}))
        out = a.join(b, on="k").agg(F.count("*").alias("c")) \
            .toArrow().to_pydict()
        assert out["c"] == [25]
    finally:
        spark.conf.unset("spark.sql.adaptive.advisoryPartitionSizeInBytes")
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")
