"""AQE partition-coalescing tests (reference: CoalesceShufflePartitionsSuite)."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.physical.adaptive import plan_merge_groups


def test_plan_merge_groups():
    assert plan_merge_groups([1, 1, 1, 10, 1], 3) == [[0, 1, 2], [3], [4]]
    assert plan_merge_groups([5, 5], 3) == [[0], [1]]
    assert plan_merge_groups([0, 0, 0], 3) == [[0, 1, 2]]


def test_coalesced_agg_correct(spark):
    # tiny shuffle partitions → coalesced into one, results unchanged
    spark.conf.set("spark.sql.adaptive.advisoryPartitionSizeInBytes",
                   1 << 30)
    try:
        df = spark.range(0, 1000, 1, 8)
        out = (df.groupBy((F.col("id") % 5).alias("m"))
               .agg(F.count("*").alias("c")).orderBy("m")
               .toArrow().to_pydict())
        assert out["c"] == [200] * 5
        snap = spark._metrics.snapshot()
        assert snap["counters"].get("aqe.partitions_coalesced", 0) > 0
    finally:
        spark.conf.unset("spark.sql.adaptive.advisoryPartitionSizeInBytes")


def test_coalesced_join_correct(spark):
    spark.conf.set("spark.sql.adaptive.advisoryPartitionSizeInBytes",
                   1 << 30)
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)  # force shuffle
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(50)), "v": list(range(50))}))
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 100, 2)), "w": list(range(50))}))
        out = a.join(b, on="k").agg(F.count("*").alias("c")) \
            .toArrow().to_pydict()
        assert out["c"] == [25]
    finally:
        spark.conf.unset("spark.sql.adaptive.advisoryPartitionSizeInBytes")
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_full_outer_join_never_broadcast(spark):
    """A replicated build side is unsound for full_outer (unmatched build
    rows would re-emit per probe partition) — the planner must pick the
    shuffled path however small the right side is."""
    l = spark.createDataFrame(pa.table({
        "k": [1, 2, 3, 4, 5, 6, 7, 8], "a": [1] * 8})).repartition(4)
    r = spark.createDataFrame(pa.table({"k": [1, 9], "b": [100, 900]}))
    l.createOrReplaceTempView("fo_l")
    r.createOrReplaceTempView("fo_r")
    out = spark.sql(
        "SELECT b FROM fo_l FULL OUTER JOIN fo_r ON fo_l.k = fo_r.k "
        "ORDER BY b NULLS LAST").toArrow().to_pydict()
    assert out["b"] == [100, 900] + [None] * 7


def test_aqe_broadcast_demotion(spark):
    """Initial plan picks a shuffled join (stats over threshold); runtime
    size of the filtered build side demotes it to broadcast and elides the
    probe-side shuffle (role of AdaptiveSparkPlanExec re-optimization +
    local shuffle read)."""
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", 200)
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(1000)), "v": list(range(1000))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 2000, 2)),
            "w": list(range(1000))})).repartition(4)
        a.createOrReplaceTempView("aqe_a")
        b.createOrReplaceTempView("aqe_b")
        out = spark.sql(
            "SELECT count(*) AS c FROM aqe_a JOIN "
            "(SELECT k, w FROM aqe_b WHERE w < 3) sb "
            "ON aqe_a.k = sb.k").toArrow().to_pydict()
        assert out["c"] == [3]
        snap = spark._metrics.snapshot()["counters"]
        assert snap.get("aqe.broadcast_demotions", 0) >= 1
        assert snap.get("aqe.probe_shuffles_elided", 0) >= 1
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_aqe_demotion_disabled_when_adaptive_off(spark):
    spark.conf.set("spark.sql.adaptive.enabled", "false")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", 200)
    before = spark._metrics.snapshot()["counters"].get(
        "aqe.broadcast_demotions", 0)
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(100)), "v": list(range(100))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 200, 2)), "w": list(range(100))}))
        out = a.join(b.filter("w < 3"), on="k") \
            .agg(F.count("*").alias("c")).toArrow().to_pydict()
        assert out["c"] == [3]
        snap = spark._metrics.snapshot()["counters"]
        assert snap.get("aqe.broadcast_demotions", 0) == before
    finally:
        spark.conf.unset("spark.sql.adaptive.enabled")
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_aqe_demotion_preserves_partitioning_dependent_agg(spark):
    """Probe-shuffle elision must NOT fire when an operator above the join
    relies on the join's hash partitioning (per-key agg over the join
    keys) — role of the reference's ValidateRequirements after AQE
    re-optimization. Results must stay correct either way."""
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", 200)
    try:
        a = spark.createDataFrame(pa.table({
            "k": [1, 2, 3, 4] * 250, "v": list(range(1000))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 2000, 2)),
            "w": list(range(1000))})).repartition(4)
        a.createOrReplaceTempView("aqe_pk_a")
        b.createOrReplaceTempView("aqe_pk_b")
        out = spark.sql(
            "SELECT aqe_pk_a.k, count(*) c FROM aqe_pk_a JOIN "
            "(SELECT k FROM aqe_pk_b WHERE w < 3) sb "
            "ON aqe_pk_a.k = sb.k GROUP BY aqe_pk_a.k "
            "ORDER BY aqe_pk_a.k").toArrow().to_pydict()
        assert out["k"] == [2, 4] and out["c"] == [250, 250]
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


# ---------------------------------------------------------------------------
# Runtime-adaptive execution: runtime join filters, stage-boundary
# re-admission, parquet-stats whole-tier admission, skew re-partitioning
# (reference: dynamic partition pruning / runtime filters in
# sqlx/dynamicpruning + AQEShuffleReadExec skew handling, recast for the
# eager-exchange TPU pipeline: the build side's key domain is harvested
# HOST-SIDE from already-synced state and pushed into the not-yet-run
# probe shuffle). Differentials run fresh sessions per leg so metric
# counters isolate the adaptive layer's effect.
# ---------------------------------------------------------------------------

import os
import tempfile

import numpy as np

from spark_tpu import TpuSession


def _session(name, extra=None):
    conf = {"spark.sql.shuffle.partitions": 4,
            "spark.sql.autoBroadcastJoinThreshold": -1}
    conf.update(extra or {})
    return TpuSession(name, conf)


def _counters(s, *prefixes):
    snap = s._metrics.snapshot()["counters"]
    return {k: v for k, v in snap.items()
            if any(k.startswith(p) for p in prefixes)}


def _rf_join_leg(name, adaptive, build_query):
    s = _session(f"{name}-{adaptive}",
                 {"spark.tpu.adaptive.runtimeFilter":
                  "true" if adaptive else "false"})
    try:
        out = build_query(s)
        return out, _counters(s, "adaptive.", "shuffle.bytes_shipped",
                              "kernel.launches")
    finally:
        s.stop()


def test_runtime_filter_join_differential():
    """A selective build side ([5,6,7] vs a 2000-key probe) installs a
    range filter on the probe shuffle: identical results, measurably
    fewer shuffled bytes, rows pruned before the exchange."""
    def q(s):
        a = s.createDataFrame(pa.table({
            "k": list(range(2000)), "v": list(range(2000))})).repartition(4)
        b = s.createDataFrame(pa.table({
            "k": [5, 6, 7], "w": [50, 60, 70]})).repartition(2)
        return a.join(b, on="k").orderBy("k").toArrow().to_pydict()

    off, m_off = _rf_join_leg("rf-join", False, q)
    on, m_on = _rf_join_leg("rf-join", True, q)
    assert off == on
    assert on["k"] == [5, 6, 7]
    assert m_on.get("adaptive.runtime_filters_installed", 0) >= 1
    assert m_on.get("adaptive.filter_rows_pruned", 0) >= 1000
    # host shuffles ship fewer bytes; the mesh path prunes before
    # staging instead (bytes_shipped counts host transfers only)
    assert m_on["shuffle.bytes_shipped"] <= m_off["shuffle.bytes_shipped"]
    assert "adaptive.runtime_filters_installed" not in m_off


def test_runtime_filter_join_agg_differential():
    def q(s):
        a = s.createDataFrame(pa.table({
            "k": [i % 40 for i in range(4000)],
            "v": list(range(4000))})).repartition(4)
        b = s.createDataFrame(pa.table({
            "k": [3, 4, 5], "w": [30, 40, 50]})).repartition(2)
        return (a.join(b, on="k").groupBy("k")
                .agg(F.count("*").alias("c"), F.sum("v").alias("sv"))
                .orderBy("k").toArrow().to_pydict())

    off, m_off = _rf_join_leg("rf-agg", False, q)
    on, m_on = _rf_join_leg("rf-agg", True, q)
    assert off == on
    assert on["c"] == [100, 100, 100]
    assert m_on.get("adaptive.runtime_filters_installed", 0) >= 1
    assert m_on.get("adaptive.filter_rows_pruned", 0) > 0


def test_runtime_filter_string_keys_differential():
    """Dict-encoded string keys: the build side's StringDict values form
    the filter domain; probe rows prune through a code-level lookup table
    (no string comparisons on device)."""
    def q(s):
        a = s.createDataFrame(pa.table({
            "k": [f"u{i % 50:03d}" for i in range(2000)],
            "v": list(range(2000))})).repartition(4)
        b = s.createDataFrame(pa.table({
            "k": ["u005", "u006"], "w": [1, 2]})).repartition(2)
        return a.join(b, on="k").orderBy("v").toArrow().to_pydict()

    off, m_off = _rf_join_leg("rf-str", False, q)
    on, m_on = _rf_join_leg("rf-str", True, q)
    assert off == on
    assert len(on["v"]) == 80
    assert m_on.get("adaptive.runtime_filters_installed", 0) >= 1
    assert m_on.get("adaptive.filter_rows_pruned", 0) == 1920
    assert m_on["shuffle.bytes_shipped"] <= m_off["shuffle.bytes_shipped"]


def test_runtime_filter_tpcds_q3_differential():
    """TPC-DS mini q3 with broadcast disabled: the dimension filters
    (i_manufact_id=28, d_moy=11) make both build sides selective —
    results identical with the filter layer installed."""
    from test_whole_query import Q3_SORTED
    from tpcds_mini import gen_tpcds

    tabs = gen_tpcds()
    outs = {}
    for adaptive in (False, True):
        s = _session(f"rf-q3-{adaptive}",
                     {"spark.tpu.adaptive.runtimeFilter":
                      "true" if adaptive else "false"})
        try:
            # register pre-partitioned views so the joins actually
            # shuffle (single-partition local tables co-locate and the
            # plan collapses to one stage with nothing to filter)
            for name, t in tabs.items():
                (s.createDataFrame(t).repartition(4)
                 .createOrReplaceTempView(name))
            outs[adaptive] = s.sql(Q3_SORTED).toArrow().to_pydict()
            if adaptive:
                m = _counters(s, "adaptive.")
                assert m.get("adaptive.runtime_filters_installed", 0) >= 1
        finally:
            s.stop()
    assert outs[False] == outs[True]
    assert len(outs[True]["sum_agg"]) > 0


def test_runtime_filter_cluster_differential():
    """2-worker cluster leg: adaptive on/off must agree when map stages
    ship to workers (the filter layer must never corrupt a cluster
    shuffle, whether or not it engages on this path)."""
    from spark_tpu.exec.cluster import LocalCluster

    rng = np.random.default_rng(20)
    t = pa.table({"k": rng.integers(0, 500, 4000),
                  "v": rng.integers(-20, 80, 4000)})
    dim = pa.table({"k": [7, 8, 9], "w": [70, 80, 90]})
    outs = {}
    for adaptive in (False, True):
        s = _session(f"rf-cluster-{adaptive}",
                     {"spark.tpu.adaptive.runtimeFilter":
                      "true" if adaptive else "false"})
        cluster = LocalCluster(num_workers=2)
        s.attachSqlCluster(cluster)
        try:
            a = s.createDataFrame(t).repartition(4)
            b = s.createDataFrame(dim).repartition(2)
            df = (a.join(b, on="k").groupBy("k")
                  .agg(F.count("*").alias("c"), F.sum("v").alias("sv"))
                  .orderBy("k"))
            outs[adaptive] = df.toArrow().to_pydict()
        finally:
            s.stop()
    assert outs[False] == outs[True]


def test_runtime_filter_zero_launch_identity():
    """Obs contract: arming the adaptive layer on a FILTER-FREE plan
    (no shuffled hash join → nothing to harvest) must not add a single
    kernel launch — the harvest reads only already-synced host state."""
    def q(s):
        df = s.createDataFrame(pa.table({
            "k": [i % 7 for i in range(3000)],
            "v": list(range(3000))})).repartition(4)
        return (df.groupBy("k").agg(F.sum("v").alias("sv"))
                .orderBy("k").toArrow().to_pydict())

    off, m_off = _rf_join_leg("rf-zero", False, q)
    on, m_on = _rf_join_leg("rf-zero", True, q)
    assert off == on
    assert m_on["kernel.launches"] == m_off["kernel.launches"]
    assert "adaptive.runtime_filters_installed" not in m_on
    assert "adaptive.filter_rows_pruned" not in m_on


# -- stage-boundary re-admission --------------------------------------------

def _csv_fixture(tmp_path):
    csv = str(tmp_path / "re_t.csv")
    with open(csv, "w") as f:
        f.write("k,v\n")
        for i in range(500):
            f.write(f"{i % 10},{i}\n")
    return csv


def _readmission_leg(name, csv, extra):
    conf = {"spark.tpu.compile.whole.minRows": 1}
    conf.update(extra)
    s = _session(name, conf)
    try:
        a = (s.read.option("header", "true").option("inferSchema", "true")
             .csv(csv).repartition(4))
        b = s.createDataFrame(pa.table({
            "k": [5, 6, 7], "w": [50, 60, 70]})).repartition(2)
        df = (a.join(b, on="k").groupBy("k")
              .agg(F.count("*").alias("c")).orderBy("k"))
        out = df.toArrow().to_pydict()
        ctx = getattr(df.query_execution, "_last_ctx", None)
        dec = getattr(ctx, "readmission_decision", None)
        spans = [d for d in s.tracer.since(0)
                 if d.get("name") == "adaptive.readmission"]
        return out, _counters(s, "adaptive."), dec, spans
    finally:
        s.stop()


def test_readmission_tier_flip(tmp_path):
    """An external scan (rows unknown at plan time) keeps the initial
    plan on the stage tier; once the scan stage materializes, the
    measured sizes re-admit the remainder to the whole tier — asserted
    via the TierDecision the re-planner recorded AND its trace span."""
    csv = _csv_fixture(tmp_path)
    off = _readmission_leg("readmit-off", csv,
                           {"spark.tpu.adaptive.readmission": "false"})
    on = _readmission_leg("readmit-on", csv,
                          {"spark.tpu.adaptive.readmission": "true"})
    assert off[0] == on[0]
    assert on[0]["c"] == [50, 50, 50]
    assert "adaptive.readmissions" not in off[1]
    assert on[1].get("adaptive.readmissions", 0) >= 1
    dec = on[2]
    assert dec is not None and dec.tier == "whole"
    assert dec.details.get("readmitted") is True
    assert on[3], "adaptive.readmission span missing from the trace"
    assert on[3][0]["args"]["tier"] == "whole"


def test_readmission_history_replan(tmp_path):
    """Recurring queries skip the mid-query flip: the warm-start manifest
    records the first run's observed sizes, and the SECOND run re-plans
    to the whole tier from history before the first batch executes."""
    csv = _csv_fixture(tmp_path)
    conf = {"spark.tpu.adaptive.readmission": "true",
            "spark.tpu.cache.dir": str(tmp_path / "cache"),
            "spark.tpu.cache.result.enabled": "false"}
    out1, m1, _, _ = _readmission_leg("readmit-h1", csv, conf)
    out2, m2, _, _ = _readmission_leg("readmit-h2", csv, conf)
    assert out1 == out2
    assert m1.get("adaptive.readmissions", 0) >= 1
    assert m2.get("adaptive.history_replans", 0) >= 1


# -- parquet footer-statistics admission ------------------------------------

def test_parquet_stats_whole_tier_admission(tmp_path):
    """Footer row-group counts admit an external parquet scan to the
    whole tier AT PLAN TIME (no stage ever executes host-side); with the
    stats feed disabled the same plan stays stage-at-a-time."""
    import pyarrow.parquet as pq

    pqf = str(tmp_path / "adm_t.parquet")
    pq.write_table(pa.table({"k": [i % 10 for i in range(500)],
                             "v": list(range(500))}), pqf)
    outs, metrics = {}, {}
    for stats_on in (False, True):
        s = _session(f"pq-adm-{stats_on}", {
            "spark.tpu.compile.whole.minRows": 1,
            "spark.tpu.adaptive.parquetStats":
                "true" if stats_on else "false"})
        try:
            a = s.read.parquet(pqf).repartition(4)
            b = s.createDataFrame(pa.table({
                "k": [5, 6, 7], "w": [50, 60, 70]})).repartition(2)
            df = (a.join(b, on="k").groupBy("k")
                  .agg(F.count("*").alias("c")).orderBy("k"))
            outs[stats_on] = df.toArrow().to_pydict()
            metrics[stats_on] = _counters(s, "whole_query.")
        finally:
            s.stop()
    assert outs[False] == outs[True]
    assert outs[True]["c"] == [50, 50, 50]
    assert metrics[True].get("whole_query.dispatches", 0) >= 1
    assert metrics[False].get("whole_query.dispatches", 0) == 0


# -- mesh skew re-partitioning ----------------------------------------------

def test_skew_split_replans_on_mesh(monkeypatch):
    """When quota-ladder retries exhaust on a hot key, the adaptive layer
    splits the batch set and re-plans each half ON the mesh instead of
    abandoning the whole exchange to the host-shuffle fallback."""
    import jax

    import spark_tpu.parallel.mesh_exchange as ME

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    # first quota overflow exhausts the ladder → fallback decision point
    monkeypatch.setattr(ME, "_MAX_QUOTA_RETRIES", 1)
    rng = np.random.default_rng(11)
    t = pa.table({"k": np.zeros(4000, dtype=np.int64),
                  "v": rng.integers(0, 1000, 4000)})
    outs, metrics = {}, {}
    for skew_on in (False, True):
        s = TpuSession(f"skew-{skew_on}", {
            "spark.sql.shuffle.partitions": 8,
            "spark.tpu.batch.capacity": 1 << 10,
            "spark.tpu.mesh.enabled": "true",
            "spark.tpu.adaptive.skewRepartition":
                "true" if skew_on else "false"})
        try:
            df = s.createDataFrame(t).repartition(8)
            outs[skew_on] = sorted(
                tuple(r) for r in df.repartition(8, "k").collect())
            metrics[skew_on] = _counters(s, "adaptive.", "exchange.")
        finally:
            s.stop()
    assert outs[False] == outs[True]
    assert metrics[False].get("exchange.mesh_fallback", 0) >= 1
    assert metrics[True].get("adaptive.skew_repartitions", 0) >= 1
    assert metrics[True].get("exchange.mesh_fallback", 0) == 0


# -- plan_lint honesty ------------------------------------------------------

def test_plan_lint_runtime_filter_degrades_honestly(spark):
    """With the filter layer armed, a shuffled single-key join's launch
    prediction is runtime-dependent — the report degrades to exact=False
    with the adaptive reason named (never silently wrong)."""
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)
    spark.conf.set("spark.tpu.adaptive.runtimeFilter", "true")
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(100)), "v": list(range(100))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": [1, 2], "w": [10, 20]})).repartition(2)
        report = a.join(b, on="k").query_execution.analysis_report()
        assert not report.exact
        assert any("adaptive runtime join filter" in r
                   for r in report.inexact_reasons), report.inexact_reasons
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")
        spark.conf.unset("spark.tpu.adaptive.runtimeFilter")


def test_plan_lint_broadcast_join_stays_exact_with_adaptive(spark):
    """Exactness case: a broadcast join never takes a runtime filter
    (the build side is already local), so arming the layer must NOT
    degrade its analysis."""
    spark.conf.set("spark.tpu.adaptive.runtimeFilter", "true")
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(100)), "v": list(range(100))})).repartition(4)
        b = spark.createDataFrame(pa.table({"k": [1, 2], "w": [10, 20]}))
        report = a.join(b, on="k").query_execution.analysis_report()
        assert report.exact, report.inexact_reasons
    finally:
        spark.conf.unset("spark.tpu.adaptive.runtimeFilter")


def test_plan_lint_readmission_named(spark):
    """Re-admission honesty: any staged plan may collapse mid-query with
    the re-admission layer armed — the analyzer names that, and an
    exchange-free plan stays exact (nothing to re-admit)."""
    spark.conf.set("spark.tpu.adaptive.readmission", "true")
    try:
        df = spark.createDataFrame(pa.table({
            "k": [i % 5 for i in range(100)],
            "v": list(range(100))})).repartition(4).groupBy("k").count()
        report = df.query_execution.analysis_report()
        assert not report.exact
        assert any("adaptive re-admission" in r
                   for r in report.inexact_reasons), report.inexact_reasons
        flat = spark.createDataFrame(pa.table({
            "k": [1, 2, 3]})).select((F.col("k") + 1).alias("k1"))
        flat_report = flat.query_execution.analysis_report()
        assert flat_report.exact, flat_report.inexact_reasons
    finally:
        spark.conf.unset("spark.tpu.adaptive.readmission")
