"""AQE partition-coalescing tests (reference: CoalesceShufflePartitionsSuite)."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.physical.adaptive import plan_merge_groups


def test_plan_merge_groups():
    assert plan_merge_groups([1, 1, 1, 10, 1], 3) == [[0, 1, 2], [3], [4]]
    assert plan_merge_groups([5, 5], 3) == [[0], [1]]
    assert plan_merge_groups([0, 0, 0], 3) == [[0, 1, 2]]


def test_coalesced_agg_correct(spark):
    # tiny shuffle partitions → coalesced into one, results unchanged
    spark.conf.set("spark.sql.adaptive.advisoryPartitionSizeInBytes",
                   1 << 30)
    try:
        df = spark.range(0, 1000, 1, 8)
        out = (df.groupBy((F.col("id") % 5).alias("m"))
               .agg(F.count("*").alias("c")).orderBy("m")
               .toArrow().to_pydict())
        assert out["c"] == [200] * 5
        snap = spark._metrics.snapshot()
        assert snap["counters"].get("aqe.partitions_coalesced", 0) > 0
    finally:
        spark.conf.unset("spark.sql.adaptive.advisoryPartitionSizeInBytes")


def test_coalesced_join_correct(spark):
    spark.conf.set("spark.sql.adaptive.advisoryPartitionSizeInBytes",
                   1 << 30)
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)  # force shuffle
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(50)), "v": list(range(50))}))
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 100, 2)), "w": list(range(50))}))
        out = a.join(b, on="k").agg(F.count("*").alias("c")) \
            .toArrow().to_pydict()
        assert out["c"] == [25]
    finally:
        spark.conf.unset("spark.sql.adaptive.advisoryPartitionSizeInBytes")
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_full_outer_join_never_broadcast(spark):
    """A replicated build side is unsound for full_outer (unmatched build
    rows would re-emit per probe partition) — the planner must pick the
    shuffled path however small the right side is."""
    l = spark.createDataFrame(pa.table({
        "k": [1, 2, 3, 4, 5, 6, 7, 8], "a": [1] * 8})).repartition(4)
    r = spark.createDataFrame(pa.table({"k": [1, 9], "b": [100, 900]}))
    l.createOrReplaceTempView("fo_l")
    r.createOrReplaceTempView("fo_r")
    out = spark.sql(
        "SELECT b FROM fo_l FULL OUTER JOIN fo_r ON fo_l.k = fo_r.k "
        "ORDER BY b NULLS LAST").toArrow().to_pydict()
    assert out["b"] == [100, 900] + [None] * 7


def test_aqe_broadcast_demotion(spark):
    """Initial plan picks a shuffled join (stats over threshold); runtime
    size of the filtered build side demotes it to broadcast and elides the
    probe-side shuffle (role of AdaptiveSparkPlanExec re-optimization +
    local shuffle read)."""
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", 200)
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(1000)), "v": list(range(1000))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 2000, 2)),
            "w": list(range(1000))})).repartition(4)
        a.createOrReplaceTempView("aqe_a")
        b.createOrReplaceTempView("aqe_b")
        out = spark.sql(
            "SELECT count(*) AS c FROM aqe_a JOIN "
            "(SELECT k, w FROM aqe_b WHERE w < 3) sb "
            "ON aqe_a.k = sb.k").toArrow().to_pydict()
        assert out["c"] == [3]
        snap = spark._metrics.snapshot()["counters"]
        assert snap.get("aqe.broadcast_demotions", 0) >= 1
        assert snap.get("aqe.probe_shuffles_elided", 0) >= 1
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_aqe_demotion_disabled_when_adaptive_off(spark):
    spark.conf.set("spark.sql.adaptive.enabled", "false")
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", 200)
    before = spark._metrics.snapshot()["counters"].get(
        "aqe.broadcast_demotions", 0)
    try:
        a = spark.createDataFrame(pa.table({
            "k": list(range(100)), "v": list(range(100))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 200, 2)), "w": list(range(100))}))
        out = a.join(b.filter("w < 3"), on="k") \
            .agg(F.count("*").alias("c")).toArrow().to_pydict()
        assert out["c"] == [3]
        snap = spark._metrics.snapshot()["counters"]
        assert snap.get("aqe.broadcast_demotions", 0) == before
    finally:
        spark.conf.unset("spark.sql.adaptive.enabled")
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")


def test_aqe_demotion_preserves_partitioning_dependent_agg(spark):
    """Probe-shuffle elision must NOT fire when an operator above the join
    relies on the join's hash partitioning (per-key agg over the join
    keys) — role of the reference's ValidateRequirements after AQE
    re-optimization. Results must stay correct either way."""
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", 200)
    try:
        a = spark.createDataFrame(pa.table({
            "k": [1, 2, 3, 4] * 250, "v": list(range(1000))})).repartition(4)
        b = spark.createDataFrame(pa.table({
            "k": list(range(0, 2000, 2)),
            "w": list(range(1000))})).repartition(4)
        a.createOrReplaceTempView("aqe_pk_a")
        b.createOrReplaceTempView("aqe_pk_b")
        out = spark.sql(
            "SELECT aqe_pk_a.k, count(*) c FROM aqe_pk_a JOIN "
            "(SELECT k FROM aqe_pk_b WHERE w < 3) sb "
            "ON aqe_pk_a.k = sb.k GROUP BY aqe_pk_a.k "
            "ORDER BY aqe_pk_a.k").toArrow().to_pydict()
        assert out["k"] == [2, 4] and out["c"] == [250, 250]
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")
