"""Service metrics plane (spark_tpu/obs/export.py + serve wiring).

Contract under test: fixed log-bucket histograms merge EXACTLY (a
two-process merge reproduces the single-registry quantile buckets),
the registry's typed instruments follow get-or-create/label-separation
semantics with lazily-evaluated gauges, the Prometheus text exposition
round-trips through its own parser, the plane is structurally
zero-overhead (identical kernel-launch deltas with export on and off,
fusion on or off), SLO burn accounting raises obs.slo findings that
reach pool status and the live store, and a 2-worker cluster's
heartbeat-shipped executor payloads render as executor-labeled series
in the driver scrape that reconcile with the stored payloads.
"""

import time

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.config import SQLConf
from spark_tpu.obs import export as mx
from spark_tpu.obs.export import BUCKET_BOUNDS, Histogram, MetricsRegistry
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
from spark_tpu.serve import FairScheduler, QueryService


@pytest.fixture(autouse=True)
def _restore_export():
    """Every test leaves the process-global plane OFF with a clean
    registry — the module-bool discipline other suites rely on."""
    yield
    mx.stop_ticker()
    mx.configure(SQLConf({}))          # export off, defaults restored
    mx.REGISTRY.reset()


def _session(name, extra=None):
    from spark_tpu import TpuSession

    conf = {"spark.sql.shuffle.partitions": 2,
            "spark.tpu.batch.capacity": 1 << 11,
            "spark.tpu.fusion.minRows": "0",
            "spark.tpu.cache.result.enabled": "false"}
    conf.update(extra or {})
    return TpuSession(name, conf)


def _seed(s, view="mx_t", n=4000, seed=23):
    rng = np.random.default_rng(seed)
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 12, n).astype(np.int64),
        "v": rng.integers(-30, 100, n).astype(np.int64),
    })).createOrReplaceTempView(view)


# ---------------------------------------------------------------------------
# histograms: buckets, quantile bounds, exact merge
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_observe_counts_and_stats(self):
        h = Histogram()
        for v in (0.01, 0.5, 5.0, 50.0, 500.0):
            h.observe(v)
        assert h.count == 5
        assert h.sum == pytest.approx(555.51)
        assert h.min == 0.01 and h.max == 500.0
        assert sum(h.counts) == 5

    def test_quantile_bounds_contain_true_quantile(self):
        rng = np.random.default_rng(3)
        vals = rng.lognormal(mean=1.0, sigma=1.5, size=2000)
        h = Histogram()
        for v in vals:
            h.observe(float(v))
        for q in (0.5, 0.95, 0.99):
            lo, hi = h.quantile_bounds(q)
            true_q = float(np.quantile(vals, q))
            assert lo <= true_q <= hi, (q, lo, true_q, hi)

    def test_overflow_bucket_answers_with_observed_max(self):
        h = Histogram()
        h.observe(1e9)                    # far past the last bound
        assert h.counts[-1] == 1
        assert h.quantile(0.99) == 1e9

    def test_merge_is_exact_two_process_reproduction(self):
        """The acceptance identity: two 'processes' each observe half
        the samples; merging their histograms reproduces the single
        histogram's buckets — and therefore its quantiles — EXACTLY."""
        rng = np.random.default_rng(7)
        vals = rng.lognormal(mean=0.5, sigma=2.0, size=1001).tolist()
        single = Histogram()
        a, b = Histogram(), Histogram()
        for i, v in enumerate(vals):
            single.observe(v)
            (a if i % 2 else b).observe(v)
        # simulate the cross-process leg: b's SNAPSHOT (what a heartbeat
        # or scrape ships) folds into a
        merged = Histogram.from_snapshot(a.snapshot()) \
            .merge_snapshot(b.snapshot())
        assert merged.counts == single.counts
        assert merged.count == single.count
        assert merged.sum == pytest.approx(single.sum)
        for q in (0.5, 0.9, 0.99):
            assert merged.quantile_bounds(q) == single.quantile_bounds(q)

    def test_merge_quantiles_bound_pooled_samples(self):
        rng = np.random.default_rng(11)
        va = rng.exponential(5.0, 500)
        vb = rng.exponential(50.0, 500)
        a, b = Histogram(), Histogram()
        for v in va:
            a.observe(float(v))
        for v in vb:
            b.observe(float(v))
        a.merge(b)
        pooled = np.concatenate([va, vb])
        for q in (0.5, 0.95):
            lo, hi = a.quantile_bounds(q)
            assert lo <= float(np.quantile(pooled, q)) <= hi

    def test_merge_rejects_foreign_bucket_layout(self):
        with pytest.raises(ValueError):
            Histogram().merge_snapshot({"counts": [0] * 10, "count": 0,
                                        "sum": 0.0})

    def test_empty_quantile_is_none(self):
        assert Histogram().quantile(0.5) is None
        assert Histogram().percentile_ms(0.99) is None

    def test_bounds_are_shared_process_constants(self):
        assert len(BUCKET_BOUNDS) == 44
        assert BUCKET_BOUNDS[0] == pytest.approx(0.05)
        ratios = [BUCKET_BOUNDS[i + 1] / BUCKET_BOUNDS[i]
                  for i in range(len(BUCKET_BOUNDS) - 1)]
        assert all(r == pytest.approx(2.0 ** 0.5) for r in ratios)


# ---------------------------------------------------------------------------
# registry: typed instruments, labels, lazy gauges, sources
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_get_or_create_and_label_separation(self):
        reg = MetricsRegistry()
        c1 = reg.counter("q.count", pool="dash")
        c2 = reg.counter("q.count", pool="dash")
        c3 = reg.counter("q.count", pool="batch")
        assert c1 is c2 and c1 is not c3
        c1.inc()
        c1.inc(4)
        assert c1.value == 5 and c3.value == 0

    def test_gauge_is_lazy_and_rebinds(self):
        reg = MetricsRegistry()
        calls = {"n": 0}

        def probe():
            calls["n"] += 1
            return 7.0

        reg.gauge("hbm.now", probe)
        assert calls["n"] == 0              # never eagerly evaluated
        samples = reg.collect()
        assert calls["n"] == 1
        assert ("gauge", "hbm.now", (), 7.0) in samples
        reg.gauge("hbm.now", lambda: 9.0)   # newest provider wins
        assert ("gauge", "hbm.now", (), 9.0) in reg.collect()

    def test_failing_gauge_and_source_are_skipped(self):
        reg = MetricsRegistry()
        reg.gauge("bad", lambda: 1 / 0)
        reg.add_source("boom", lambda: 1 / 0)
        reg.counter("ok").inc()
        samples = reg.collect()
        assert ("counter", "ok", (), 1) in samples
        assert not any(name == "bad" for _k, name, _l, _v in samples)

    def test_histogram_instrument_and_reset(self):
        reg = MetricsRegistry()
        reg.histogram("lat", pool="a").observe(3.0)
        kinds = [k for k, *_ in reg.collect()]
        assert "histogram" in kinds
        reg.reset()
        assert reg.collect() == []


# ---------------------------------------------------------------------------
# Prometheus text exposition round-trip
# ---------------------------------------------------------------------------

class TestPrometheus:
    def test_render_parse_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("kernel.launches").inc(42)
        reg.gauge("hbm.bytes", lambda: 1024.0)
        h = reg.histogram("serve.pool.e2e_ms", pool="dash")
        for v in (0.2, 3.0, 700.0):
            h.observe(v)
        text = reg.render_prometheus()
        out = mx.parse_prometheus(text)
        assert out["types"]["spark_tpu_kernel_launches"] == "counter"
        assert out["types"]["spark_tpu_hbm_bytes"] == "gauge"
        assert out["types"]["spark_tpu_serve_pool_e2e_ms"] == "histogram"
        assert out["samples"][("spark_tpu_kernel_launches", ())] == 42
        assert out["samples"][("spark_tpu_hbm_bytes", ())] == 1024.0
        assert out["samples"][
            ("spark_tpu_serve_pool_e2e_ms_count",
             (("pool", "dash"),))] == 3
        assert out["samples"][
            ("spark_tpu_serve_pool_e2e_ms_sum",
             (("pool", "dash"),))] == pytest.approx(703.2)
        # bucket series are CUMULATIVE and end at the +Inf total
        buckets = {lbls: v for (n, lbls), v in out["samples"].items()
                   if n == "spark_tpu_serve_pool_e2e_ms_bucket"}
        inf = [v for lbls, v in buckets.items()
               if dict(lbls).get("le") == "+Inf"]
        assert inf == [3.0]
        assert all(v <= 3.0 for v in buckets.values())

    def test_label_escaping_round_trips(self):
        reg = MetricsRegistry()
        reg.counter("odd", session='a"b\\c').inc(2)
        out = mx.parse_prometheus(reg.render_prometheus())
        assert out["samples"][
            ("spark_tpu_odd", (("session", 'a"b\\c'),))] == 2

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            mx.parse_prometheus("this is { not exposition format")

    def test_histogram_merge_from_two_scrapes(self):
        """Quantiles computed from two scraped bucket vectors added
        element-wise equal the single-registry answer — the fleet
        aggregation path (ROADMAP direction 2)."""
        h1, h2, both = Histogram(), Histogram(), Histogram()
        rng = np.random.default_rng(5)
        for v in rng.exponential(10.0, 400):
            h1.observe(float(v))
            both.observe(float(v))
        for v in rng.exponential(100.0, 400):
            h2.observe(float(v))
            both.observe(float(v))
        merged = Histogram.from_snapshot(h1.snapshot()).merge(h2)
        assert merged.counts == both.counts


# ---------------------------------------------------------------------------
# configure / ticker / time series
# ---------------------------------------------------------------------------

class TestTickerAndRing:
    def test_configure_flips_module_bool(self):
        mx.configure(SQLConf({"spark.tpu.metrics.export": "true"}))
        assert mx.ENABLED
        mx.configure(SQLConf({}))
        assert not mx.ENABLED

    def test_off_never_starts_ticker(self):
        mx.configure(SQLConf({}))
        mx.start_ticker()
        assert mx._TICKER is None

    def test_tick_once_samples_into_ring(self):
        mx.configure(SQLConf({"spark.tpu.metrics.export": "true",
                              "spark.tpu.metrics.ringSize": "16"}))
        mx.REGISTRY.reset()
        c = mx.REGISTRY.counter("serve.requests")
        h = mx.REGISTRY.histogram("serve.pool.e2e_ms", pool="a")
        c.inc(3)
        h.observe(1.0)
        mx.tick_once(now=100.0)
        c.inc(2)
        h.observe(2.0)
        mx.tick_once(now=101.0)
        snap = mx.timeseries_snapshot()
        assert snap["series"]["serve.requests"] == [[100.0, 3],
                                                    [101.0, 5]]
        # histograms ride the ring as their scalar count
        assert snap["series"]["serve.pool.e2e_ms.count{pool=a}"] == \
            [[100.0, 1], [101.0, 2]]
        sparks = mx.sparklines(series_prefix="serve.")
        assert sparks["serve.requests"] == [3, 5]


# ---------------------------------------------------------------------------
# zero-overhead guard: launch deltas identical with export on/off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
def test_export_on_adds_zero_launches(fusion):
    s = _session(f"mx-overhead-{fusion}",
                 {"spark.tpu.fusion.enabled": fusion})
    try:
        _seed(s)
        q = "select k, sum(v) s from mx_t where v > 0 group by k"

        def warm_delta():
            s.sql(q).toArrow()
            before = dict(KC.launches_by_kind)
            s.sql(q).toArrow()
            return {k: v - before.get(k, 0)
                    for k, v in KC.launches_by_kind.items()
                    if v != before.get(k, 0)}

        off = warm_delta()
        assert off, "probe query launched nothing — vacuous comparison"
        s.conf.set("spark.tpu.metrics.export", "true")
        mx.configure(s.conf)
        mx.register_default_sources(session=s)
        mx.start_ticker()
        on = warm_delta()
        assert on == off, (
            f"metrics export changed kernel dispatches: {on} vs {off}")
        # and the scrape itself is device-free: same launch count after
        before = KC.launches
        mx.render_prometheus()
        assert KC.launches == before
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# SLO burn accounting
# ---------------------------------------------------------------------------

class TestSLO:
    def test_release_returns_burn_finding(self):
        conf = SQLConf({"spark.tpu.scheduler.pools": "a:1",
                        "spark.tpu.serve.pool.a.sloMs": "0.0001"})
        sched = FairScheduler(conf)
        t = sched.submit("a")
        sched.wait(t, timeout=1.0)
        sched.note_query(t, "q-slo-1")
        time.sleep(0.002)               # guarantee the breach
        finding = sched.release(t)
        assert finding is not None
        assert finding["kind"] == "obs.slo"
        assert finding["pool"] == "a"
        assert finding["query"] == "q-slo-1"
        assert finding["e2e_ms"] > finding["slo_ms"]
        assert finding["burn_rate"] == 1.0
        st = sched.status()["pools"]["a"]["slo"]
        assert st["breaches"] == 1 and st["ok"] == 0

    def test_within_slo_returns_none_and_counts_ok(self):
        conf = SQLConf({"spark.tpu.scheduler.pools": "a:1",
                        "spark.tpu.serve.pool.a.sloMs": "60000"})
        sched = FairScheduler(conf)
        t = sched.submit("a")
        sched.wait(t, timeout=1.0)
        assert sched.release(t) is None
        st = sched.status()["pools"]["a"]["slo"]
        assert st["ok"] == 1 and st["breaches"] == 0
        assert st["burn_rate"] == 0.0

    def test_no_slo_configured_no_accounting(self):
        sched = FairScheduler(SQLConf({}))
        t = sched.submit("default")
        sched.wait(t, timeout=1.0)
        assert sched.release(t) is None
        assert "slo" not in sched.status()["pools"]["default"]

    def test_slo_finding_reaches_live_store_end_to_end(self):
        s = _session("mx-slo", {
            "spark.tpu.scheduler.pools": "dash:1",
            "spark.tpu.serve.pool.dash.sloMs": "0.0001",
        })
        try:
            _seed(s)
            svc = QueryService(s)
            c = svc.open_session()
            c.conf.set("spark.tpu.scheduler.pool", "dash")
            svc.execute_sql(
                c, "select k, sum(v) s from mx_t group by k")
            st = svc.status()["pools"]["dash"]
            assert st["slo"]["breaches"] >= 1
            # the finding landed on the query's live record and the
            # pool status surfaces it through recent_findings
            finds = st.get("slo_findings") or []
            assert any(f.get("kind") == "obs.slo" for f in finds), st
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# serve wiring: pool histograms on the scrape, count identity
# ---------------------------------------------------------------------------

def test_pool_histograms_on_scrape_count_admitted_queries():
    s = _session("mx-serve", {
        "spark.tpu.scheduler.pools": "dash:2,batch:1",
        "spark.tpu.metrics.export": "true",
        "spark.tpu.metrics.tickInterval": "0.1",
    })
    try:
        _seed(s)
        svc = QueryService(s)
        c = svc.open_session()
        q = "select k, sum(v) s from mx_t group by k"
        for _ in range(3):
            svc.execute_sql(c, q)
        out = mx.parse_prometheus(mx.render_prometheus())
        e2e = sum(v for (n, _l), v in out["samples"].items()
                  if n == "spark_tpu_serve_pool_e2e_ms_count")
        assert int(e2e) == 3
        # drain freezes the ring into the status surface
        assert svc.drain(timeout=10.0)
        assert svc.drain_snapshot is not None
        status = svc.status()
        assert "drain_timeseries" in status
    finally:
        s.stop()


def test_executor_payload_shape():
    p = mx.executor_payload()
    assert "kernel.launches" in p and "kernel.compiles" in p
    assert all(isinstance(v, (int, float)) for v in p.values())
    assert any(k.startswith("net.retry.") for k in p)


# ---------------------------------------------------------------------------
# 2-worker cluster leg: executor-labeled series in the driver scrape
# ---------------------------------------------------------------------------

def test_cluster_executor_labeled_series_reconcile():
    s = _session("mx-cluster", {
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
        "spark.tpu.heartbeat.interval": "0.2",
        "spark.tpu.metrics.export": "true",
    })
    try:
        _seed(s, n=4000)
        # a bare group-by over the 1-partition in-memory view collapses
        # into the (driver-local) result stage — a join forces shuffle
        # exchanges, i.e. remote map stages on the workers
        s.createDataFrame(pa.table({
            "k": np.arange(12).astype(np.int64),
            "name": [f"n{i}" for i in range(12)],
        })).createOrReplaceTempView("mx_dim")
        svc = QueryService(s)
        c = svc.open_session()
        q = ("select d.name, sum(t.v) s from mx_t t "
             "join mx_dim d on t.k = d.k group by d.name")
        svc.execute_sql(c, q)
        # workers attach their registry payload to the NEXT heartbeat
        # after begin_stage_obs configured export — poll with a deadline
        deadline = time.monotonic() + 20.0
        with_metrics = {}
        while time.monotonic() < deadline:
            with s.live_obs._lock:
                with_metrics = {
                    eid: dict(e["metrics"])
                    for eid, e in s.live_obs.executors.items()
                    if e.get("metrics")}
            if len(with_metrics) >= 2:
                break
            svc.execute_sql(c, q)       # keep both workers busy
            time.sleep(0.25)
        assert len(with_metrics) >= 2, (
            f"executor metrics payloads never arrived: "
            f"{list(with_metrics)}")
        out = mx.parse_prometheus(mx.render_prometheus())
        for eid, payload in with_metrics.items():
            key = ("spark_tpu_executor_kernel_launches",
                   (("executor", eid),))
            assert key in out["samples"], (eid, "missing from scrape")
            # the scrape renders exactly the payload the heartbeat
            # shipped (cumulative totals — driver and worker agree)
            assert out["samples"][key] >= \
                float(payload["kernel.launches"]) - 1e-9
        total_worker = sum(float(p["kernel.launches"])
                           for p in with_metrics.values())
        assert total_worker > 0, "workers reported zero launches"
        assert svc.drain(timeout=10.0)
    finally:
        s.stop()
