"""df.na, pivot, and unpivot tests."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F


@pytest.fixture()
def nadf(spark):
    return spark.createDataFrame(pa.table({
        "a": pa.array([1, None, 3], pa.int64()),
        "b": pa.array([None, 2.5, 3.5], pa.float64()),
        "s": pa.array(["x", None, "z"]),
    }))


def test_na_drop(nadf):
    assert nadf.na.drop().count() == 1
    assert nadf.na.drop(how="all").count() == 3
    assert nadf.na.drop(subset=["a"]).count() == 2
    assert nadf.dropna(subset=["a", "b"]).count() == 1


def test_na_fill(nadf):
    out = nadf.na.fill(0).toArrow().to_pydict()
    assert out["a"] == [1, 0, 3]
    assert out["b"] == [0.0, 2.5, 3.5]
    assert out["s"] == ["x", None, "z"]  # numeric fill skips strings
    out2 = nadf.na.fill({"s": "missing"}).toArrow().to_pydict()
    assert out2["s"] == ["x", "missing", "z"]


def test_na_replace(nadf):
    out = nadf.na.replace(1, 100, subset=["a"]).toArrow().to_pydict()
    assert out["a"] == [100, None, 3]
    out2 = nadf.na.replace({"x": "X"}).toArrow().to_pydict()
    assert out2["s"] == ["X", None, "z"]


def test_pivot(spark):
    df = spark.createDataFrame(pa.table({
        "year": [2020, 2020, 2021, 2021, 2021],
        "quarter": ["q1", "q2", "q1", "q1", "q2"],
        "rev": [10, 20, 30, 40, 50],
    }))
    out = (df.groupBy("year").pivot("quarter")
           .agg(F.sum("rev")).orderBy("year").toArrow().to_pydict())
    assert out["year"] == [2020, 2021]
    assert out["q1"] == [10, 70]
    assert out["q2"] == [20, 50]


def test_pivot_explicit_values_and_count(spark):
    df = spark.createDataFrame(pa.table({
        "g": ["a", "a", "b"],
        "p": ["x", "y", "x"],
        "v": [1, 2, 3]}))
    out = (df.groupBy("g").pivot("p", ["x"])
           .agg(F.count("*").alias("n")).orderBy("g").toArrow().to_pydict())
    assert out["x_n"] == [1, 1]


def test_unpivot(spark):
    df = spark.createDataFrame(pa.table({
        "id": [1, 2], "m1": [10, 20], "m2": [30, 40]}))
    out = (df.unpivot("id", ["m1", "m2"])
           .orderBy("id", "variable").toArrow().to_pydict())
    assert out["id"] == [1, 1, 2, 2]
    assert out["variable"] == ["m1", "m2", "m1", "m2"]
    assert out["value"] == [10, 30, 20, 40]
