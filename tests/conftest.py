"""Test harness: CPU backend with 8 virtual devices (SURVEY.md §4 —
the local-cluster analog for distributed logic on one host).

NOTE: the container's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon (the TPU tunnel). Env vars are therefore too late —
jax.config.update is the reliable override, and it also avoids touching the
tunnel from test processes entirely."""

import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
# subprocesses spawned by tests (gate probes, dryrun re-exec, workers) must
# also be pure-CPU: this var triggers the container sitecustomize's
# accelerator-plugin registration, which overrides JAX_PLATFORMS=cpu and
# would make child processes dial the (possibly busy) TPU tunnel
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def thread_audit():
    """Thread-leak check (role of the reference's ThreadAudit,
    core/src/test/.../ThreadAudit.scala): snapshot threads at session start,
    warn on leaks at the end (daemon pools excluded)."""
    import threading
    import warnings

    before = {t.name for t in threading.enumerate()}
    yield
    after = [t for t in threading.enumerate()
             if t.name not in before and not t.daemon and t.is_alive()]
    if after:
        warnings.warn(f"possible thread leak: {[t.name for t in after]}")


@pytest.fixture(scope="session")
def spark():
    from spark_tpu import TpuSession

    conf = {"spark.sql.shuffle.partitions": 4,
            "spark.tpu.batch.capacity": 1 << 12}
    import os as _os
    if _os.environ.get("SPARK_TPU_TEST_FUSION"):
        conf["spark.tpu.fusion.enabled"] = _os.environ["SPARK_TPU_TEST_FUSION"]
    if os.environ.get("SPARK_TPU_VALIDATE") == "1":
        conf["spark.tpu.debug.validateBatches"] = "true"
    s = TpuSession("tests", conf)
    yield s
    s.stop()


@pytest.fixture()
def people(spark):
    df = spark.createDataFrame(pa.table({
        "name": ["alice", "bob", "carol", "dave", "eve", None],
        "age": [25, 32, 25, None, 41, 25],
        "dept": ["eng", "sales", "eng", "eng", "hr", "sales"],
        "salary": [100.0, 80.5, 120.0, 95.0, None, 70.0],
    }))
    df.createOrReplaceTempView("people")
    return df
