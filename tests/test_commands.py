"""DDL / utility command tests + distinct-aggregate rewrite."""

import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.errors import AnalysisException


def test_create_and_drop_view(spark):
    spark.sql("CREATE OR REPLACE TEMPORARY VIEW v1 AS SELECT 1 AS x")
    assert spark.sql("SELECT x + 1 AS y FROM v1").toArrow().to_pydict() == \
        {"y": [2]}
    spark.sql("DROP VIEW v1")
    with pytest.raises(AnalysisException):
        spark.sql("SELECT * FROM v1").toArrow()
    spark.sql("DROP VIEW IF EXISTS v1")  # no error


def test_create_table_as_materializes(spark):
    spark.sql("CREATE OR REPLACE TEMPORARY VIEW src AS "
              "SELECT col1 AS x FROM (VALUES (1), (2), (3))")
    spark.sql("CREATE TABLE t_mat AS SELECT x * 10 AS y FROM src")
    out = spark.sql("SELECT sum(y) AS s FROM t_mat").toArrow().to_pydict()
    assert out["s"] == [60]
    spark.sql("DROP TABLE t_mat")
    spark.sql("DROP VIEW src")


def test_show_tables_and_describe(spark):
    spark.sql("CREATE OR REPLACE TEMP VIEW shown AS SELECT 1 AS a, 'x' AS b")
    names = spark.sql("SHOW TABLES").toArrow().to_pydict()["tableName"]
    assert "shown" in names
    d = spark.sql("DESCRIBE shown").toArrow().to_pydict()
    assert d["col_name"] == ["a", "b"]
    assert d["data_type"] == ["integer", "string"]
    spark.sql("DROP VIEW shown")


def test_explain(spark):
    out = spark.sql("EXPLAIN SELECT 1 AS one").toArrow().to_pydict()
    assert "Physical Plan" in out["plan"][0]


def test_set_command(spark):
    spark.sql("SET spark.sql.shuffle.partitions = 6")
    assert spark.conf.shuffle_partitions == 6
    out = spark.sql("SET spark.sql.shuffle.partitions").toArrow().to_pydict()
    assert out["value"] == ["6"]
    spark.sql("SET spark.sql.shuffle.partitions = 4")


def test_count_distinct_global(spark):
    df = spark.createDataFrame(pa.table({"x": [1, 1, 2, 3, 3, 3]}))
    out = df.agg(F.countDistinct("x").alias("c")).toArrow().to_pydict()
    assert out["c"] == [3]


def test_count_distinct_grouped(spark):
    df = spark.createDataFrame(pa.table({
        "g": ["a", "a", "a", "b", "b"],
        "x": [1, 1, 2, 5, 5]}))
    out = df.groupBy("g").agg(F.countDistinct("x").alias("c")) \
        .orderBy("g").toArrow().to_pydict()
    assert out["c"] == [2, 1]


def test_count_distinct_sql(spark):
    spark.sql("CREATE OR REPLACE TEMP VIEW cd AS "
              "SELECT col1 AS g, col2 AS x FROM "
              "(VALUES (1, 10), (1, 10), (1, 20), (2, 30))")
    out = spark.sql("SELECT g, count(DISTINCT x) AS c FROM cd GROUP BY g "
                    "ORDER BY g").toArrow().to_pydict()
    assert out["c"] == [2, 1]
    spark.sql("DROP VIEW cd")


def test_mixed_distinct_and_plain_aggregates(spark):
    import pyarrow as pa

    df = spark.createDataFrame(pa.table({
        "g": ["a", "a", "b", "b", "b"],
        "x": [1, 1, 2, 3, 3],
        "v": [10, 20, 30, 40, 50]}))
    out = (df.groupBy("g")
           .agg(F.sum("v").alias("s"), F.countDistinct("x").alias("d"),
                F.count("*").alias("n"))
           .orderBy("g").toArrow().to_pydict())
    assert out["s"] == [30, 120]
    assert out["d"] == [1, 2]
    assert out["n"] == [2, 3]


def test_mixed_distinct_global(spark):
    import pyarrow as pa

    df = spark.createDataFrame(pa.table({"x": [1, 1, 2], "v": [5, 5, 10]}))
    out = df.agg(F.sum("v").alias("s"),
                 F.countDistinct("x").alias("d")).toArrow().to_pydict()
    assert out["s"] == [20]
    assert out["d"] == [2]


def test_warehouse_tables_and_insert(tmp_path):
    import pyarrow as pa

    from spark_tpu import TpuSession

    s = TpuSession("wh", {"spark.sql.warehouse.dir": str(tmp_path / "wh"),
                          "spark.tpu.batch.capacity": 1 << 12})
    try:
        s.sql("CREATE TABLE managed AS SELECT col1 AS x FROM (VALUES (1), (2))")
        assert "managed" in s.sql("SHOW TABLES").toArrow().to_pydict()["tableName"]
        assert s.sql("SELECT sum(x) AS s FROM managed").toArrow() \
            .to_pydict()["s"] == [3]

        s.sql("INSERT INTO managed VALUES (10)")
        assert s.sql("SELECT sum(x) AS s FROM managed").toArrow() \
            .to_pydict()["s"] == [13]

        s.sql("INSERT OVERWRITE managed VALUES (7)")
        assert s.sql("SELECT sum(x) AS s FROM managed").toArrow() \
            .to_pydict()["s"] == [7]

        # persists across sessions sharing the warehouse dir
        s2 = TpuSession("wh2", {"spark.sql.warehouse.dir": str(tmp_path / "wh"),
                                "spark.tpu.batch.capacity": 1 << 12})
        assert s2.sql("SELECT x FROM managed").toArrow().to_pydict()["x"] == [7]
        s2.stop()

        s.sql("DROP TABLE managed")
        from spark_tpu.errors import AnalysisException
        import pytest as _pt

        with _pt.raises(AnalysisException):
            s.sql("SELECT * FROM managed").toArrow()
    finally:
        s.stop()


def test_save_as_table_api(tmp_path):
    import pyarrow as pa

    from spark_tpu import TpuSession

    s = TpuSession("wh3", {"spark.sql.warehouse.dir": str(tmp_path / "w3"),
                           "spark.tpu.batch.capacity": 1 << 12})
    try:
        df = s.createDataFrame(pa.table({"a": [1, 2]}))
        df.write.saveAsTable("t_api")
        df.write.insertInto("t_api")
        assert s.sql("SELECT count(*) AS c FROM t_api").toArrow() \
            .to_pydict()["c"] == [4]
    finally:
        s.stop()


def test_cache_fragment_substitution(spark):
    import pyarrow as pa

    from spark_tpu.plan.logical import LocalRelation

    base = spark.createDataFrame(pa.table({
        "x": list(range(100)), "y": list(range(100))}))
    filtered = base.filter(F.col("x") > 50)
    filtered.cache()
    try:
        # an INDEPENDENT query with a semantically equal subtree reuses the
        # materialized cache
        q = base.filter(F.col("x") > 50).agg(F.count("*").alias("c"))
        plan = q.query_execution.with_cached_data
        assert any(isinstance(n, LocalRelation) and n.table.num_rows == 49
                   for n in plan.iter_nodes())
        assert q.toArrow().to_pydict()["c"] == [49]
    finally:
        filtered.unpersist()
    q2 = base.filter(F.col("x") > 50).agg(F.count("*").alias("c"))
    plan2 = q2.query_execution.with_cached_data
    assert not any(isinstance(n, LocalRelation) and n.table.num_rows == 49
                   for n in plan2.iter_nodes())


def _dml_table(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({
        "id": [1, 2, 3], "name": ["a", "b", "c"],
        "amt": [10, 20, 30]})).createOrReplaceTempView("dml_t")


def test_update_statement(spark):
    _dml_table(spark)
    spark.sql("UPDATE dml_t SET amt = amt + 100 WHERE id >= 2")
    out = spark.sql("SELECT amt FROM dml_t ORDER BY id").toArrow().to_pydict()
    assert out["amt"] == [10, 120, 130]
    spark.sql("UPDATE dml_t SET amt = 0")  # no WHERE = all rows
    out = spark.sql("SELECT amt FROM dml_t").toArrow().to_pydict()
    assert out["amt"] == [0, 0, 0]


def test_delete_statement(spark):
    _dml_table(spark)
    spark.sql("DELETE FROM dml_t WHERE id = 1")
    out = spark.sql("SELECT id FROM dml_t ORDER BY id").toArrow().to_pydict()
    assert out["id"] == [2, 3]
    spark.sql("DELETE FROM dml_t")
    out = spark.sql("SELECT id FROM dml_t").toArrow().to_pydict()
    assert out["id"] == []


def test_merge_statement(spark):
    import pyarrow as pa

    _dml_table(spark)
    spark.createDataFrame(pa.table({
        "id": [2, 3, 4], "v": [999, -1, 40]})) \
        .createOrReplaceTempView("dml_src")
    spark.sql("""
        MERGE INTO dml_t AS t USING dml_src AS u ON t.id = u.id
        WHEN MATCHED AND u.v < 0 THEN DELETE
        WHEN MATCHED THEN UPDATE SET amt = u.v
        WHEN NOT MATCHED THEN INSERT (id, amt) VALUES (u.id, u.v)""")
    out = spark.sql("SELECT id, name, amt FROM dml_t ORDER BY id") \
        .toArrow().to_pydict()
    # id=1 untouched, id=2 updated, id=3 deleted, id=4 inserted
    assert out["id"] == [1, 2, 4]
    assert out["amt"] == [10, 999, 40]
    assert out["name"] == ["a", "b", None]


def test_merge_insert_star(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({"k": [1], "v": [5]})) \
        .createOrReplaceTempView("ms_t")
    spark.createDataFrame(pa.table({"k": [1, 2], "v": [50, 20]})) \
        .createOrReplaceTempView("ms_s")
    spark.sql("""
        MERGE INTO ms_t USING ms_s ON ms_t.k = ms_s.k
        WHEN MATCHED THEN UPDATE SET v = ms_s.v
        WHEN NOT MATCHED THEN INSERT *""")
    out = spark.sql("SELECT k, v FROM ms_t ORDER BY k").toArrow().to_pydict()
    assert out["k"] == [1, 2]
    assert out["v"] == [50, 20]


def test_merge_cardinality_violation(spark):
    # one target row matching >1 source rows must raise, not duplicate
    # (reference: MERGE_CARDINALITY_VIOLATION)
    import pyarrow as pa
    import pytest

    from spark_tpu.errors import ExecutionError

    spark.createDataFrame(pa.table({"k": [1, 2], "v": [10, 20]})) \
        .createOrReplaceTempView("mcv_t")
    spark.createDataFrame(pa.table({"k": [1, 1], "v": [5, 6]})) \
        .createOrReplaceTempView("mcv_s")
    with pytest.raises(ExecutionError, match="CARDINALITY"):
        spark.sql("""
            MERGE INTO mcv_t AS t USING mcv_s AS s ON t.k = s.k
            WHEN MATCHED THEN UPDATE SET v = s.v""")


def test_merge_insert_only_multi_match_ok(spark):
    # insert-only MERGE has no cardinality constraint (reference behavior)
    import pyarrow as pa

    spark.createDataFrame(pa.table({"k": [1], "v": [10]})) \
        .createOrReplaceTempView("mio_t")
    spark.createDataFrame(pa.table({"k": [1, 1, 2], "v": [5, 6, 7]})) \
        .createOrReplaceTempView("mio_s")
    spark.sql("""
        MERGE INTO mio_t AS t USING mio_s AS s ON t.k = s.k
        WHEN NOT MATCHED THEN INSERT *""")
    out = spark.sql("SELECT k, v FROM mio_t ORDER BY k, v") \
        .toArrow().to_pydict()
    assert out["k"] == [1, 2]
    assert out["v"] == [10, 7]


def test_show_functions_and_catalog_api(spark):
    import pyarrow as pa

    out = spark.sql("SHOW FUNCTIONS").toArrow()
    fns = out.column("function").to_pylist()
    assert "sum" in fns and "get_json_object" in fns and len(fns) > 150
    liked = spark.sql("SHOW FUNCTIONS LIKE 'ARRAY_J*|SUM'").toArrow() \
        .column("function").to_pylist()
    assert liked == ["array_join", "sum"]  # case-insensitive + alternation
    assert "count" in fns  # special-cased fn still listed
    # catalog API surface (pyspark Catalog shape)
    assert spark.catalog.functionExists("crc32")
    assert spark.catalog.functionExists("COUNT")
    assert not spark.catalog.functionExists("no_such_fn")
    spark.createDataFrame(pa.table({"a": [1], "s": ["x"]})) \
        .createOrReplaceTempView("cat_t")
    cols = spark.catalog.listColumns("cat_t")
    assert [c["name"] for c in cols] == ["a", "s"]
    assert all("dataType" in c for c in cols)
