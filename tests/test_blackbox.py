"""Query black box (spark_tpu/obs/blackbox.py + obs/diagnose.py).

Contract under test: anomaly findings (obs.slo at ticket release,
query.failed, admission rejection) trigger EXACTLY one self-contained
diagnostic bundle per query — manifest, Chrome trace, plan reports
rendered without re-execution, metrics scrape, profile with embedded
same-key history — under a flock-safe bounded retention ring; healthy
runs capture nothing and the armed-untriggered kernel-launch delta is
identical to off (fusion on or off); the postmortem renderer works from
the bundle directory alone; `/*+ POOL(x) */` statement hints route
through the fair scheduler with unknown pools a typed error; the live
store counts its 64-query ring evictions; and a 2-worker cluster's
bundle carries the pulled per-executor diagnostic rings.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_tpu.config import SQLConf
from spark_tpu.errors import PoolQueueFull, UnknownPoolError
from spark_tpu.obs import blackbox
from spark_tpu.obs import export as mx
from spark_tpu.obs.diagnose import render_index, render_postmortem
from spark_tpu.obs.live import LiveObs
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
from spark_tpu.serve import QueryService


@pytest.fixture(autouse=True)
def _restore_blackbox():
    """Every test leaves the process-global capture layer OFF with
    clean registries — the module-bool discipline other suites rely
    on."""
    yield
    blackbox.reset()
    mx.configure(SQLConf({}))
    mx.REGISTRY.reset()


def _session(name, tmp_path=None, extra=None):
    from spark_tpu import TpuSession

    conf = {"spark.sql.shuffle.partitions": 2,
            "spark.tpu.batch.capacity": 1 << 11,
            "spark.tpu.fusion.minRows": "0",
            "spark.tpu.cache.result.enabled": "false"}
    if tmp_path is not None:
        conf["spark.tpu.obs.bundles"] = "true"
        conf["spark.tpu.obs.bundleDir"] = str(tmp_path / "bundles")
    conf.update(extra or {})
    return TpuSession(name, conf)


def _seed(s, view="bb_t", n=2000, seed=5):
    rng = np.random.default_rng(seed)
    s.createDataFrame(pa.table({
        "k": rng.integers(0, 12, n).astype(np.int64),
        "v": rng.integers(-30, 100, n).astype(np.int64),
    })).createOrReplaceTempView(view)


def _qid(df):
    return df.query_execution._last_ctx.query_id


# ---------------------------------------------------------------------------
# triggers: post-close SLO finding, failure, rejection, healthy sampling
# ---------------------------------------------------------------------------

class TestTriggers:
    def test_off_by_default(self, tmp_path):
        s = _session("bb-off")
        try:
            assert not blackbox.ENABLED
            _seed(s)
            s.sql("select k, sum(v) s from bb_t group by k").collect()
            assert blackbox.list_bundles(str(tmp_path)) == []
        finally:
            s.stop()

    def test_healthy_armed_run_captures_nothing(self, tmp_path):
        s = _session("bb-healthy", tmp_path)
        try:
            assert blackbox.ENABLED
            _seed(s)
            for _ in range(3):
                s.sql("select k, sum(v) s from bb_t group by k").collect()
            assert blackbox.list_bundles(
                str(tmp_path / "bundles")) == []
        finally:
            s.stop()

    def test_post_close_slo_finding_captures_once(self, tmp_path):
        """The obs.slo verdict lands on ticket release — AFTER execute()
        returned. The finding sink must still capture against the
        recently closed execution, and capture-once dedup must hold when
        the same query breaches again."""
        s = _session("bb-slo", tmp_path)
        try:
            _seed(s)
            df = s.sql("select k, sum(v) s from bb_t group by k")
            df.collect()
            qid = _qid(df)
            breach = {"severity": "warning", "kind": "obs.slo",
                      "msg": "e2e 120.0ms over pool slo 50.0ms"}
            s.live_obs.add_finding(qid, breach)
            entries = blackbox.list_bundles(str(tmp_path / "bundles"))
            assert len(entries) == 1
            assert entries[0]["trigger_kind"] == "obs.slo"
            assert entries[0]["query_id"] == qid
            # second breach of the SAME query: capture-once dedup
            s.live_obs.add_finding(qid, dict(breach))
            assert len(blackbox.list_bundles(
                str(tmp_path / "bundles"))) == 1
        finally:
            s.stop()

    def test_info_findings_never_trigger(self, tmp_path):
        s = _session("bb-info", tmp_path)
        try:
            _seed(s)
            df = s.sql("select k from bb_t limit 5")
            df.collect()
            s.live_obs.add_finding(_qid(df), {
                "severity": "info", "kind": "obs.slo", "msg": "ok"})
            s.live_obs.add_finding(_qid(df), {
                "severity": "warning", "kind": "obs.drift", "msg": "x"})
            assert blackbox.list_bundles(
                str(tmp_path / "bundles")) == []
        finally:
            s.stop()

    def test_query_failure_captures_bundle(self, tmp_path):
        """A mid-execution fault (chaos kernel.dispatch raise) must
        leave a query.failed bundle behind while the error still
        propagates to the caller."""
        s = _session("bb-fail", tmp_path, extra={
            "spark.tpu.faults.enabled": "true",
            "spark.tpu.faults.seed": "3",
            "spark.tpu.faults.points": "kernel.dispatch=always",
        })
        try:
            from spark_tpu.utils import faults

            faults.configure(s.conf)
            _seed(s)
            with pytest.raises(Exception):
                s.sql("select k, sum(v) s from bb_t group by k") \
                    .collect()
            entries = blackbox.list_bundles(str(tmp_path / "bundles"))
            assert len(entries) == 1
            assert entries[0]["trigger_kind"] == "query.failed"
            assert entries[0]["reason"] == "failure"
        finally:
            s.stop()
            from spark_tpu.utils import faults

            faults.reset()

    def test_rejection_capture_is_rate_limited(self, tmp_path):
        s = _session("bb-rej", tmp_path)
        try:
            err = PoolQueueFull("etl", 8)
            bid = blackbox.record_rejection(s, err, pool="etl")
            assert bid is not None
            entries = blackbox.list_bundles(str(tmp_path / "bundles"))
            assert len(entries) == 1
            assert entries[0]["trigger_kind"] == "serve.rejected"
            # a saturated pool rejecting a burst must not turn capture
            # into its own overload: within the gap, no second bundle
            assert blackbox.record_rejection(s, err, pool="etl") is None
            assert len(blackbox.list_bundles(
                str(tmp_path / "bundles"))) == 1
        finally:
            s.stop()

    def test_healthy_sampling_is_deterministic(self, tmp_path):
        s = _session("bb-sample", tmp_path, extra={
            "spark.tpu.obs.bundle.sampleHealthy": "2"})
        try:
            _seed(s)
            for i in range(4):
                s.sql(f"select k, sum(v) s from bb_t where v > {i} "
                      "group by k").collect()
            entries = blackbox.list_bundles(str(tmp_path / "bundles"))
            assert len(entries) == 2            # 1-in-2 of 4 queries
            assert all(e["reason"] == "sampled" for e in entries)
            assert all(e["trigger_kind"] is None for e in entries)
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# bundle contents: self-contained, renderable offline, bounded ring
# ---------------------------------------------------------------------------

class TestBundleContents:
    def test_manual_capture_is_complete_and_renders_offline(
            self, tmp_path):
        s = _session("bb-manual", tmp_path, extra={
            "spark.tpu.obs.profileDir": str(tmp_path / "profiles"),
            "spark.tpu.metrics.export": "true"})
        try:
            _seed(s)
            df = s.sql("select k, sum(v) s from bb_t group by k")
            df.collect()
            bid = s.capture_diagnostics(df)
            assert bid is not None
            bdir = str(tmp_path / "bundles")
            bundle = os.path.join(bdir, f"bundle-{bid}")
            for fname in ("bundle.json", "trace.json",
                          "explain_simple.txt", "explain_analysis.txt",
                          "explain_analyze.txt", "metrics.prom"):
                assert os.path.isfile(os.path.join(bundle, fname)), fname
            with open(os.path.join(bundle, "bundle.json")) as f:
                manifest = json.load(f)
            assert manifest["id"] == bid
            assert manifest["reason"] == "manual"
            assert manifest["query_id"] == _qid(df)
            assert manifest["plan"]["query_key"]
            assert manifest["profile"] is not None
            assert manifest["conf_overrides"].get(
                "spark.tpu.obs.bundles") == "true"
            # the analyze report came from RECORDED metrics — the
            # launch counter must not move while rendering reports
            # (asserted by the launch-identity test below); here the
            # report text itself must carry per-operator rows
            with open(os.path.join(bundle,
                                   "explain_analyze.txt")) as f:
                assert "rows" in f.read()
            # postmortem renders from the directory alone
            report = render_postmortem(bdir, bid)
            assert "Trigger timeline" in report
            assert "Counter drift vs same-key baseline" in report
            assert bid in render_index(bdir)
        finally:
            s.stop()

    def test_capture_without_dataframe_uses_most_recent(self, tmp_path):
        s = _session("bb-recent", tmp_path)
        try:
            _seed(s)
            df = s.sql("select k from bb_t limit 3")
            df.collect()
            bid = s.capture_diagnostics()
            manifest = blackbox.load_bundle(
                str(tmp_path / "bundles"), bid)
            assert manifest["query_id"] == _qid(df)
        finally:
            s.stop()

    def test_profile_history_embedded_for_drift(self, tmp_path):
        """Re-running the same query key embeds the PRIOR runs as the
        bundle's baseline history — diagnose's drift section must not
        need the profile store."""
        s = _session("bb-hist", tmp_path, extra={
            "spark.tpu.obs.profileDir": str(tmp_path / "profiles")})
        try:
            _seed(s)
            q = "select k, sum(v) s from bb_t group by k"
            for _ in range(3):
                df = s.sql(q)
                df.collect()
            bid = s.capture_diagnostics(df)
            manifest = blackbox.load_bundle(
                str(tmp_path / "bundles"), bid)
            hist = manifest["profile_history"]
            assert len(hist) >= 1
            assert all(p["query_key"] == manifest["plan"]["query_key"]
                       for p in hist)
            report = render_postmortem(str(tmp_path / "bundles"), bid)
            assert "baselines:" in report
        finally:
            s.stop()

    def test_retention_ring_prunes_oldest(self, tmp_path):
        s = _session("bb-ring", tmp_path, extra={
            "spark.tpu.obs.bundle.ring": "2"})
        try:
            _seed(s)
            df = s.sql("select k from bb_t limit 2")
            df.collect()
            bids = [s.capture_diagnostics(df) for _ in range(4)]
            bdir = str(tmp_path / "bundles")
            entries = blackbox.list_bundles(bdir)
            assert len(entries) <= 2
            assert entries[0]["id"] == bids[-1]    # newest survives
            dirs = [d for d in os.listdir(bdir)
                    if d.startswith("bundle-")]
            assert len(dirs) <= 2
            assert blackbox.load_bundle(bdir, bids[0]) is None
        finally:
            s.stop()

    def test_unknown_bundle_id_raises(self, tmp_path):
        (tmp_path / "bundles").mkdir()
        with pytest.raises(KeyError):
            render_postmortem(str(tmp_path / "bundles"), "nope")


# ---------------------------------------------------------------------------
# obs contract: armed-untriggered launch identity, fusion on and off
# ---------------------------------------------------------------------------

class TestZeroOverhead:
    @pytest.mark.parametrize("fusion_min", ["0", "1000000000"])
    def test_launch_delta_identical_armed_vs_off(self, tmp_path,
                                                 fusion_min):
        s = _session("bb-zero", extra={
            "spark.tpu.fusion.minRows": fusion_min})
        try:
            _seed(s)
            q = "select k, sum(v) s from bb_t group by k"
            s.sql(q).collect()                    # compile warmup
            l0 = KC.launches
            s.sql(q).collect()
            delta_off = KC.launches - l0
            assert delta_off > 0
            s.conf.set("spark.tpu.obs.bundles", "true")
            s.conf.set("spark.tpu.obs.bundleDir",
                       str(tmp_path / "bundles"))
            blackbox.configure(s.conf)
            l0 = KC.launches
            s.sql(q).collect()
            assert KC.launches - l0 == delta_off
            assert blackbox.list_bundles(
                str(tmp_path / "bundles")) == []
        finally:
            s.stop()

    def test_lock_is_watched(self):
        import spark_tpu.exec.worker_main  # noqa: F401 — registers slot
        from spark_tpu.utils import lockwatch

        names = set(lockwatch.registered_names())
        assert "obs.blackbox._LOCK" in names
        assert "exec.worker_main._DIAG_LOCK" in names


# ---------------------------------------------------------------------------
# satellite: /*+ POOL(x) */ statement hints
# ---------------------------------------------------------------------------

class TestPoolHints:
    def test_hint_routes_statement_to_pool(self, tmp_path):
        s = _session("bb-pool", extra={
            "spark.tpu.scheduler.pools": "etl:2"})
        try:
            _seed(s)
            service = QueryService(s)
            t = service.execute_sql(
                s, "/*+ POOL(etl) */ select k, sum(v) s from bb_t "
                   "group by k")
            assert t.num_rows == 12
            pools = service.status()["pools"]
            assert pools["etl"]["admitted"] == 1
            assert pools["default"]["admitted"] == 0
        finally:
            s.stop()

    def test_unknown_pool_is_typed_error_naming_pools(self):
        s = _session("bb-pool-err", extra={
            "spark.tpu.scheduler.pools": "etl:2,adhoc"})
        try:
            _seed(s)
            with pytest.raises(UnknownPoolError) as ei:
                s.sql("/*+ POOL(etk) */ select k from bb_t limit 1")
            e = ei.value
            assert e.error_class == "UNKNOWN_POOL"
            assert e.pool == "etk"
            assert e.valid == ["adhoc", "default", "etl"]
            for name in ("adhoc", "default", "etl"):
                assert name in str(e)
        finally:
            s.stop()

    def test_hint_is_stripped_before_parse(self):
        s = _session("bb-pool-strip")
        try:
            _seed(s)
            df = s.sql("select /*+ pool(default) */ k, sum(v) s "
                       "from bb_t group by k")
            assert df._pool_hint == "default"
            assert df.toArrow().num_rows == 12
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# satellite: live-store ring eviction counting
# ---------------------------------------------------------------------------

class TestLiveEvictions:
    def test_ring_evictions_counted_and_surfaced(self):
        live = LiveObs()
        for i in range(70):
            live.add_finding(f"q{i:03d}", {
                "severity": "info", "kind": "obs.note", "msg": "x"})
        assert live.evictions == 70 - 64
        assert live.snapshot()["evictions"] == 6
        samples = mx._live_source(live)
        assert ("counter", "obs.live.evictions", (), 6) in samples

    def test_no_evictions_under_ring_capacity(self):
        live = LiveObs()
        for i in range(10):
            live.add_finding(f"q{i}", {
                "severity": "info", "kind": "obs.note", "msg": "x"})
        assert live.evictions == 0
        samples = mx._live_source(live)
        assert ("counter", "obs.live.evictions", (), 0) in samples


# ---------------------------------------------------------------------------
# cluster: pull-on-anomaly fleet state
# ---------------------------------------------------------------------------

class TestClusterPull:
    def test_bundle_pulls_worker_diagnostic_rings(self, tmp_path):
        """The bundle's fleet state comes from the workers'
        diagnostic_state RPC at capture time: bounded post-task rings
        with executor-labeled spans, never shipped on the healthy
        path."""
        s = _session("bb-cluster", tmp_path, extra={
            "spark.sql.adaptive.enabled": "false",
            "spark.tpu.cluster.enabled": "true",
            "spark.tpu.cluster.workers": "2"})
        try:
            _seed(s, n=4000)
            df = s.table("bb_t").repartition(2)
            assert df.toArrow().num_rows == 4000
            bid = s.capture_diagnostics(df)
            bdir = str(tmp_path / "bundles")
            manifest = blackbox.load_bundle(bdir, bid)
            workers = manifest["workers"]
            assert workers                      # every worker answered
            tasks = [t for w in workers.values()
                     for t in (w.get("tasks") or [])]
            assert tasks and any(t["spans"] for t in tasks)
            assert all("faults" in w and "lockwatch" in w
                       for w in workers.values())
            with open(os.path.join(bdir, f"bundle-{bid}",
                                   "trace.json")) as f:
                trace = json.load(f)
            procs = {e["args"]["name"]
                     for e in trace["traceEvents"]
                     if e.get("name") == "process_name"}
            assert any(str(p).startswith("executor ") for p in procs)
            # postmortem's executor map renders the pulled rings
            assert "pulled ring:" in render_postmortem(bdir, bid)
        finally:
            s.stop()
