"""Compressed execution (ROADMAP direction 3): dictionary/RLE-native
kernels, encoded scans, and the code-shipping shuffle.

Differential suite against the decoded oracle
(spark.tpu.encoding.enabled=false): the encoded path — dense-on-codes
aggregation, fused string-key join probes / exchanges (padded dict-hash
aux luts), sorted-run (RLE) segment reduce, dictionary-preserving cluster
IPC — must produce byte-identical results on agg/join/sort/shuffle, local
+ cluster + mesh, nullable and high-cardinality dictionaries, with
≤1-launch-per-batch regression guards and exact plan_lint predictions
fusion on AND off."""

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC


@pytest.fixture()
def enc_spark(spark):
    spark.conf.set("spark.tpu.fusion.minRows", "0")
    spark.conf.set("spark.tpu.fusion.enabled", "true")
    yield spark
    for k in ("spark.tpu.fusion.enabled", "spark.tpu.fusion.minRows",
              "spark.tpu.encoding.enabled"):
        spark.conf.unset(k)


@pytest.fixture()
def edata(enc_spark):
    rng = np.random.default_rng(23)
    n = 5000
    s = [None if i % 37 == 0 else f"cat{i % 17}" for i in range(n)]
    hc = [f"val{rng.integers(0, 2000):04d}" for _ in range(n)]
    enc_spark.createDataFrame(pa.table({
        "k": rng.integers(0, 13, n),
        "v": rng.integers(-50, 100, n),
        "s": s,
        "hc": hc,
    })).createOrReplaceTempView("enc_t")
    sdim = pa.table({
        "sk": [f"cat{i}" for i in range(17)],
        "w": np.arange(17, dtype=np.int64),
    })
    enc_spark.createDataFrame(sdim).createOrReplaceTempView("enc_dim")
    return enc_spark


def _encoding_differential(spark, build_query, sort_cols):
    """Run the same query encoded and decoded; compare row-for-row."""
    outs = {}
    for enabled in (True, False):
        spark.conf.set("spark.tpu.encoding.enabled",
                       str(enabled).lower())
        outs[enabled] = build_query().toPandas() \
            .sort_values(sort_cols).reset_index(drop=True)
    spark.conf.unset("spark.tpu.encoding.enabled")
    got, want = outs[True], outs[False]
    assert list(got.columns) == list(want.columns)
    assert len(got) == len(want), f"{len(got)} vs {len(want)} rows"
    for c in got.columns:
        g, w = got[c].to_numpy(), want[c].to_numpy()
        if np.issubdtype(np.asarray(w).dtype, np.floating):
            np.testing.assert_allclose(g.astype(float), w.astype(float),
                                       rtol=1e-12, atol=1e-12)
        else:
            assert list(g) == list(w), f"column {c} differs"


def _kind_delta(run):
    before = dict(KC.launches_by_kind)
    run()
    after = dict(KC.launches_by_kind)
    return {k: v - before.get(k, 0) for k, v in after.items()
            if v != before.get(k, 0)}


def _assert_exact(spark, build):
    df = build()
    report = df.query_execution.analysis_report()
    df.toArrow()  # warm
    before = dict(KC.launches_by_kind)
    build().toArrow()
    after = dict(KC.launches_by_kind)
    measured = {k: v - before.get(k, 0) for k, v in after.items()
                if v != before.get(k, 0)}
    assert report.exact, report.inexact_reasons
    assert report.predicted_launches == measured, (
        f"predicted {dict(sorted(report.predicted_launches.items()))} != "
        f"measured {dict(sorted(measured.items()))}\n{report.render()}")


# ---------------------------------------------------------------------------
# differentials: encoded vs decoded oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fusion", ["true", "false"])
def test_dict_groupby_differential(edata, fusion):
    """Nullable dictionary grouping key: dense-on-codes vs the decoded
    sort path, fusion on and off (the null-key group rides the dense
    table's parking slot)."""
    edata.conf.set("spark.tpu.fusion.enabled", fusion)
    _encoding_differential(
        edata,
        lambda: edata.sql("select s, count(*) c, sum(v) sv, min(v) mn "
                          "from enc_t where v > 0 group by s"),
        ["s"])


def test_high_cardinality_dict_groupby_differential(edata):
    _encoding_differential(
        edata,
        lambda: edata.sql("select hc, count(*) c, max(v) mx from enc_t "
                          "group by hc"),
        ["hc"])


def test_string_minmax_over_dict_key_differential(edata):
    """String values reduced (rank space) under a string key grouped on
    codes — both encodings of the same batch cooperate."""
    _encoding_differential(
        edata,
        lambda: edata.sql("select s, min(hc) mn, max(hc) mx, count(*) c "
                          "from enc_t group by s"),
        ["s"])


def test_string_join_differential(edata):
    """String-key join: fused probe via the padded dict-hash lut vs the
    decoded unfused probe."""
    _encoding_differential(
        edata,
        lambda: edata.sql("select s, w, v from enc_t join enc_dim "
                          "on s = sk where v > 5"),
        ["s", "w", "v"])


def test_string_join_agg_differential(edata):
    _encoding_differential(
        edata,
        lambda: edata.sql("select w, count(*) c, sum(v) sv from enc_t "
                          "join enc_dim on s = sk group by w"),
        ["w"])


def test_string_sort_differential(edata):
    _encoding_differential(
        edata,
        lambda: edata.sql("select s, v from enc_t where v > 90 "
                          "order by s, v"),
        ["s", "v"])


def test_string_repartition_differential_host(edata):
    """Non-power-of-two partition count keeps the exchange on the host
    shuffle path: the fused map dispatch computes string pids in-kernel
    via the dict-hash lut."""
    _encoding_differential(
        edata,
        lambda: (edata.sql("select s, v * 2 as v2 from enc_t "
                           "where v > 0").repartition(5, "s")),
        ["s", "v2"])


def test_string_repartition_differential_mesh(edata):
    """Power-of-two partition count takes the mesh path (8 virtual
    devices): string keys ride staged eq-key planes after the pipeline
    materializes."""
    _encoding_differential(
        edata,
        lambda: (edata.sql("select s, v from enc_t where v != 7")
                 .repartition(4, "s").groupBy("s").count()),
        ["s"])


def test_sorted_run_agg_differential(enc_spark):
    """RLE fast path: a SORTED sparse integral key (dense span check
    fails) reduces per run boundary — results match the sorting oracle
    and the decoded oracle."""
    rng = np.random.default_rng(29)
    n = 3000
    sk = np.cumsum(rng.integers(5, 60, n)).astype(np.int64)  # sorted,
    # span ~100k >> 4*4096: the dense-range path declines
    enc_spark.createDataFrame(pa.table({
        "sk": sk, "v": rng.integers(0, 50, n),
    })).createOrReplaceTempView("enc_sorted")
    _encoding_differential(
        enc_spark,
        lambda: enc_spark.sql("select sk, count(*) c, sum(v) sv "
                              "from enc_sorted group by sk"),
        ["sk"])


# ---------------------------------------------------------------------------
# dispatch-count guards + exact predictions
# ---------------------------------------------------------------------------

def test_dict_groupby_single_dispatch_no_probe(enc_spark):
    """≤1 launch per batch for the fused string-key aggregate, and ZERO
    krange3 probes: the code domain is known host-side (len(dict))."""
    cap = 1 << 12
    n_batches = 4
    rng = np.random.default_rng(31)
    t = pa.table({"g": [f"g{int(x)}" for x in rng.integers(0, 11,
                                                           cap * n_batches)],
                  "v": rng.integers(0, 100, cap * n_batches)})
    df = enc_spark.createDataFrame(t)
    q = lambda: (df.filter(F.col("v") > 25)  # noqa: E731
                 .groupBy("g").agg(F.sum("v").alias("sv")).toArrow())
    q()  # warm
    delta = _kind_delta(q)
    assert delta.get("fused_agg", 0) == n_batches, delta
    assert delta.get("krange3", 0) == 0, delta
    assert delta.get("gagg", 0) == 0, delta
    total = sum(delta.values())
    assert total <= n_batches + 4, delta


def test_sorted_run_agg_kind_and_exact(enc_spark):
    """The sorted-run chunk dispatches ONE ragg kernel (no sort-path
    gagg, no dense dagg) and the analyzer predicts it exactly."""
    rng = np.random.default_rng(37)
    n = 3000
    t = pa.table({"sk": np.cumsum(rng.integers(5, 60, n)).astype(np.int64),
                  "v": rng.integers(0, 50, n)})
    df = enc_spark.createDataFrame(t)

    def q():
        return df.groupBy("sk").agg(F.count("*").alias("c"))

    _assert_exact(enc_spark, q)
    delta = _kind_delta(lambda: q().toArrow())
    assert delta.get("ragg", 0) == 1, delta
    assert delta.get("gagg", 0) == 0, delta
    assert delta.get("dagg", 0) == 0, delta
    report = q().query_execution.analysis_report()
    assert any("sorted-run" in nn for s in report.stages
               for nn in s["notes"]), report.render()


@pytest.mark.parametrize("fusion", ["true", "false"])
def test_dict_agg_prediction_exact(edata, fusion):
    edata.conf.set("spark.tpu.fusion.enabled", fusion)
    _assert_exact(edata, lambda: edata.sql(
        "select s, count(*) c, sum(v) sv from enc_t where v > 0 "
        "group by s"))


@pytest.mark.parametrize("fusion", ["true", "false"])
def test_string_shuffle_agg_prediction_exact(edata, fusion):
    """String-keyed repartition + group-by: the reduce layout rides the
    dictionary-hash eq lanes host-side, the reduce tiles carry merged
    dictionary domains, and the whole plan predicts exactly."""
    edata.conf.set("spark.tpu.fusion.enabled", fusion)
    _assert_exact(edata, lambda: (
        edata.sql("select s, v from enc_t where v > 0")
        .repartition(5, "s").groupBy("s").count()))


def test_string_probe_single_dispatch(edata):
    """Fused string probe: one dispatch per probe batch, no separate
    pipeline launch (the dict-hash lut rides as an aux input)."""
    q = lambda: edata.sql(  # noqa: E731
        "select s, w from enc_t join enc_dim on s = sk "
        "where v > 0").toArrow()
    q()  # warm
    delta = _kind_delta(q)
    assert delta.get("fused_probe", 0) >= 1, delta
    assert delta.get("join_probe", 0) == 0, delta  # unfused path retired
    # the only pipeline launch left is the BUILD side's own filter
    assert delta.get("pipeline", 0) <= 1, delta


def test_dict_ingest_seeds_range_memo(enc_spark):
    """Satellite: dictionary cardinality seeds the dense-range memo at
    ingest — a dense-range read of a CODE column never launches the
    krange3 probe, even cold."""
    from spark_tpu.physical.operators import dense_range_stats

    t = pa.table({"c": ["a", "b", "a", "c", None, "b"]})
    df = enc_spark.createDataFrame(t)
    parts = df.query_execution.execute()
    before = KC.launches_by_kind.get("krange3", 0)
    for part in parts:
        for b in part:
            col = b.columns[0]
            kmin, kmax, any_live = dense_range_stats(
                col, b.row_mask, b.capacity)
            assert (kmin, kmax) == (0, len(col.dictionary) - 1)
            assert any_live
    assert KC.launches_by_kind.get("krange3", 0) == before


# ---------------------------------------------------------------------------
# code-shipping shuffle: encoded IPC + dictionary identity
# ---------------------------------------------------------------------------

def test_encoded_ipc_roundtrip_shares_dictionaries(enc_spark):
    """The encoded wire format ships codes + dictionaries (never decoded
    values); equal dictionary tokens rebuild to ONE shared StringDict
    across blocks (identity remap, no re-encode)."""
    from spark_tpu.exec.cluster_sql import (
        _ipc_to_partition, _partition_to_ipc_encoded,
    )
    from spark_tpu.physical.operators import attrs_schema

    df = enc_spark.createDataFrame(pa.table({
        "s": [f"x{i % 7}" for i in range(6000)],
        "v": np.arange(6000, dtype=np.int64),
    }))
    parts = df.query_execution.execute()
    part = [b for p in parts for b in p]
    assert len(part) >= 2  # 6000 rows at 4096-capacity tiles
    payload, tokens = _partition_to_ipc_encoded(part)
    assert payload[0] == "enc1"
    assert 0 in tokens and len(tokens[0]) == len(part)
    schema = attrs_schema(df.query_execution.physical.output)
    cache: dict = {}
    # tokens travel on the MapStatus (dict_ids), not in the payload —
    # the reduce side hands them back in alongside the intern cache
    rebuilt = _ipc_to_partition(payload, schema, dict_cache=cache,
                                dict_tokens=tokens)
    assert len(rebuilt) == len(part)
    dicts = [b.columns[0].dictionary for b in rebuilt]
    # equal tokens -> the SAME StringDict object (identity fast path)
    tok_to_dict = {}
    for tok, sd in zip(tokens[0], dicts):
        if tok in tok_to_dict:
            assert sd is tok_to_dict[tok]
        tok_to_dict[tok] = sd
    # values decode identically to the source
    src = pa.concat_tables([b.to_arrow() for b in part])
    got = pa.concat_tables([b.to_arrow() for b in rebuilt])
    assert src.equals(got)


def test_cluster_encoded_differential_and_bytes(enc_spark):
    """Cluster shuffle ships codes + one dictionary per map task:
    encoded and decoded cluster runs agree, the MapStatus carries the
    dictionary identity, and the encoded payload moves measurably fewer
    bytes for a dictionary-heavy table."""
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    rng = np.random.default_rng(41)
    n = 6000
    t = pa.table({
        # long repeated strings: the decoded wire format pays them per row
        "s": [f"category-with-a-rather-long-name-{int(x):02d}"
              for x in rng.integers(0, 12, n)],
        "v": rng.integers(-20, 80, n),
    })
    outs, bytes_written = {}, {}
    for enabled in ("true", "false"):
        s = TpuSession(f"enc-cluster-{enabled}", {
            "spark.sql.shuffle.partitions": "3",
            "spark.tpu.batch.capacity": 1 << 12,
            "spark.sql.adaptive.enabled": "false",
            "spark.tpu.fusion.enabled": "true",
            "spark.tpu.fusion.minRows": "0",
            "spark.tpu.encoding.enabled": enabled,
        })
        cluster = LocalCluster(num_workers=2)
        s.attachSqlCluster(cluster)
        try:
            s.createDataFrame(t).createOrReplaceTempView("ec_t")
            df = (s.sql("select s, v from ec_t where v > 0")
                  .repartition(3, "s").groupBy("s")
                  .agg(F.sum("v").alias("sv")))
            outs[enabled] = (df.toPandas().sort_values("s")
                             .reset_index(drop=True))
            snap = s._metrics.snapshot()["counters"]
            assert snap.get("scheduler.stages_remote", 0) >= 1
            bytes_written[enabled] = snap.get("shuffle.bytes_written", 0)
        finally:
            s.stop()
    assert outs["true"].equals(outs["false"])
    assert bytes_written["true"] > 0 and bytes_written["false"] > 0
    # codes + one dict per map task beat decoded row values on the wire
    assert bytes_written["true"] < bytes_written["false"], bytes_written


def test_local_shuffle_bytes_encoded_smaller(edata):
    """Local host shuffle: the shipped host planes are int32 codes +
    shared dictionary references either way — the counter exists and the
    encoded fused path moves no MORE bytes than the decoded oracle."""
    def run():
        (edata.sql("select s, v from enc_t where v > 0")
         .repartition(5, "s").toArrow())

    sizes = {}
    for enabled in ("true", "false"):
        edata.conf.set("spark.tpu.encoding.enabled", enabled)
        before = edata._metrics.snapshot()["counters"].get(
            "shuffle.bytes_shipped", 0)
        run()
        after = edata._metrics.snapshot()["counters"].get(
            "shuffle.bytes_shipped", 0)
        sizes[enabled] = after - before
    edata.conf.unset("spark.tpu.encoding.enabled")
    assert sizes["true"] > 0
    assert sizes["true"] <= sizes["false"], sizes
