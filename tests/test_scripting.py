"""SQL scripting tests (reference: sql/core scripting
SqlScriptingInterpreterSuite / SqlScriptingExecutionSuite shapes)."""

import pyarrow as pa
import pytest


def test_script_sequential_statements_and_variables(spark):
    spark.createDataFrame(pa.table({"x": [1, 2, 3, 4]})) \
        .createOrReplaceTempView("sc_t")
    out = spark.sql("""
    BEGIN
        DECLARE lim INT DEFAULT 2;
        SELECT count(*) AS c FROM sc_t WHERE x > lim;
    END""").toArrow()
    assert out.column("c")[0].as_py() == 2
    # block-scoped: lim is gone after the script
    with pytest.raises(Exception):
        spark.sql("SELECT lim AS v").toArrow()


def test_script_if_else(spark):
    out = spark.sql("""
    BEGIN
        DECLARE mode INT DEFAULT 2;
        IF mode = 1 THEN
            SELECT 'one' AS r;
        ELSEIF mode = 2 THEN
            SELECT 'two' AS r;
        ELSE
            SELECT 'other' AS r;
        END IF;
    END""").toArrow()
    assert out.column("r")[0].as_py() == "two"


def test_script_while_loop(spark):
    out = spark.sql("""
    BEGIN
        DECLARE i INT DEFAULT 0;
        DECLARE total INT DEFAULT 0;
        WHILE i < 5 DO
            SET VAR total = total + i;
            SET VAR i = i + 1;
        END WHILE;
        SELECT total AS t;
    END""").toArrow()
    assert out.column("t")[0].as_py() == 0 + 1 + 2 + 3 + 4


def test_script_repeat_until(spark):
    out = spark.sql("""
    BEGIN
        DECLARE i INT DEFAULT 0;
        REPEAT
            SET VAR i = i + 2;
        UNTIL i >= 7
        END REPEAT;
        SELECT i AS v;
    END""").toArrow()
    assert out.column("v")[0].as_py() == 8


def test_script_nested_if_inside_while(spark):
    out = spark.sql("""
    BEGIN
        DECLARE i INT DEFAULT 0;
        DECLARE evens INT DEFAULT 0;
        WHILE i < 6 DO
            IF i % 2 = 0 THEN
                SET VAR evens = evens + 1;
            END IF;
            SET VAR i = i + 1;
        END WHILE;
        SELECT evens AS e;
    END""").toArrow()
    assert out.column("e")[0].as_py() == 3


def test_script_writes_through_dml(spark):
    spark.sql("""
    BEGIN
        CREATE OR REPLACE TEMP VIEW sc_out AS SELECT 1 AS a;
    END""")
    assert spark.sql("SELECT * FROM sc_out").toArrow() \
        .column("a")[0].as_py() == 1


def test_script_leave_exits(spark):
    out = spark.sql("""
    BEGIN
        DECLARE i INT DEFAULT 0;
        WHILE 1 = 1 DO
            SET VAR i = i + 1;
            IF i >= 3 THEN
                LEAVE;
            END IF;
        END WHILE;
        SELECT i AS v;
    END""").toArrow()
    assert out.column("v")[0].as_py() == 3


def test_script_nested_same_kind_constructs(spark):
    """WHILE directly inside WHILE and IF directly inside IF (same-kind
    nesting as the FIRST body statement — the shape that breaks naive
    fragment scanners)."""
    out = spark.sql("""
    BEGIN
        DECLARE i INT DEFAULT 0;
        DECLARE acc INT DEFAULT 0;
        WHILE i < 2 DO
            WHILE acc < (i + 1) * 10 DO
                SET VAR acc = acc + 5;
            END WHILE;
            SET VAR i = i + 1;
        END WHILE;
        SELECT acc AS a;
    END""").toArrow()
    assert out.column("a")[0].as_py() == 20
    out2 = spark.sql("""
    BEGIN
        DECLARE x INT DEFAULT 5;
        IF x > 0 THEN
            IF x > 3 THEN
                SELECT 'big' AS r;
            ELSE
                SELECT 'small' AS r;
            END IF;
        END IF;
    END""").toArrow()
    assert out2.column("r")[0].as_py() == "big"


def test_script_case_expression_not_confused_with_control(spark):
    out = spark.sql("""
    BEGIN
        DECLARE v INT DEFAULT 2;
        SELECT CASE WHEN v = 1 THEN 'one' ELSE 'many' END AS label;
    END""").toArrow()
    assert out.column("label")[0].as_py() == "many"


def test_script_result_not_reexecuted(spark):
    """The returned DataFrame is materialized — collecting it twice must
    not re-run the final statement."""
    df = spark.sql("""
    BEGIN
        DECLARE n INT DEFAULT 3;
        SELECT n * 2 AS v;
    END""")
    assert df.toArrow().column("v")[0].as_py() == 6
    assert df.toArrow().column("v")[0].as_py() == 6  # n already dropped


def test_variable_does_not_shadow_correlated_outer_column(spark):
    """A session variable must lose to a correlated OUTER column of the
    same name (reference resolution order)."""
    import pyarrow as pa

    spark.sql("DECLARE VARIABLE corr_k INT DEFAULT 1")
    try:
        spark.createDataFrame(pa.table({
            "corr_k": [1, 2], "x": [10, 20]})) \
            .createOrReplaceTempView("corr_t")
        spark.createDataFrame(pa.table({
            "ik": [1, 1, 2], "y": [5, 6, 100]})) \
            .createOrReplaceTempView("corr_s")
        # correlated: ik = corr_t.corr_k (outer), NOT the variable (=1)
        out = spark.sql("""
            SELECT x FROM corr_t
            WHERE x > (SELECT max(y) FROM corr_s WHERE ik = corr_k)
            ORDER BY x""").toArrow()
        # row corr_k=1: max(y)=6 < 10 → keep; row corr_k=2: max=100 > 20 → drop
        assert out.column("x").to_pylist() == [10]
    finally:
        spark.sql("DROP TEMPORARY VARIABLE corr_k")


def test_recursive_view_rejected_even_in_subquery(spark):
    import pyarrow as pa
    import pytest as _pytest

    spark.createDataFrame(pa.table({"a": [1]})) \
        .createOrReplaceTempView("rv_base")
    spark.sql("CREATE OR REPLACE TEMP VIEW rv_v AS SELECT * FROM rv_base")
    with _pytest.raises(Exception, match="Recursive view"):
        spark.sql("CREATE OR REPLACE TEMP VIEW rv_v AS "
                  "SELECT * FROM rv_base WHERE a IN (SELECT a FROM rv_v)")


def test_variable_loses_to_column_in_having(spark):
    import pyarrow as pa

    spark.sql("DECLARE VARIABLE hav_age INT DEFAULT 1000")
    try:
        spark.createDataFrame(pa.table({
            "k": [1, 1, 2], "hav_age": [60, 70, 10]})) \
            .createOrReplaceTempView("hav_t")
        out = spark.sql(
            "SELECT k FROM hav_t GROUP BY k HAVING max(hav_age) > 50"
        ).toArrow()
        assert out.column("k").to_pylist() == [1]  # column, not var
    finally:
        spark.sql("DROP TEMPORARY VARIABLE hav_age")


def test_variable_declared_type_is_sticky(spark):
    spark.sql("DECLARE VARIABLE typed_n INT DEFAULT 1")
    try:
        spark.sql("SET VARIABLE typed_n = '7'")  # cast to INT
        out = spark.sql("SELECT typed_n + 1 AS v").toArrow()
        assert out.column("v")[0].as_py() == 8
        import pytest as _pytest

        with _pytest.raises(Exception, match="already exists"):
            spark.sql("DECLARE VARIABLE typed_n INT DEFAULT 2")
        spark.sql("DECLARE OR REPLACE VARIABLE typed_n INT DEFAULT 2")
        assert spark.sql("SELECT typed_n AS v").toArrow() \
            .column("v")[0].as_py() == 2
    finally:
        spark.sql("DROP TEMPORARY VARIABLE typed_n")


def test_script_inner_declare_shadows_and_restores(spark):
    out = spark.sql("""
    BEGIN
        DECLARE sx INT DEFAULT 1;
        BEGIN
            DECLARE sx INT DEFAULT 100;
            SET VAR sx = sx + 1;
        END;
        SET VAR sx = sx + 10;
        SELECT sx AS v;
    END""").toArrow()
    assert out.column("v")[0].as_py() == 11  # outer sx restored, then +10
