"""Higher-order functions, lambda binding, and the NULL-semantics fixes
(reference: sqlcat/expressions/higherOrderFunctions.scala,
collectionOperations.scala, optimizer/subquery.scala null-aware IN)."""

import pyarrow as pa
import pytest


def one(spark, q):
    return spark.sql(q).toArrow().to_pylist()[0]["r"]


class TestHigherOrder:
    def test_transform(self, spark):
        assert one(spark, "select transform(array(1,2,3), x -> x + 1) r") \
            == [2, 3, 4]

    def test_transform_with_index(self, spark):
        assert one(spark,
                   "select transform(array(10,20), (x, i) -> x + i) r") \
            == [10, 21]

    def test_filter(self, spark):
        assert one(spark,
                   "select filter(array(1,2,3,4), x -> x % 2 = 0) r") \
            == [2, 4]

    def test_aggregate_and_finish(self, spark):
        assert one(spark,
                   "select aggregate(array(1,2,3), 0, "
                   "(acc, x) -> acc + x) r") == 6
        assert one(spark,
                   "select aggregate(array(1,2,3), 0, "
                   "(acc, x) -> acc + x, acc -> acc * 10) r") == 60

    def test_zip_with_pads_nulls(self, spark):
        assert one(spark, "select zip_with(array(1,2), array(3,4,5), "
                          "(a, b) -> coalesce(a, 0) + b) r") == [4, 6, 5]

    def test_exists_three_valued(self, spark):
        assert one(spark,
                   "select exists(array(1,2), x -> x > 1) r") is True
        # no TRUE + a NULL predicate result → NULL
        assert one(spark,
                   "select exists(array(1,null), x -> x > 5) r") is None
        # a TRUE wins over NULLs
        assert one(spark,
                   "select exists(array(1,null,3), x -> x > 2) r") is True

    def test_forall(self, spark):
        assert one(spark, "select forall(array(1,2), x -> x > 0) r") \
            is True
        assert one(spark, "select forall(array(1,-2), x -> x > 0) r") \
            is False

    def test_map_hofs(self, spark):
        assert one(spark, "select transform_values(map('a',1,'b',2), "
                          "(k, v) -> v + 1) r") == [("a", 2), ("b", 3)]
        assert one(spark, "select map_filter(map('a',1,'b',2), "
                          "(k, v) -> v > 1) r") == [("b", 2)]
        assert one(spark, "select map_zip_with(map('a',1), map('a',2), "
                          "(k, v1, v2) -> v1 + v2) r") == [("a", 3)]

    def test_array_sort_comparator_and_default(self, spark):
        assert one(spark, "select array_sort(array(3,1,2), (a, b) -> "
                          "case when a < b then -1 when a > b then 1 "
                          "else 0 end) r") == [1, 2, 3]
        assert one(spark, "select array_sort(array(3,null,1)) r") \
            == [1, 3, None]

    def test_nested_hof(self, spark):
        assert one(spark, "select transform(array(1,2), x -> "
                          "aggregate(array(1,2,3), 0, (a,b) -> a+b) + x)"
                          " r") == [7, 8]

    def test_column_input_and_capture(self, spark):
        spark.createDataFrame(pa.table({
            "id": [1, 2],
            "nums": pa.array([[1, 2, 3], [4, 5]],
                             pa.list_(pa.int64()))})) \
            .createOrReplaceTempView("hof_t")
        got = spark.sql("select transform(nums, x -> x + id) r "
                        "from hof_t").toArrow().to_pylist()
        assert [r["r"] for r in got] == [[2, 3, 4], [6, 7]]
        got = spark.sql("select aggregate(nums, 0, (a, x) -> a + x) r "
                        "from hof_t").toArrow().to_pylist()
        assert [r["r"] for r in got] == [6, 9]


class TestNullSemanticsFixes:
    def test_flatten_null_subarray_nulls_result(self, spark):
        assert one(spark, "select flatten(array(array(1), null)) r") \
            is None
        assert one(spark,
                   "select flatten(array(array(1), array(2,3))) r") \
            == [1, 2, 3]

    def test_get_json_object_null_vs_missing(self, spark):
        assert one(spark, "select get_json_object("
                          "'{\"a\":null}', '$.a') r") is None
        assert one(spark, "select get_json_object("
                          "'{\"a\":1}', '$.b') r") is None
        assert one(spark, "select get_json_object("
                          "'{\"a\":1}', '$.a') r") == "1"

    def test_element_at_string_out_of_bounds(self, spark):
        assert one(spark,
                   "select element_at(split('a,b', ','), 5) r") is None


class TestCorrelatedInThreeValued:
    @pytest.fixture()
    def views(self, spark):
        spark.sql(
            "create or replace temp view tin3 as "
            "select 1 a, 1 k union all select cast(null as int) a, 1 k "
            "union all select 5 a, 1 k union all select 1 a, 2 k "
            "union all select 2 a, 3 k")
        spark.sql(
            "create or replace temp view uin3 as "
            "select 1 b, 1 ku union all "
            "select cast(null as int) b, 1 ku union all select 2 b, 2 ku")

    def test_correlated_in_value_position(self, spark, views):
        rows = spark.sql(
            "select a, k, a in (select b from uin3 where ku = k) r "
            "from tin3").toArrow().to_pylist()
        got = {(r["a"], r["k"]): r["r"] for r in rows}
        assert got == {(1, 1): True,      # matched
                       (5, 1): None,      # unmatched, set has NULL
                       (None, 1): None,   # NULL probe, set non-empty
                       (1, 2): False,     # unmatched, set all non-null
                       (2, 3): False}     # empty set → false, not NULL

    def test_correlated_not_in_value_position(self, spark, views):
        rows = spark.sql(
            "select a, k, a not in (select b from uin3 where ku = k) r "
            "from tin3").toArrow().to_pylist()
        got = {(r["a"], r["k"]): r["r"] for r in rows}
        assert got == {(1, 1): False, (5, 1): None, (None, 1): None,
                       (1, 2): True, (2, 3): True}


class TestIntervalRegexpBreadth:
    def test_interval_algebra(self, spark):
        assert str(one(spark, "select timestamp '2020-01-01 00:00:00' "
                              "+ interval '2' day * 3 r")) \
            == "2020-01-07 00:00:00"
        assert str(one(spark, "select timestamp '2020-01-02 00:00:00' "
                              "- interval '1' day / 2 r")) \
            == "2020-01-01 12:00:00"

    def test_make_interval_family(self, spark):
        assert str(one(spark, "select date '2020-01-01' + "
                              "make_interval(0,1,0,2,0,0,0) r")) \
            == "2020-02-03"
        assert str(one(spark, "select timestamp '2020-01-01 00:00:00' + "
                              "make_dt_interval(0, 1, 30, 15.5) r")) \
            == "2020-01-01 01:30:15.500000"
        assert str(one(spark, "select date '2020-03-31' + "
                              "make_ym_interval(1, 1) r")) == "2021-04-30"

    def test_regexp_family(self, spark):
        assert one(spark, "select regexp_extract_all('a1b2c3', "
                          "'([a-z])(\\\\d)', 1) r") == ["a", "b", "c"]
        assert one(spark, "select regexp_extract_all('a1b2', "
                          "'[a-z]\\\\d') r") == ["a1", "b2"]
        assert one(spark, "select regexp_substr('abc', 'z') r") is None
        assert one(spark, "select regexp_instr('abcdef', 'cd') r") == 3
        assert one(spark, "select regexp_count('abab', 'ab') r") == 2
        assert one(spark, "select regexp_like('abc', '^a') r") is True

    def test_to_number(self, spark):
        assert float(one(spark,
                         "select to_number('-12.34', '99.99') r")) \
            == -12.34
        assert float(one(spark, "select try_to_number('$1,234.5', "
                                "'$9,999.9') r")) == 1234.5
        assert one(spark, "select try_to_number('bogus', '999') r") \
            is None


class TestModeAggregate:
    def test_mode_grouped_tiebreak_nulls(self, spark):
        spark.sql(
            "create or replace temp view modet as "
            "select 1 g, 5 v union all select 1, 5 union all "
            "select 1, 9 union all select 2, 7 union all select 2, 8 "
            "union all select 3, cast(null as int) "
            "union all select 3, cast(null as int)")
        r = spark.sql("select g, mode(v) m from modet group by g "
                      "order by g").toArrow().to_pylist()
        # g=2 ties 7/8 -> deterministic smallest; all-null group -> NULL
        assert r == [{"g": 1, "m": 5}, {"g": 2, "m": 7},
                     {"g": 3, "m": None}]
        assert spark.sql("select mode(v) m from modet")             .toArrow().to_pylist()[0]["m"] == 5

    def test_mode_strings(self, spark):
        spark.sql(
            "create or replace temp view modes as "
            "select 'a' s union all select 'b' union all select 'b'")
        assert spark.sql("select mode(s) m from modes")             .toArrow().to_pylist()[0]["m"] == "b"

    def test_mode_null_grouping_key(self, spark):
        spark.sql(
            "create or replace temp view moden as "
            "select cast(null as int) g, 4 v union all "
            "select cast(null as int), 4 union all "
            "select cast(null as int), 9 union all select 1, 7")
        r = spark.sql("select g, mode(v) m from moden group by g "
                      "order by g nulls first").toArrow().to_pylist()
        assert r == [{"g": None, "m": 4}, {"g": 1, "m": 7}]

    def test_mode_aliased_group_and_nested_expr(self, spark):
        spark.sql(
            "create or replace temp view modex as "
            "select 1 g, 5 v union all select 1, 5 union all "
            "select 1, 9 union all select 2, 7 union all select 2, 8")
        r = spark.sql("select g as h, mode(v) m from modex group by g "
                      "order by h").toArrow().to_pylist()
        assert r == [{"h": 1, "m": 5}, {"h": 2, "m": 7}]
        r2 = spark.sql("select g, mode(v) + 1 m from modex group by g "
                       "order by g").toArrow().to_pylist()
        assert [x["m"] for x in r2] == [6, 8]


class TestUsingJoin:
    @pytest.fixture()
    def views(self, spark):
        spark.sql("create or replace temp view uja as "
                  "select 1 id, 'a' t union all select 2, 'b' "
                  "union all select 3, 'c'")
        spark.sql("create or replace temp view ujb as "
                  "select 2 id, 'x' u union all select 3, 'y' "
                  "union all select 4, 'z'")

    def test_all_join_types(self, spark, views):
        inner = spark.sql("select * from uja join ujb using (id) "
                          "order by id").toArrow()
        assert inner.column_names == ["id", "t", "u"]
        assert [r["id"] for r in inner.to_pylist()] == [2, 3]
        full = spark.sql("select * from uja full join ujb using (id) "
                         "order by id").toArrow().to_pylist()
        assert [r["id"] for r in full] == [1, 2, 3, 4]
        assert full[0]["u"] is None and full[3]["t"] is None
        anti = spark.sql("select t from uja left anti join ujb "
                         "using (id)").toArrow().to_pylist()
        assert anti == [{"t": "a"}]

    def test_self_join_using_dedups_ids(self, spark):
        spark.sql("create or replace temp view ujs as "
                  "select 1 k, 'a' v union all select 2, 'b' "
                  "union all select cast(null as int), 'c'")
        n = spark.sql("select count(*) c from ujs a join ujs b "
                      "using (k)").toArrow().to_pylist()[0]["c"]
        assert n == 2      # NULL keys never match; no cross-join blowup
        got = spark.sql("select a.v x, b.v y from ujs a join ujs b "
                        "using (k) order by x").toArrow().to_pylist()
        assert got == [{"x": "a", "y": "a"}, {"x": "b", "y": "b"}]

    def test_outer_using_nullability(self, spark):
        spark.sql("create or replace temp view ujl as select 0 id "
                  "union all select 1 union all select 2")
        spark.sql("create or replace temp view ujr as "
                  "select 2 id, 20 y union all select 4, 40")
        sch = spark.sql("select * from ujl left join ujr using (id)")             .schema
        assert [f for f in sch if f.name == "y"][0].nullable is True
