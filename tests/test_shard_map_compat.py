"""parallel/_shard_map_compat: the jax version-skew shim must translate
the replication-check kwarg by FEATURE DETECTION and fail loudly on an
unrecognized shard_map surface — a silent fallback would leave the mesh
kernels running with no replication check on the next jax rename."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_tpu.parallel import _shard_map_compat as C


@pytest.fixture(autouse=True)
def _reset_detection():
    """Detection is cached per process; each test re-detects."""
    before = C._check_kwarg
    C._check_kwarg = None
    yield
    C._check_kwarg = before


def _call(monkeypatch, fake):
    monkeypatch.setattr(C, "_shard_map", fake)
    return C.shard_map(lambda x: x, mesh="m", in_specs=("i",),
                       out_specs="o", check_vma=False)


def test_translates_to_check_vma(monkeypatch):
    seen = {}

    def fake(f, *, mesh, in_specs, out_specs, check_vma=True):
        seen.update(mesh=mesh, check_vma=check_vma)
        return "wrapped"

    assert _call(monkeypatch, fake) == "wrapped"
    assert seen["check_vma"] is False
    assert C._check_kwarg == "check_vma"


def test_translates_to_check_rep(monkeypatch):
    seen = {}

    def fake(f, *, mesh, in_specs, out_specs, check_rep=True):
        seen.update(check_rep=check_rep)
        return "wrapped"

    assert _call(monkeypatch, fake) == "wrapped"
    assert seen["check_rep"] is False
    assert C._check_kwarg == "check_rep"


def test_unknown_surface_fails_loudly(monkeypatch):
    def fake(f, *, mesh, in_specs, out_specs, verify_replication=True):
        return "wrapped"  # pragma: no cover — must never be reached

    with pytest.raises(RuntimeError, match="_shard_map_compat"):
        _call(monkeypatch, fake)


def test_var_kwargs_surface_fails_loudly(monkeypatch):
    """**kwargs hides the real parameter name: refusing is the only safe
    move (a guessed kwarg would blow up — or silently no-op — deep
    inside jax)."""

    def fake(f, *, mesh, in_specs, out_specs, **kw):
        return "wrapped"  # pragma: no cover

    with pytest.raises(RuntimeError, match="renamed"):
        _call(monkeypatch, fake)


def test_no_check_requested_skips_detection(monkeypatch):
    """check_vma=None passes nothing through — no detection, any
    surface accepted."""

    def fake(f, *, mesh, in_specs, out_specs):
        return "wrapped"

    monkeypatch.setattr(C, "_shard_map", fake)
    assert C.shard_map(lambda x: x, mesh="m", in_specs=("i",),
                       out_specs="o") == "wrapped"
    assert C._check_kwarg is None  # still undetected


def test_real_jax_shard_map_smoke():
    """The shim must drive THIS container's jax end to end (the loud-
    failure contract is only meaningful if the happy path works)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    x = jax.device_put(jnp.arange(8, dtype=jnp.int32),
                       NamedSharding(mesh, P("data")))
    f = C.shard_map(lambda a: a * 2, mesh=mesh, in_specs=(P("data"),),
                    out_specs=P("data"), check_vma=False)
    np.testing.assert_array_equal(np.asarray(jax.jit(f)(x)),
                                  np.arange(8) * 2)
