"""Subquery tests (reference: sqlcat/optimizer subquery suites + SQL tests)."""

import pyarrow as pa
import pytest


@pytest.fixture()
def shop(spark):
    orders = spark.createDataFrame(pa.table({
        "oid": [1, 2, 3, 4, 5],
        "cust": ["a", "a", "b", "c", "b"],
        "amount": [10.0, 20.0, 5.0, 99.0, 30.0],
    }))
    customers = spark.createDataFrame(pa.table({
        "cid": ["a", "b", "d"],
        "region": ["west", "east", "west"],
    }))
    orders.createOrReplaceTempView("orders")
    customers.createOrReplaceTempView("customers")
    return spark


def q(spark, text):
    return spark.sql(text).toArrow().to_pydict()


def test_uncorrelated_scalar_subquery(shop):
    out = q(shop, """SELECT oid FROM orders
                     WHERE amount > (SELECT avg(amount) FROM orders)
                     ORDER BY oid""")
    assert out["oid"] == [4]  # avg = 32.8


def test_scalar_subquery_in_select(shop):
    out = q(shop, "SELECT (SELECT max(amount) FROM orders) AS m")
    assert out["m"] == [99.0]


def test_in_subquery(shop):
    out = q(shop, """SELECT oid FROM orders
                     WHERE cust IN (SELECT cid FROM customers)
                     ORDER BY oid""")
    assert out["oid"] == [1, 2, 3, 5]


def test_not_in_subquery(shop):
    out = q(shop, """SELECT oid FROM orders
                     WHERE cust NOT IN (SELECT cid FROM customers)""")
    assert out["oid"] == [4]


def test_correlated_exists(shop):
    out = q(shop, """SELECT cid FROM customers c
                     WHERE EXISTS (SELECT 1 FROM orders o
                                   WHERE o.cust = c.cid)
                     ORDER BY cid""")
    assert out["cid"] == ["a", "b"]


def test_correlated_not_exists(shop):
    out = q(shop, """SELECT cid FROM customers c
                     WHERE NOT EXISTS (SELECT 1 FROM orders o
                                       WHERE o.cust = c.cid)""")
    assert out["cid"] == ["d"]


def test_correlated_scalar_subquery(shop):
    # orders above their customer's average
    out = q(shop, """SELECT oid FROM orders o
                     WHERE amount > (SELECT avg(amount) FROM orders i
                                     WHERE i.cust = o.cust)
                     ORDER BY oid""")
    # cust a avg 15 → oid2; cust b avg 17.5 → oid5; cust c avg 99 → none
    assert out["oid"] == [2, 5]


def test_in_subquery_with_correlation(shop):
    out = q(shop, """SELECT oid FROM orders o
                     WHERE amount IN (SELECT max(amount) FROM orders i
                                      WHERE i.cust = o.cust)
                     ORDER BY oid""")
    assert out["oid"] == [2, 4, 5]


def test_not_in_null_aware(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({"x": [1, 2, None]})) \
        .createOrReplaceTempView("na_outer")
    spark.createDataFrame(pa.table({"y": [2, None]})) \
        .createOrReplaceTempView("na_inner_null")
    spark.createDataFrame(pa.table({"y": [2, 3]})) \
        .createOrReplaceTempView("na_inner")
    # a NULL in the subquery makes NOT IN never-true → empty result
    out = q(spark, "SELECT x FROM na_outer "
                   "WHERE x NOT IN (SELECT y FROM na_inner_null)")
    assert out["x"] == []
    # NULL outer values are filtered (NOT IN is unknown, not true)
    out = q(spark, "SELECT x FROM na_outer "
                   "WHERE x NOT IN (SELECT y FROM na_inner) ORDER BY x")
    assert out["x"] == [1]
    # IN keeps plain semantics
    out = q(spark, "SELECT x FROM na_outer "
                   "WHERE x IN (SELECT y FROM na_inner)")
    assert out["x"] == [2]


def test_existence_subquery_in_select(spark):
    import pyarrow as pa

    spark.createDataFrame(pa.table({"cid": ["a", "b", "c"]})) \
        .createOrReplaceTempView("ex_cust")
    spark.createDataFrame(pa.table({
        "cust": ["a", "a", "b"], "amt": [5, 7, 3]})) \
        .createOrReplaceTempView("ex_ords")
    out = q(spark, """SELECT cid, cid IN (SELECT cust FROM ex_ords) AS has
                      FROM ex_cust ORDER BY cid""")
    assert out["has"] == [True, True, False]
    out = q(spark, """SELECT cid,
                EXISTS(SELECT 1 FROM ex_ords WHERE cust = cid) AS e
                      FROM ex_cust ORDER BY cid""")
    assert out["e"] == [True, True, False]
    out = q(spark, """SELECT cid,
                cid NOT IN (SELECT cust FROM ex_ords) AS miss
                      FROM ex_cust ORDER BY cid""")
    assert out["miss"] == [False, False, True]
    # uncorrelated EXISTS broadcasts one flag
    out = q(spark, """SELECT cid,
                EXISTS(SELECT 1 FROM ex_ords WHERE amt > 6) AS big
                      FROM ex_cust ORDER BY cid""")
    assert out["big"] == [True, True, True]


def test_residual_correlation_below_aggregate_rejected(spark):
    # pulling a correlated non-equality predicate from BELOW an aggregate
    # would change the aggregate's input — must fail loudly, not silently
    # mis-execute (code-review r2 finding)
    import pyarrow as pa
    import pytest

    from spark_tpu.errors import UnsupportedOperationError

    spark.createDataFrame(pa.table({"x": [5], "w": [3]})) \
        .createOrReplaceTempView("rcba_o")
    spark.createDataFrame(pa.table({"a": [5, 9], "w2": [1, 2]})) \
        .createOrReplaceTempView("rcba_t")
    with pytest.raises(UnsupportedOperationError):
        spark.sql("""select x from rcba_o o where x in
                     (select max(a) from rcba_t t where t.w2 <> o.w)""") \
            .toArrow()


def test_residual_correlated_exists(spark):
    # the q16 shape: equality + non-equality correlated EXISTS
    import pyarrow as pa

    spark.createDataFrame(pa.table({"o": [1, 1, 2], "w": [10, 11, 20]})) \
        .createOrReplaceTempView("rce_s")
    out = spark.sql("""select distinct o from rce_s s1 where exists
                       (select * from rce_s s2 where s1.o = s2.o
                        and s1.w <> s2.w) order by o""").toArrow()
    assert out.to_pydict()["o"] == [1]
