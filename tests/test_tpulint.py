"""tpulint source-lint pass (spark_tpu/analysis/lint.py): rule detection,
pragma suppression, the memoized-wrapper exemption, baseline semantics —
plus the tier-1 CI gate: the repo must be clean against its checked-in
baseline (AST only, no device work)."""

import json
import os
import subprocess
import sys

from spark_tpu.analysis import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HOT = "spark_tpu/physical/fake_op.py"        # hot-path module path
COLD = "spark_tpu/api/fake_api.py"           # not a hot path


def _rules(src, relpath=HOT, keys=frozenset()):
    return [(v.rule, v.line) for v in
            lint.lint_source(src, relpath, registered_keys=set(keys))]


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

def test_item_flagged_on_hot_path():
    src = "def f(x):\n    return x.item()\n"
    assert ("host-sync", 2) in _rules(src)
    assert _rules(src, relpath=COLD) == []  # not a hot path


def test_np_asarray_and_casts_flagged():
    src = ("import numpy as np\n"
           "def f(col, d):\n"
           "    a = np.asarray(col.data)\n"
           "    n = int(d.sum())\n"
           "    return a, n\n")
    rules = [r for r, _ in _rules(src)]
    assert rules.count("host-sync") == 2


def test_block_until_ready_flagged_everywhere():
    src = "def f(x):\n    x.block_until_ready()\n"
    assert ("host-sync", 2) in _rules(src, relpath=COLD)


def test_memoized_wrapper_exempts_host_sync():
    src = ("import numpy as np\n"
           "def rng(col, mask):\n"
           "    def compute():\n"
           "        return int(np.asarray(col.data)[mask].min())\n"
           "    return memo_device_scalars(('r',), (col.data,), compute)\n")
    assert _rules(src) == []
    lam = ("def rng(col, d):\n"
           "    return memo_device_scalars(('r',), (col.data,),\n"
           "                               lambda: int(d.min()))\n")
    assert _rules(lam) == []


def test_memo_exemption_limited_to_the_closure():
    """A sync OUTSIDE the compute closure is still per-call — flagged even
    though the same function also calls memo_device_scalars."""
    src = ("import numpy as np\n"
           "def rng(col, mask, batch):\n"
           "    n = int(batch.row_mask.sum())\n"
           "    def compute():\n"
           "        return int(np.asarray(col.data)[mask].min())\n"
           "    return memo_device_scalars(('r', n), (col.data,), compute)\n")
    assert [(r, ln) for r, ln in _rules(src)] == [("host-sync", 3)]


def test_pragma_suppresses_rule():
    src = ("def f(x):\n"
           "    return x.item()  # tpulint: ignore[host-sync]\n")
    assert _rules(src) == []
    src2 = ("def f(x):\n"
            "    # tpulint: ignore\n"
            "    return x.item()\n")
    assert _rules(src2) == []
    src3 = ("def f(x):\n"
            "    return x.item()  # tpulint: ignore[raw-jit]\n")
    assert ("host-sync", 2) in _rules(src3)  # wrong rule listed


def test_trailing_pragma_does_not_leak_to_next_line():
    src = ("def f(x, y):\n"
           "    a = x.item()  # tpulint: ignore[host-sync]\n"
           "    b = y.item()\n"
           "    return a, b\n")
    assert _rules(src) == [("host-sync", 3)]
    # a comment-only pragma still covers the following statement
    src2 = ("def f(x):\n"
            "    # tpulint: ignore[host-sync]\n"
            "    return x.item()\n")
    assert _rules(src2) == []


# ---------------------------------------------------------------------------
# row-loop / raw-jit / config-key
# ---------------------------------------------------------------------------

def test_row_loop_flagged_in_kernel_dirs():
    src = ("def f(batch):\n"
           "    for i in range(batch.num_rows):\n"
           "        pass\n")
    assert ("row-loop", 2) in _rules(src)
    assert _rules(src, relpath="spark_tpu/ml/fake.py") == []


def test_raw_jit_flagged_unless_cached():
    src = ("import jax\n"
           "def f():\n"
           "    return jax.jit(lambda x: x)\n")
    assert ("raw-jit", 3) in _rules(src)
    cached = ("import jax\n"
              "def op(cache):\n"
              "    def build():\n"
              "        return jax.jit(lambda x: x)\n"
              "    return cache.get_or_build(('k',), build)\n")
    assert _rules(cached) == []
    # module-level builder referenced from a get_or_build call site
    helper = ("import jax\n"
              "def _kern():\n"
              "    return jax.jit(lambda x: x)\n"
              "def op(cache):\n"
              "    return cache.get_or_build(('k',), lambda: _kern())\n")
    assert _rules(helper) == []


def test_config_key_requires_registration():
    src = "def f(conf):\n    return conf.get('spark.tpu.made.up', 1)\n"
    assert ("config-key", 2) in _rules(src)
    assert _rules(src, keys={"spark.tpu.made.up"}) == []


def test_registry_collects_config_entries(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text("X = _register(ConfigEntry('spark.tpu.some.key', 1,\n"
                   "    'doc', int))\n")
    assert lint.registered_config_keys(str(tmp_path)) == \
        {"spark.tpu.some.key"}


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def test_baseline_blocks_only_new_violations(tmp_path):
    v1 = lint.lint_source("def f(x):\n    return x.item()\n", HOT,
                          registered_keys=set())
    path = tmp_path / "base.json"
    lint.write_baseline(str(path), v1)
    baseline = lint.load_baseline(str(path))
    assert lint.new_violations(v1, baseline) == []
    v2 = lint.lint_source(
        "def f(x):\n    return x.item()\ndef g(y):\n    return y.item()\n",
        HOT, registered_keys=set())
    extra = lint.new_violations(v2, baseline)
    assert len(extra) == 1 and extra[0].rule == "host-sync"


# ---------------------------------------------------------------------------
# CI gate: the repo itself must be clean against its baseline
# ---------------------------------------------------------------------------

def test_repo_clean_against_checked_in_baseline():
    violations = lint.lint_paths([os.path.join(REPO, "spark_tpu")],
                                 repo_root=REPO)
    baseline = lint.load_baseline(
        os.path.join(REPO, "dev", "tpulint_baseline.json"))
    offending = lint.new_violations(violations, baseline)
    msg = "\n".join(str(v) for v in offending[:20])
    assert not offending, (
        f"tpulint found NEW violations beyond dev/tpulint_baseline.json "
        f"(fix them, suppress with '# tpulint: ignore[rule]' where "
        f"justified, or regenerate the baseline via "
        f"`python dev/tpulint.py --write-baseline`):\n{msg}")


def test_no_unregistered_config_keys_at_all():
    """config-key debt is fully paid: single source of truth holds."""
    violations = lint.lint_paths([os.path.join(REPO, "spark_tpu")],
                                 repo_root=REPO)
    bad = [v for v in violations if v.rule == "config-key"]
    assert not bad, "\n".join(str(v) for v in bad)


def test_cli_runs_clean_and_fails_on_new(tmp_path):
    cli = os.path.join(REPO, "dev", "tpulint.py")
    r = subprocess.run(
        [sys.executable, cli, os.path.join(REPO, "spark_tpu"),
         "--baseline", os.path.join(REPO, "dev", "tpulint_baseline.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    # a file with a fresh violation and no baseline → exit 1 + json output
    bad = tmp_path / "spark_tpu" / "ops" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f(x):\n    return x.item()\n")
    r = subprocess.run(
        [sys.executable, cli, str(tmp_path / "spark_tpu"),
         "--format", "json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    data = json.loads(r.stdout)
    assert data["total"] == 1 and data["new"][0]["rule"] == "host-sync"
