"""The real TPC-DS q1–q99 suite as the regression gate.

Role of the reference's TPCDSQueryTestSuite
(sql/core/src/test/scala/org/apache/spark/sql/TPCDSQueryTestSuite.scala):
every benchmark query executes over deterministic generated data
(tests/tpcds/datagen.py, the GenTPCDSData analog) and its full sorted
result is checked against a committed golden file produced by an
INDEPENDENT engine (sqlite — tests/tpcds/oracle.py), the analog of the
committed tpcds-query-results.

Regenerate goldens (after datagen/oracle changes):
    SPARK_TPU_REGEN_TPCDS=1 python -m pytest tests/test_tpcds_full.py -q

ROLLUP/GROUPING() queries are oracle-verified too: the rewrite layer
expands `GROUP BY ROLLUP` into the UNION ALL of grouping-set branches
sqlite can run (tests/tpcds/oracle.py expand_rollup).
"""

from __future__ import annotations

import glob
import json
import os
import signal

import pytest

HERE = os.path.dirname(__file__)
QUERY_DIR = os.path.join(HERE, "tpcds", "queries")
GOLDEN_DIR = os.path.join(HERE, "tpcds", "expected")
SCALE = 0.1
REGEN = os.environ.get("SPARK_TPU_REGEN_TPCDS") == "1"

# empty since r4: ROLLUP queries verify via expand_rollup, q64 runs via
# CTE materialization (plan/logical.py WithCTE)
EXEC_ONLY: set[str] = set()
SKIP: dict[str, str] = {}

ALL_QUERIES = sorted(
    os.path.basename(f)[:-4]
    for f in glob.glob(os.path.join(QUERY_DIR, "q*.sql")))

PER_QUERY_TIMEOUT = int(os.environ.get("SPARK_TPU_TPCDS_TIMEOUT", "240"))


def _norm_rows(table):
    """Engine arrow table → normalized sorted row list (shared shape with
    the oracle's normalization)."""
    from tests.tpcds.oracle import _norm_cell, _sort_key

    cols = [table.column(i).to_pylist() for i in range(table.num_columns)]
    rows = [tuple(_norm_cell(c) for c in r) for r in zip(*cols)] \
        if cols else []
    return sorted(rows, key=_sort_key)


@pytest.fixture(scope="session")
def tpcds(spark):
    from tests.tpcds.datagen import gen_tpcds_full

    tables = gen_tpcds_full(scale=SCALE)
    for name, tab in tables.items():
        spark.createDataFrame(tab).createOrReplaceTempView(name)
    yield {"spark": spark, "tables": tables}


class _QueryTimeout(Exception):
    pass


def _alarm(signum, frame):
    raise _QueryTimeout()


@pytest.mark.tpcds
@pytest.mark.parametrize("qname", ALL_QUERIES)
def test_tpcds_query(tpcds, qname):
    if qname in SKIP:
        pytest.skip(SKIP[qname])
    from tests.tpcds.oracle import strip_trailing_limit

    spark = tpcds["spark"]
    sql = strip_trailing_limit(
        open(os.path.join(QUERY_DIR, f"{qname}.sql")).read())

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(PER_QUERY_TIMEOUT)
    try:
        result = spark.sql(sql).toArrow()
    except _QueryTimeout:
        pytest.fail(f"{qname}: exceeded {PER_QUERY_TIMEOUT}s")
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
    rows = _norm_rows(result)

    golden_path = os.path.join(GOLDEN_DIR, f"{qname}.json")
    if REGEN:
        if qname in EXEC_ONLY:
            payload = {"tier": "exec", "num_rows": len(rows),
                       "num_cols": result.num_columns,
                       "rows": [list(r) for r in rows]}
        else:
            from tests.tpcds.datagen import gen_tpcds_full
            from tests.tpcds.oracle import (
                load_sqlite, rewrite_for_sqlite,
            )

            conn = _oracle_conn(tpcds)
            osql = rewrite_for_sqlite(sql, qname)
            orows = conn.execute(osql).fetchall()
            from tests.tpcds.oracle import _norm_cell, _sort_key

            orows = sorted(
                [tuple(_norm_cell(c) for c in r) for r in orows],
                key=_sort_key)
            payload = {"tier": "oracle", "num_rows": len(orows),
                       "num_cols": len(orows[0]) if orows else
                       result.num_columns,
                       "rows": [list(r) for r in orows]}
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(golden_path, "w") as f:
            json.dump(payload, f)

    if not os.path.exists(golden_path):
        pytest.skip(f"{qname}: no golden (regen with "
                    "SPARK_TPU_REGEN_TPCDS=1)")
    golden = json.load(open(golden_path))
    expected = [tuple(r) for r in golden["rows"]]

    from tests.tpcds.oracle import compare_rows

    ok, msg = compare_rows(rows, expected)
    label = "oracle" if golden["tier"] == "oracle" else "exec-tier pin"
    assert ok, f"{qname} vs {label}: {msg}"


def _oracle_conn(tpcds_env):
    if "_oracle" not in tpcds_env:
        from tests.tpcds.oracle import load_sqlite

        tpcds_env["_oracle"] = load_sqlite(tpcds_env["tables"])
    return tpcds_env["_oracle"]
