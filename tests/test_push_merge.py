"""Push-merge (magnet) shuffle: server-side merge of pushed blocks per
reduce partition, MergeStatus, merged-chunk-first fetch with per-block
fallback (reference: common/network-shuffle RemoteBlockPushResolver.java:97,
core/scheduler/MergeStatus.scala, ShuffleBlockFetcherIterator merged-chunk
read path)."""

import os
import pickle

import pytest

from spark_tpu.exec.map_output import fetch_merged
from spark_tpu.exec.shuffle_service import ExternalShuffleService, merged_path
from spark_tpu.net.transport import RpcClient

TOKEN = "deadbeef" * 4


@pytest.fixture()
def service(tmp_path):
    svc = ExternalShuffleService(str(tmp_path), TOKEN)
    addr = svc.start()
    client = RpcClient(addr, TOKEN)
    client.wait_ready(10)
    try:
        yield svc, client, str(tmp_path)
    finally:
        client.close()
        svc.stop()


def _push(client, sid, map_id, rid, data) -> bytes:
    return client.call("push_block",
                       pickle.dumps((sid, map_id, rid, data)), timeout=10)


def test_merge_appends_and_finalize_reports_map_ids(service):
    _, client, _ = service
    assert _push(client, "s1", 0, 0, b"aaa") == b"ok"
    assert _push(client, "s1", 1, 0, b"bbbb") == b"ok"
    assert _push(client, "s1", 1, 1, b"cc") == b"ok"
    merged = pickle.loads(
        client.call("finalize_merge", pickle.dumps("s1"), timeout=10))
    assert merged == {0: (0, 1), 1: (1,)}


def test_duplicate_push_is_deduped(service):
    """Speculative duplicates of a map task push byte-identical blocks;
    the merger keeps the first and reports 'dup' (the reference's
    deterministic-dedup by map index)."""
    _, client, _ = service
    assert _push(client, "s2", 0, 0, b"xyz") == b"ok"
    assert _push(client, "s2", 0, 0, b"xyz") == b"dup"
    got = fetch_merged(client, "s2", 0)
    assert got == [(0, b"xyz")]


def test_late_push_after_finalize_is_dropped(service):
    _, client, _ = service
    assert _push(client, "s3", 0, 0, b"early") == b"ok"
    client.call("finalize_merge", pickle.dumps("s3"), timeout=10)
    assert _push(client, "s3", 1, 0, b"late") == b"late"
    got = fetch_merged(client, "s3", 0)
    assert got == [(0, b"early")]  # late block never entered the chunk


def test_fetch_merged_splits_frames_in_push_order(service):
    _, client, _ = service
    _push(client, "s4", 2, 5, b"11")
    _push(client, "s4", 0, 5, b"222")
    _push(client, "s4", 1, 5, b"3")
    got = fetch_merged(client, "s4", 5)
    assert got == [(2, b"11"), (0, b"222"), (1, b"3")]


def test_fetch_merged_detects_truncated_chunk(service):
    """A merged chunk whose bytes disagree with its index must read as
    missing (→ per-map fallback), never as silently-wrong data."""
    _, client, root = service
    _push(client, "s5", 0, 0, b"payload-one")
    _push(client, "s5", 1, 0, b"payload-two")
    path = merged_path(root, "s5", 0)
    with open(path, "rb") as f:
        raw = f.read()
    with open(path, "wb") as f:
        f.write(raw[:-3])  # truncate
    assert fetch_merged(client, "s5", 0) is None


def test_fetch_merged_missing_chunk(service):
    _, client, _ = service
    assert fetch_merged(client, "nope", 0) is None


def test_free_shuffle_removes_merged_state(service):
    _, client, root = service
    _push(client, "s6", 0, 0, b"live")
    assert os.path.exists(merged_path(root, "s6", 0))
    client.call("free_shuffle", pickle.dumps("s6"), timeout=10)
    assert not os.path.exists(merged_path(root, "s6", 0))
    assert fetch_merged(client, "s6", 0) is None


# ---------------------------------------------------------------------------
# End-to-end: multi-map-task stages + merged-chunk-only recovery
# ---------------------------------------------------------------------------

def test_sliced_map_tasks_correct_results():
    """mapParallelism=2 splits eligible map stages into two map tasks on
    different executors; results must match the single-mapper plan and
    the map-task metric must show the split happened."""
    import collections

    import numpy as np
    import pyarrow as pa

    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("sliced", {"spark.sql.shuffle.partitions": "4",
                              "spark.tpu.shuffle.mapParallelism": "2"})
    cluster = LocalCluster(num_workers=2)
    s.attachSqlCluster(cluster)
    try:
        n = 5000
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 40, n)
        s.createDataFrame(pa.table({
            "k": keys, "v": rng.integers(1, 6, n)})) \
            .createOrReplaceTempView("slfact")
        # scan→repartition (stage 1, scan leaf → 1 mapper), then
        # Fetch(4)→partial-agg→hash exchange (stage 2, SLICED → 2 mappers)
        df = s.table("slfact").repartition(4).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        assert got == dict(collections.Counter(keys.tolist()))
        m = s._metrics.snapshot()["counters"]
        assert m.get("scheduler.map_tasks", 0) >= 3, m  # 1 + 2
    finally:
        s.stop()


def test_reducers_complete_from_merged_chunks_after_all_mappers_die():
    """The magnet durability contract: after every map stage finished
    and its merge finalized, ALL executors die — the reduce (result)
    stage must still complete, from the service's merged chunks alone
    (no per-map fallback exists: every origin worker is gone, and push
    mode shares no filesystem with the workers)."""
    import collections

    import numpy as np
    import pyarrow as pa

    import spark_tpu.exec.cluster_sql as CS
    from spark_tpu.api.session import TpuSession
    from spark_tpu.exec.cluster import LocalCluster

    s = TpuSession("magnet", {"spark.sql.shuffle.partitions": "3",
                              "spark.tpu.shuffle.mapParallelism": "2"})
    cluster = LocalCluster(num_workers=2, push_shuffle=True)
    s.attachSqlCluster(cluster)

    calls = {"n": 0}
    orig = CS.ClusterDAGScheduler._run_remote

    def kill_all_after_last_map(self, stage):
        status = orig(self, stage)
        calls["n"] += 1
        if calls["n"] == 2:  # repartition stage + group-by map stage
            for w in list(cluster._workers.values()):
                if w.proc is not None:
                    w.proc.kill()
                    w.proc.wait(timeout=10)
        return status

    CS.ClusterDAGScheduler._run_remote = kill_all_after_last_map
    try:
        n = 4000
        rng = np.random.default_rng(13)
        keys = rng.integers(0, 30, n)
        s.createDataFrame(pa.table({
            "k": keys, "v": rng.integers(1, 5, n)})) \
            .createOrReplaceTempView("magfact")
        df = s.table("magfact").repartition(3).groupBy("k").count()
        got = {r["k"]: r["count"] for r in df.collect()}
        assert got == dict(collections.Counter(keys.tolist()))
        assert calls["n"] == 2, calls
        m = s._metrics.snapshot()["counters"]
        assert m.get("scheduler.fetch_failures", 0) == 0, m
        # all three reduce partitions came from merged chunks
        assert m.get("shuffle.merged_chunks_fetched", 0) >= 3, m
        # the split really happened: stage 2 ran as two map tasks
        assert m.get("scheduler.map_tasks", 0) >= 3, m
    finally:
        CS.ClusterDAGScheduler._run_remote = orig
        s.stop()
