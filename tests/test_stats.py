"""Statistics framework tests (reference: StatisticsCollectionSuite,
FilterEstimationSuite, JoinEstimationSuite in sql/catalyst tests)."""

import numpy as np
import pyarrow as pa
import pytest


def test_analyze_table_collects_column_stats(spark):
    t = pa.table({"k": [1, 2, 2, 3, None], "s": ["a", "b", "b", "c", "c"]})
    spark.createDataFrame(t).createOrReplaceTempView("stats_t")
    out = spark.sql(
        "ANALYZE TABLE stats_t COMPUTE STATISTICS FOR ALL COLUMNS"
    ).toArrow()
    assert out.column("rows")[0].as_py() == 5
    st = spark._table_stats["stats_t"]
    assert st.row_count == 5
    ks = st.col_stats["k"]
    assert ks.distinct_count == 3 and ks.null_count == 1
    assert ks.min == 1 and ks.max == 3


def test_filter_estimation_uses_stats(spark):
    from spark_tpu.plan.stats import estimate

    n = 1000
    t = pa.table({"x": np.arange(n), "k": np.arange(n) % 10})
    spark.createDataFrame(t).createOrReplaceTempView("est_t")
    spark.sql("ANALYZE TABLE est_t COMPUTE STATISTICS FOR ALL COLUMNS")
    plan = spark.sql("SELECT * FROM est_t WHERE x < 100").query_execution \
        .analyzed
    st = estimate(plan)
    assert st.row_count is not None
    # range selectivity ~10%, generous tolerance
    assert 50 <= st.row_count <= 200
    plan_eq = spark.sql("SELECT * FROM est_t WHERE k = 3") \
        .query_execution.analyzed
    st_eq = estimate(plan_eq)
    assert 50 <= st_eq.row_count <= 200  # 1/ndv(k)=1/10


def test_join_estimation_divides_by_ndv(spark):
    from spark_tpu.plan.stats import estimate

    fact = pa.table({"fk": np.arange(1000) % 50, "v": np.ones(1000)})
    dim = pa.table({"pk": np.arange(50), "name": [f"n{i}" for i in range(50)]})
    spark.createDataFrame(fact).createOrReplaceTempView("est_fact")
    spark.createDataFrame(dim).createOrReplaceTempView("est_dim")
    spark.sql("ANALYZE TABLE est_fact COMPUTE STATISTICS FOR ALL COLUMNS")
    spark.sql("ANALYZE TABLE est_dim COMPUTE STATISTICS FOR ALL COLUMNS")
    plan = spark.sql(
        "SELECT * FROM est_fact JOIN est_dim ON fk = pk"
    ).query_execution.analyzed
    st = estimate(plan)
    # 1000 * 50 / ndv(50) = 1000
    assert 500 <= st.row_count <= 2000


def test_cbo_join_reorder_prefers_selective_path(spark):
    """Three-table chain where the cheap-looking middle table explodes
    without ndv information: with ANALYZE'd stats the reorder keeps the
    high-ndv key join first (CostBasedJoinReorder role)."""
    rng = np.random.default_rng(0)
    n = 2000
    # fact: unique id (high ndv), low-ndv tag
    fact = pa.table({"id": np.arange(n), "tag": rng.integers(0, 3, n)})
    # ids: 1:1 on id (joins to 2000 rows)
    ids = pa.table({"id2": np.arange(n), "w": rng.random(n)})
    # tags: 500 rows per tag value (joins to n*500 rows if taken first!)
    tags = pa.table({"tag2": np.repeat(np.arange(3), 500),
                     "label": ["t"] * 1500})
    spark.createDataFrame(fact).createOrReplaceTempView("cbo_fact")
    spark.createDataFrame(ids).createOrReplaceTempView("cbo_ids")
    spark.createDataFrame(tags).createOrReplaceTempView("cbo_tags")
    for t in ("cbo_fact", "cbo_ids", "cbo_tags"):
        spark.sql(f"ANALYZE TABLE {t} COMPUTE STATISTICS FOR ALL COLUMNS")
    df = spark.sql(
        "SELECT count(*) AS c FROM cbo_fact, cbo_ids, cbo_tags "
        "WHERE id = id2 AND tag = tag2")
    # plan shape: the id=id2 join (output 2000) must come before the
    # tag=tag2 join (output 1M if first)
    txt = df.query_execution.optimized.tree_string()
    joins = [l for l in txt.splitlines() if "Join" in l]
    assert len(joins) == 2
    # deeper (later in tree_string) join is executed FIRST — it must be
    # the id join
    assert "id" in joins[-1] and "tag2" not in joins[-1], txt
    # and the result is right
    assert df.toArrow().column("c")[0].as_py() == 2000 * 500
