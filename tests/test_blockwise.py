"""Blockwise aggregation, TopK, and skew-split tests."""

import numpy as np
import pyarrow as pa
import pytest

import spark_tpu.api.functions as F
from spark_tpu.physical.adaptive import split_skewed_join_inputs


def test_blockwise_agg_matches(spark):
    # force tiny block threshold → incremental fold path
    spark.conf.set("spark.tpu.agg.blockRows", 1 << 12)
    spark.conf.set("spark.tpu.batch.capacity", 1 << 10)
    try:
        df = spark.range(0, 20_000, 1, 1)
        out = (df.groupBy((F.col("id") % 7).alias("m"))
               .agg(F.sum("id").alias("s"), F.count("*").alias("c"),
                    F.min("id").alias("mn"), F.max("id").alias("mx"))
               .orderBy("m").toArrow().to_pydict())
        want_s = [sum(x for x in range(20_000) if x % 7 == m)
                  for m in range(7)]
        assert out["s"] == want_s
        assert sum(out["c"]) == 20_000
        assert out["mn"] == list(range(7))
    finally:
        spark.conf.unset("spark.tpu.agg.blockRows")
        spark.conf.set("spark.tpu.batch.capacity", 1 << 12)


def test_topk_plan_and_result(spark):
    df = spark.range(0, 10_000, 1, 8)
    q = df.orderBy(F.col("id").desc()).limit(5)
    plan_str = q.query_execution.physical.tree_string()
    # TopK: local sort+limit below the gather exchange
    assert "Sort" in plan_str and "Limit" in plan_str
    out = q.toArrow().to_pydict()
    assert out["id"] == [9999, 9998, 9997, 9996, 9995]


def test_topk_with_ties_and_offset(spark):
    df = spark.createDataFrame(pa.table({"v": [5, 1, 5, 3, 2, 5]}))
    out = df.orderBy(F.col("v").desc()).limit(4).toArrow().to_pydict()
    assert out["v"] == [5, 5, 5, 3]


def test_skew_split_shapes(spark):
    from spark_tpu.exec.context import ExecContext

    ctx = ExecContext(conf=spark.conf)
    mk = lambda n: [_fake_batch(spark, 100) for _ in range(n)]
    left = [mk(8), mk(1), mk(1)]   # partition 0 is 8x the median
    right = [mk(1), mk(1), mk(1)]
    l2, r2 = split_skewed_join_inputs(left, right, ctx, "inner")
    assert len(l2) == len(r2)
    assert len(l2) > 3              # partition 0 split
    assert sum(len(p) for p in l2) == sum(len(p) for p in left)
    # build side duplicated alongside its probe splits
    assert r2.count(right[0]) >= 2


def _fake_batch(spark, n):
    from spark_tpu.columnar.batch import ColumnarBatch
    from spark_tpu.types import StructField, StructType, int64

    schema = StructType([StructField("x", int64, False)])
    return ColumnarBatch.from_numpy(schema, [np.arange(n)])


def test_skewed_join_correct(spark):
    spark.conf.set("spark.sql.autoBroadcastJoinThreshold", -1)
    spark.conf.set("spark.tpu.batch.capacity", 1 << 10)
    try:
        # key 0 is heavily skewed
        n = 8000
        keys = [0] * (n // 2) + list(range(1, n // 2 + 1))
        a = spark.createDataFrame(pa.table({"k": keys,
                                            "v": list(range(n))}))
        b = spark.createDataFrame(pa.table({"k": list(range(100)),
                                            "w": list(range(100))}))
        out = (a.join(b, on="k")
               .agg(F.count("*").alias("c")).toArrow().to_pydict())
        want = sum(1 for k in keys if 0 <= k < 100)
        assert out["c"] == [want]
    finally:
        spark.conf.unset("spark.sql.autoBroadcastJoinThreshold")
        spark.conf.set("spark.tpu.batch.capacity", 1 << 12)
