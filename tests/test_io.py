"""IO tests: parquet round trips, partitioned layout, csv/json."""

import os
import tempfile

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_tpu.api.functions as F


def test_parquet_roundtrip(spark, tmp_path):
    p = str(tmp_path / "t.parquet")
    df = spark.createDataFrame(pa.table({
        "a": [1, 2, 3], "s": ["x", "y", "z"]}))
    df.write.parquet(p)
    back = spark.read.parquet(p)
    assert back.orderBy("a").toArrow().to_pydict() == \
        {"a": [1, 2, 3], "s": ["x", "y", "z"]}


def test_parquet_partitioned_write_read(spark, tmp_path):
    p = str(tmp_path / "part")
    df = spark.createDataFrame(pa.table({
        "k": ["a", "a", "b"], "year": [2020, 2021, 2020],
        "v": [1.0, 2.0, 3.0]}))
    df.write.partitionBy("k", "year").parquet(p)
    assert os.path.isdir(os.path.join(p, "k=a", "year=2020"))

    back = spark.read.parquet(p)
    assert set(back.columns) == {"v", "k", "year"}
    out = back.orderBy("v").toArrow().to_pydict()
    assert out["k"] == ["a", "a", "b"]
    assert out["year"] == [2020, 2021, 2020]

    # partition pruning predicate works on reconstructed columns
    assert back.filter(F.col("year") == 2020).count() == 2


def test_parquet_column_pruning_pushdown(spark, tmp_path):
    p = str(tmp_path / "w.parquet")
    spark.createDataFrame(pa.table({
        "a": list(range(100)), "b": list(range(100)),
        "c": list(range(100))})).write.parquet(p)
    df = spark.read.parquet(p).select("a")
    plan = df.query_execution.physical.tree_string()
    assert "b" not in plan  # scan narrowed
    assert df.count() == 100


def test_csv_roundtrip(spark, tmp_path):
    p = str(tmp_path / "t.csv")
    spark.createDataFrame(pa.table({"x": [1, 2], "y": ["p", "q"]})) \
        .write.csv(p)
    back = spark.read.csv(p)
    assert back.orderBy("x").toArrow().to_pydict() == \
        {"x": [1, 2], "y": ["p", "q"]}


def test_json_write_read(spark, tmp_path):
    p = str(tmp_path / "t.json")
    spark.createDataFrame(pa.table({"x": [1, 2]})).write.json(p)
    back = spark.read.json(p)
    assert sorted(back.toArrow().to_pydict()["x"]) == [1, 2]


def test_write_modes(spark, tmp_path):
    from spark_tpu.errors import AnalysisException

    p = str(tmp_path / "m.parquet")
    df = spark.createDataFrame(pa.table({"x": [1]}))
    df.write.parquet(p)
    with pytest.raises(AnalysisException):
        df.write.parquet(p)  # errorifexists
    df.write.mode("ignore").parquet(p)
    spark.createDataFrame(pa.table({"x": [9]})).write.mode("overwrite") \
        .parquet(p)
    assert spark.read.parquet(p).toArrow().to_pydict()["x"] == [9]


# ---------------------------------------------------------------------------
# Commit protocol + ORC + JDBC (r4)
# ---------------------------------------------------------------------------

def test_commit_coordinator_exactly_one_winner():
    """Two attempts of the same task race the coordinator from many
    threads; exactly one commits, the loser aborts and leaves no files
    (reference: OutputCommitCoordinator.scala + TaskCommitDenied)."""
    import threading

    from spark_tpu.io.commit import (
        CommitDeniedError, FileCommitProtocol,
    )

    d = tempfile.mkdtemp(prefix="sparktpu-commit-")
    out = os.path.join(d, "out")
    os.makedirs(out)
    proto = FileCommitProtocol(out)
    proto.setup_job()

    results = []

    def attempt(tag):
        att = proto.new_task_attempt(task_id=0)
        with open(att.path_for("part-00000.txt"), "w") as f:
            f.write(tag)
        try:
            att.commit()
            results.append(("committed", tag))
        except CommitDeniedError:
            results.append(("denied", tag))

    threads = [threading.Thread(target=attempt, args=(f"a{i}",))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    proto.commit_job()
    assert sum(1 for s, _ in results if s == "committed") == 1
    assert sum(1 for s, _ in results if s == "denied") == 7
    winner = next(tag for s, tag in results if s == "committed")
    assert open(os.path.join(out, "part-00000.txt")).read() == winner
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    assert not os.path.exists(os.path.join(out, "_temporary"))


def test_partitioned_write_commits_atomically(spark):
    d = tempfile.mkdtemp(prefix="sparktpu-io-")
    p = os.path.join(d, "part_out")
    df = spark.createDataFrame(pa.table({
        "k": [1, 1, 2, 2, 3], "v": [10.0, 11.0, 20.0, 21.0, 30.0]}))
    df.write.partitionBy("k").parquet(p)
    assert os.path.exists(os.path.join(p, "_SUCCESS"))
    assert not os.path.exists(os.path.join(p, "_temporary"))
    back = spark.read.parquet(p).toArrow()
    assert sorted(back.column("v").to_pylist()) == [10.0, 11.0, 20.0,
                                                    21.0, 30.0]


def test_orc_roundtrip(spark):
    d = tempfile.mkdtemp(prefix="sparktpu-io-")
    p = os.path.join(d, "t.orc")
    t = pa.table({"a": [1, 2, 3], "b": ["x", "y", None],
                  "c": [1.5, None, 3.5]})
    spark.createDataFrame(t).write.orc(p)
    back = spark.read.orc(p)
    assert back.toArrow().to_pydict() == t.to_pydict()
    # SQL over an ORC scan with projection pushdown
    back.createOrReplaceTempView("orc_t")
    out = spark.sql("SELECT a FROM orc_t WHERE c > 1").toArrow()
    assert sorted(out.column("a").to_pylist()) == [1, 3]


def test_orc_partitioned_write_and_format_load(spark):
    d = tempfile.mkdtemp(prefix="sparktpu-io-")
    p = os.path.join(d, "orc_parts")
    spark.createDataFrame(pa.table({
        "k": ["a", "a", "b"], "v": [1, 2, 3]})) \
        .write.partitionBy("k").orc(p)
    assert os.path.exists(os.path.join(p, "_SUCCESS"))
    back = spark.read.format("orc").load(p).toArrow()
    assert sorted(back.column("v").to_pylist()) == [1, 2, 3]


def test_jdbc_read_partitioned(spark):
    import sqlite3

    d = tempfile.mkdtemp(prefix="sparktpu-io-")
    db = os.path.join(d, "db.sqlite")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE emp (id INTEGER, name TEXT, sal REAL)")
    conn.executemany("INSERT INTO emp VALUES (?,?,?)",
                     [(i, f"e{i}", 100.0 * i) for i in range(50)])
    conn.commit()
    conn.close()

    df = (spark.read.format("jdbc")
          .option("url", f"jdbc:sqlite:{db}")
          .option("dbtable", "emp")
          .option("partitionColumn", "id")
          .option("numPartitions", "4")
          .load())
    assert df.count() == 50
    out = spark.createDataFrame(pa.table({"id": [1, 2]})) \
        .join(df, "id").toArrow()
    assert sorted(out.column("sal").to_pylist()) == [100.0, 200.0]


def test_tpcds_q3_from_orc(spark, tmp_path):
    """TPC-DS runs from ORC files (VERDICT r3 item 6 'the TPC-DS suite
    loading from ORC')."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tests.tpcds.datagen import _Gen
    from tests.tpcds.oracle import strip_trailing_limit

    g = _Gen(0.1, 17)
    for t in ("date_dim", "time_dim", "item", "customer_address",
              "customer_demographics", "household_demographics",
              "income_band", "customer", "store", "warehouse",
              "ship_mode", "reason", "call_center", "catalog_page",
              "web_site", "web_page", "promotion", "store_sales"):
        getattr(g, t)()
    q3 = strip_trailing_limit(open(os.path.join(
        os.path.dirname(__file__), "tpcds", "queries", "q3.sql")).read())
    # in-memory reference result
    for n in ("date_dim", "store_sales", "item"):
        spark.createDataFrame(g.tables[n]).createOrReplaceTempView(n)
    want = spark.sql(q3).toArrow()
    # same tables through ORC files
    for n in ("date_dim", "store_sales", "item"):
        p = str(tmp_path / f"{n}.orc")
        spark.createDataFrame(g.tables[n]).write.orc(p)
        spark.read.orc(p).createOrReplaceTempView(n)
    got = spark.sql(q3).toArrow()
    assert got.num_rows == want.num_rows > 0
    assert sorted(map(str, got.to_pylist())) == \
        sorted(map(str, want.to_pylist()))


def test_text_source(spark, tmp_path):
    p = tmp_path / "lines.txt"
    p.write_text("hello world\nfoo\nbar baz\n")
    df = spark.read.text(str(p))
    assert df.toArrow().column("value").to_pylist() == \
        ["hello world", "foo", "bar baz"]
    df.createOrReplaceTempView("lines")
    out = spark.sql(
        "SELECT count(*) c FROM lines WHERE value LIKE '%o%'").toArrow()
    assert out.column("c")[0].as_py() == 2
