"""IO tests: parquet round trips, partitioned layout, csv/json."""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_tpu.api.functions as F


def test_parquet_roundtrip(spark, tmp_path):
    p = str(tmp_path / "t.parquet")
    df = spark.createDataFrame(pa.table({
        "a": [1, 2, 3], "s": ["x", "y", "z"]}))
    df.write.parquet(p)
    back = spark.read.parquet(p)
    assert back.orderBy("a").toArrow().to_pydict() == \
        {"a": [1, 2, 3], "s": ["x", "y", "z"]}


def test_parquet_partitioned_write_read(spark, tmp_path):
    p = str(tmp_path / "part")
    df = spark.createDataFrame(pa.table({
        "k": ["a", "a", "b"], "year": [2020, 2021, 2020],
        "v": [1.0, 2.0, 3.0]}))
    df.write.partitionBy("k", "year").parquet(p)
    assert os.path.isdir(os.path.join(p, "k=a", "year=2020"))

    back = spark.read.parquet(p)
    assert set(back.columns) == {"v", "k", "year"}
    out = back.orderBy("v").toArrow().to_pydict()
    assert out["k"] == ["a", "a", "b"]
    assert out["year"] == [2020, 2021, 2020]

    # partition pruning predicate works on reconstructed columns
    assert back.filter(F.col("year") == 2020).count() == 2


def test_parquet_column_pruning_pushdown(spark, tmp_path):
    p = str(tmp_path / "w.parquet")
    spark.createDataFrame(pa.table({
        "a": list(range(100)), "b": list(range(100)),
        "c": list(range(100))})).write.parquet(p)
    df = spark.read.parquet(p).select("a")
    plan = df.query_execution.physical.tree_string()
    assert "b" not in plan  # scan narrowed
    assert df.count() == 100


def test_csv_roundtrip(spark, tmp_path):
    p = str(tmp_path / "t.csv")
    spark.createDataFrame(pa.table({"x": [1, 2], "y": ["p", "q"]})) \
        .write.csv(p)
    back = spark.read.csv(p)
    assert back.orderBy("x").toArrow().to_pydict() == \
        {"x": [1, 2], "y": ["p", "q"]}


def test_json_write_read(spark, tmp_path):
    p = str(tmp_path / "t.json")
    spark.createDataFrame(pa.table({"x": [1, 2]})).write.json(p)
    back = spark.read.json(p)
    assert sorted(back.toArrow().to_pydict()["x"]) == [1, 2]


def test_write_modes(spark, tmp_path):
    from spark_tpu.errors import AnalysisException

    p = str(tmp_path / "m.parquet")
    df = spark.createDataFrame(pa.table({"x": [1]}))
    df.write.parquet(p)
    with pytest.raises(AnalysisException):
        df.write.parquet(p)  # errorifexists
    df.write.mode("ignore").parquet(p)
    spark.createDataFrame(pa.table({"x": [9]})).write.mode("overwrite") \
        .parquet(p)
    assert spark.read.parquet(p).toArrow().to_pydict()["x"] == [9]
