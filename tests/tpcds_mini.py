"""Mini TPC-DS data generator.

Role of the reference's GenTPCDSData.scala (sql/core/src/test/scala/...):
a scaled-down star schema — store_sales fact + date_dim/item/customer/store
dimensions — with TPC-DS column names so real benchmark queries run
unmodified. Deterministic via seed.
"""

from __future__ import annotations

import datetime

import numpy as np
import pyarrow as pa


def gen_tpcds(n_sales: int = 20_000, n_items: int = 200,
              n_customers: int = 500, n_stores: int = 10,
              seed: int = 42) -> dict[str, pa.Table]:
    rng = np.random.default_rng(seed)

    # date_dim: 3 years of days
    base = datetime.date(1998, 1, 1)
    n_days = 3 * 365
    dates = [base + datetime.timedelta(days=i) for i in range(n_days)]
    date_dim = pa.table({
        "d_date_sk": pa.array(range(2450000, 2450000 + n_days), pa.int32()),
        "d_date": pa.array(dates, pa.date32()),
        "d_year": pa.array([d.year for d in dates], pa.int32()),
        "d_moy": pa.array([d.month for d in dates], pa.int32()),
        "d_dom": pa.array([d.day for d in dates], pa.int32()),
        "d_qoy": pa.array([(d.month - 1) // 3 + 1 for d in dates], pa.int32()),
        "d_day_name": pa.array([d.strftime("%A") for d in dates]),
    })

    brands = [f"brand#{i % 25 + 1}" for i in range(n_items)]
    categories = ["Books", "Electronics", "Home", "Music", "Sports"]
    item = pa.table({
        "i_item_sk": pa.array(range(1, n_items + 1), pa.int32()),
        "i_item_id": pa.array([f"ITEM{i:06d}" for i in range(n_items)]),
        "i_brand_id": pa.array([i % 25 + 1 for i in range(n_items)],
                               pa.int32()),
        "i_brand": pa.array(brands),
        "i_category": pa.array([categories[i % len(categories)]
                                for i in range(n_items)]),
        "i_manufact_id": pa.array([i % 50 + 1 for i in range(n_items)],
                                  pa.int32()),
        "i_current_price": pa.array(
            np.round(rng.uniform(0.5, 100.0, n_items), 2), pa.float64()),
    })

    states = ["CA", "TX", "NY", "WA", "OR"]
    customer = pa.table({
        "c_customer_sk": pa.array(range(1, n_customers + 1), pa.int32()),
        "c_customer_id": pa.array([f"CUST{i:08d}"
                                   for i in range(n_customers)]),
        "c_birth_year": pa.array(
            rng.integers(1930, 2000, n_customers).astype(np.int32)),
        "c_state": pa.array([states[i % len(states)]
                             for i in range(n_customers)]),
    })

    store = pa.table({
        "s_store_sk": pa.array(range(1, n_stores + 1), pa.int32()),
        "s_store_id": pa.array([f"STORE{i:04d}" for i in range(n_stores)]),
        "s_state": pa.array([states[i % len(states)]
                             for i in range(n_stores)]),
        "s_number_employees": pa.array(
            rng.integers(50, 300, n_stores).astype(np.int32)),
    })

    qty = rng.integers(1, 20, n_sales).astype(np.int32)
    price = np.round(rng.uniform(0.5, 100.0, n_sales), 2)
    discount = np.round(rng.uniform(0, 0.4, n_sales), 2)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(2450000, 2450000 + n_days, n_sales)
            .astype(np.int32)),
        "ss_item_sk": pa.array(
            rng.integers(1, n_items + 1, n_sales).astype(np.int32)),
        "ss_customer_sk": pa.array(
            rng.integers(1, n_customers + 1, n_sales).astype(np.int32)),
        "ss_store_sk": pa.array(
            rng.integers(1, n_stores + 1, n_sales).astype(np.int32)),
        "ss_quantity": pa.array(qty),
        "ss_sales_price": pa.array(price, pa.float64()),
        "ss_ext_sales_price": pa.array(
            np.round(qty * price, 2), pa.float64()),
        "ss_ext_discount_amt": pa.array(
            np.round(qty * price * discount, 2), pa.float64()),
        "ss_net_profit": pa.array(
            np.round(qty * price * (0.3 - discount), 2), pa.float64()),
    })

    return {"date_dim": date_dim, "item": item, "customer": customer,
            "store": store, "store_sales": store_sales}


def register_tpcds(spark, tables: dict[str, pa.Table] | None = None):
    tables = tables or gen_tpcds()
    for name, t in tables.items():
        spark.createDataFrame(t).createOrReplaceTempView(name)
    return tables
