#!/usr/bin/env python
"""Benchmark: BASELINE.json config #1 — groupBy-sum over a 1e7-row 2-column
DataFrame (single HashAggregateExec pipeline).

Reference baseline: apache/spark AggregateBenchmark "aggregate with
randomized keys, codegen=T vectorized hashmap=T" = 75.5 M rows/s on
1× EPYC 7763 (sql/core/benchmarks/AggregateBenchmark-results.txt) — the
fastest grouped-sum configuration the reference ships.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs on whatever jax.default_backend() provides (TPU under the driver;
CPU locally). Steady-state: data is device-resident (scan cache) and
kernels are compiled on the warm-up run, matching the reference harness's
warm iterations over an in-memory source.
"""

import json
import sys
import time

import numpy as np

BASELINE_ROWS_PER_S = 75.5e6
N_ROWS = 10_000_000
N_KEYS = 1 << 20


def _device_init_alive(timeout: float = 120.0) -> bool:
    """Probe device init in a SUBPROCESS (sequential — never run two jax
    processes concurrently against the axon tunnel): if the tunnel is
    wedged, jax.devices() hangs in C and only a kill recovers, so the
    probe protects the benchmark run itself."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    import jax

    if not _device_init_alive():
        jax.config.update("jax_platforms", "cpu")
        print("bench: accelerator init unresponsive; falling back to CPU",
              file=sys.stderr)
    jax.config.update("jax_enable_x64", True)

    import pyarrow as pa

    from spark_tpu import TpuSession
    import spark_tpu.api.functions as F
    from spark_tpu.api.dataframe import DataFrame
    from spark_tpu.io.sources import InMemorySource
    from spark_tpu.plan.logical import LogicalRelation
    from spark_tpu.expr.expressions import AttributeReference

    session = TpuSession("bench", {
        # one 16M-row tile: the whole aggregation is a single fused program
        "spark.tpu.batch.capacity": 1 << 24,
        "spark.sql.shuffle.partitions": 1,
    })

    rng = np.random.default_rng(42)
    table = pa.table({
        "k": rng.integers(0, N_KEYS, N_ROWS).astype(np.int64),
        "v": rng.integers(0, 1000, N_ROWS).astype(np.int64),
    })
    source = InMemorySource(table, num_partitions=1)
    source.cache_device_batches = True
    attrs = [AttributeReference(f.name, dt, False)
             for f, dt in zip(table.schema,
                              [__import__("spark_tpu.types",
                                          fromlist=["int64"]).int64] * 2)]
    df = DataFrame(session, LogicalRelation(source, attrs, "bench"))

    def run_once() -> float:
        q = df.groupBy("k").agg(F.sum("v").alias("s"))
        t0 = time.perf_counter()
        parts = q.query_execution.execute()
        # block until device work completes
        for part in parts:
            for b in part:
                for c in b.columns:
                    c.data.block_until_ready()
        return time.perf_counter() - t0

    run_once()  # warm-up: device upload + XLA compile
    times = [run_once() for _ in range(5)]
    best = min(times)
    rate = N_ROWS / best
    print(json.dumps({
        "metric": "groupBy-sum 1e7 rows (randomized int keys, 1M groups)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(rate / BASELINE_ROWS_PER_S, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
