#!/usr/bin/env python
"""Benchmark suite: all five BASELINE.json configs on the live backend.

Prints one JSON line per config — {"metric", "value", "unit",
"vs_baseline", "hbm_gbps"?} — then a final summary line whose value is the
geometric mean of vs_baseline across configs (the driver records the last
line; the per-config lines are the evidence trail).

Reference numbers (BASELINE.md; 1× EPYC 7763, JDK 17, "Best Time"):
  #1 groupBy-sum randomized keys ....... 75.5 M rows/s
     (sql/core/benchmarks/AggregateBenchmark-results.txt)
  #2 radix sort long keys .............. 27.5 M rows/s
     (sql/core/benchmarks/SortBenchmark-results.txt:14)
  #3 shuffled hash join ................ 10.1 M rows/s
     (sql/core/benchmarks/JoinBenchmark-results.txt:73)
  #4 TPC-DS q3 / q7 / q19 SF1 .......... 252 / 595 / 361 ms
     (sql/core/benchmarks/TPCDSQueryBenchmark-results.txt:17,41,119)

Steady-state methodology matches the reference harness: data in memory
(device-resident scan cache), one warm-up run (device upload + XLA
compile), best of N timed runs. vs_baseline > 1 means faster than the
reference for every config (for wall-clock configs it is ref_ms/our_ms).
"""

import json
import math
import os
import sys
import time

import numpy as np

# Scale knob for local/CPU smoke runs: SPARK_TPU_BENCH_SCALE=0.01 shrinks
# every dataset 100×. The driver runs at 1.0 on the real chip.
SCALE = float(os.environ.get("SPARK_TPU_BENCH_SCALE", "1.0"))

# --smoke: functional gate, not a perf number. Tiny scales, forced-CPU,
# single timed run; asserts the whole suite executes (rc=0) and emits
# kernel-launch counts so dispatch-count regressions surface in CI
# (tests/test_bench_smoke.py runs this in the tier-1 pass).
SMOKE = "--smoke" in sys.argv
if SMOKE:
    sys.argv = [a for a in sys.argv if a != "--smoke"]
    SCALE = min(SCALE, 0.002)

# --analyze: before timing each config, run the static plan analyzer
# (spark_tpu/analysis/plan_lint.py) on its main query and emit one JSON
# record with the predicted per-kind launch counts — the measured
# kernel_launches delta on the same record trail is its ground truth.
ANALYZE = "--analyze" in sys.argv
if ANALYZE:
    sys.argv = [a for a in sys.argv if a != "--analyze"]

# --trace: run with span tracing + per-operator metrics ON and write a
# Perfetto/Chrome-trace JSON (obs/tracing.py) next to the results —
# SPARK_TPU_TRACE_PATH overrides the destination. dev/run_all.sh's trace
# gate loads and validates the emitted file (dev/validate_trace.py).
TRACE = "--trace" in sys.argv
if TRACE:
    sys.argv = [a for a in sys.argv if a != "--trace"]
TRACE_PATH = os.environ.get("SPARK_TPU_TRACE_PATH", "bench_trace.json")
_TRACE_TRACERS: list = []  # host-only span buffers (never pin sessions)

# --cluster: run every config's session over a local process cluster
# (ClusterDAGScheduler ships map stages to worker processes) so the
# trace gate exercises worker-side metric/span shipping end to end —
# worker spans land in the exported trace as their own tracks and
# dev/validate_trace.py --cluster requires at least one.
CLUSTER = "--cluster" in sys.argv
if CLUSTER:
    sys.argv = [a for a in sys.argv if a != "--cluster"]
_CLUSTER_SESSIONS: list = []  # stopped at exit (kills worker processes)

# --progress: live console stage bars while configs run (obs/live.py
# ConsoleProgressReporter over heartbeat-streamed worker telemetry; a
# fast heartbeat so even short stages repaint). The reporter writes to
# stderr — the JSON record stream on stdout stays machine-clean.
PROGRESS = "--progress" in sys.argv
if PROGRESS:
    sys.argv = [a for a in sys.argv if a != "--progress"]

# --mesh: add the mesh SPMD shuffle-stage config (parallel/mesh_fusion):
# a power-of-two hash repartition whose whole stage — traced pipeline,
# partition ids, ICI all-to-all — is ONE shard_map dispatch per step.
# Reports dispatches_per_stage (mesh_stage launches per warm run) and the
# donated vs undonated send-buffer HBM watermark (DeviceLedger window).
# Needs >=2 jax devices; `python bench.py mesh` also selects it directly.
MESH = "--mesh" in sys.argv
if MESH:
    sys.argv = [a for a in sys.argv if a != "--mesh"]

# --encoded: add the compressed-execution config (columnar/encoding.py):
# a dictionary-heavy filter→repartition(string key)→group-by(string) whose
# encoded path groups directly on dictionary codes, fuses string pids via
# dict-hash luts, and ships codes + dictionaries through the shuffle.
# Reports shuffle bytes moved and hbm_gbps encoded vs decoded
# (spark.tpu.encoding.enabled=false oracle). `python bench.py encoded`
# also selects it directly.
ENCODED = "--encoded" in sys.argv
if ENCODED:
    sys.argv = [a for a in sys.argv if a != "--encoded"]

# --adaptive: add the runtime-adaptive execution config
# (physical/adaptive.py): a selective shuffled hash join measured with
# the runtime join filter off (oracle) and on. The build side's key
# domain is harvested host-side at the stage boundary and pushed into
# the not-yet-run probe shuffle, pruning probe rows before they ship.
# Reports probe rows shuffled + kernel launches per run both ways and
# the on/off speedup. `python bench.py adaptive` also selects it.
ADAPTIVE = "--adaptive" in sys.argv
if ADAPTIVE:
    sys.argv = [a for a in sys.argv if a != "--adaptive"]

# --whole-query: add the whole-query compilation config
# (physical/whole_query.py): a TPC-DS-mini-shaped join+agg plan compiled
# as ONE jitted program per step (spark.tpu.compile.tier=whole) vs the
# per-stage tier. Reports dispatches-per-query both ways and the tier
# speedup. `python bench.py whole_query` also selects it directly.
WHOLE_QUERY = "--whole-query" in sys.argv
if WHOLE_QUERY:
    sys.argv = [a for a in sys.argv if a != "--whole-query"]

# --mesh-whole: add the mesh whole-query compilation config
# (physical/mesh_whole.py): the ENTIRE sharded star-join+agg plan —
# leaves, in-program all-to-alls, join build+probe, partial and final
# aggregate — as ONE shard_map dispatch per execution step
# (spark.tpu.compile.tier=mesh-whole) vs the single-device whole tier
# and the per-stage tier. Reports dispatches-per-query for all three
# tiers, the tier speedups, and the donated vs undonated leaf-plane HBM
# watermark. Needs >=4 jax devices; `python bench.py mesh_whole` also
# selects it directly.
MESH_WHOLE = "--mesh-whole" in sys.argv
if MESH_WHOLE:
    sys.argv = [a for a in sys.argv if a != "--mesh-whole"]

# --serve-restart: measure the persistent-cache restart story
# (spark_tpu/exec/persist_cache.py): run the smoke query set in a child
# process with spark.tpu.cache.dir pointed at a scratch dir (cold leg),
# re-exec a FRESH process against the same cache dir (warm leg), and
# report cold vs warm compile counts (engine compiles, XLA disk
# hits/misses — a warm restart must show zero disk misses) plus
# repeated-query latency (first execution vs the zero-launch result-
# cache hit). `python bench.py serve_restart` also selects it directly.
SERVE_RESTART = "--serve-restart" in sys.argv
if SERVE_RESTART:
    sys.argv = [a for a in sys.argv if a != "--serve-restart"]

# internal: one serve-restart child leg (invoked by bench_serve_restart
# in a subprocess with SPARK_TPU_CACHE_DIR set) — runs the query set
# against the persistent caches and prints one SERVE-LEG json line
SERVE_LEG = "--serve-leg" in sys.argv
if SERVE_LEG:
    sys.argv = [a for a in sys.argv if a != "--serve-leg"]

# --serve: the multi-tenant serving load test (spark_tpu/serve/): 8
# concurrent per-connection sessions replay a mixed dashboard query set
# through 2 fair-scheduler pools (weights 2:1) in a COLD process, then a
# warm-restarted process replays the identical load against the same
# persistent caches. Reports p50/p99 latency per pool, peak queue depth,
# the contended-grant fairness ratio, per-query attributed launches vs
# the global counter delta (must match — scope-exact ledger), overlapped
# profile count (must be 0), and the warm leg's XLA disk misses /
# result-cache zero-launch hits. `python bench.py serve` also selects it.
SERVE = "--serve" in sys.argv
if SERVE:
    sys.argv = [a for a in sys.argv if a != "--serve"]

# internal: one serve-load child leg (invoked by bench_serve in a
# subprocess; SPARK_TPU_CACHE_DIR + SPARK_TPU_SERVE_PROFILES set) —
# prints one SERVE-LOAD json line
SERVE_LOAD_LEG = "--serve-load-leg" in sys.argv
if SERVE_LOAD_LEG:
    sys.argv = [a for a in sys.argv if a != "--serve-load-leg"]

# --profile: record a QueryProfile for every query the suite executes
# (obs/history.py flight recorder) into SPARK_TPU_PROFILE_DIR (default
# ./bench_profiles): fingerprint-keyed JSONL with per-kind launch/compile
# deltas, tier decisions, retry counters, and HBM watermarks.
# dev/perfcheck.py runs `bench.py --smoke --profile` and diffs the
# profiles' deterministic counters against dev/perf_baseline.json — the
# flight recorder's counters ARE the CI perf gate.
PROFILE = "--profile" in sys.argv
if PROFILE:
    sys.argv = [a for a in sys.argv if a != "--profile"]
PROFILE_DIR = os.environ.get("SPARK_TPU_PROFILE_DIR", "bench_profiles")


# per-config predicted peak HBM (plan_lint memory model) captured by
# _maybe_analyze so the timed record can print predicted vs measured
_PREDICTED_PEAKS: dict = {}


def _maybe_analyze(df, name: str):
    """`df` may be a DataFrame or a zero-arg callable producing one (so
    plan construction also stays inside the never-sink-the-bench guard)."""
    if not ANALYZE:
        return
    try:
        if callable(df):
            df = df()
        rep = df.query_execution.analysis_report()
        _PREDICTED_PEAKS[name] = rep.predicted_peak_hbm
        _emit({"metric": f"analysis:{name}", "value": rep.total,
               "unit": "predicted launches/run", "vs_baseline": 1.0,
               "exact": rep.exact,
               "predicted_launches": rep.predicted_launches,
               "predicted_peak_hbm": rep.predicted_peak_hbm,
               "memory_exact": rep.memory_exact,
               "fusion_boundaries": rep.fusion_boundaries[:6],
               "recompile_hazards": rep.recompile_hazards[:6]})
    except Exception as e:  # analysis must never sink a bench run
        _emit({"metric": f"analysis:{name} FAILED", "value": 0,
               "unit": "error", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:200]})


def _device_init_alive(timeout: float = 30.0) -> bool:
    """Single source of truth: __graft_entry__.accelerator_healthy (probes
    compute execution in a subprocess; see its docstring for the tunnel
    and libtpu-skew rationale). Capped at 30 s, cached across processes."""
    _here = os.path.dirname(os.path.abspath(__file__))
    if _here not in sys.path:
        sys.path.insert(0, _here)
    from __graft_entry__ import accelerator_healthy

    return accelerator_healthy(timeout)


_CONFIG_TIMEOUT_S = int(os.environ.get("SPARK_TPU_BENCH_TIMEOUT", "1500"))
# Whole-suite deadline: no matter what the accelerator does, the suite
# emits its records and summary line inside this budget (r03: a full-scale
# CPU-fallback run ate the driver budget and rc=124 lost everything after
# the last flushed line).
_SUITE_BUDGET_S = int(os.environ.get("SPARK_TPU_BENCH_BUDGET", "5400"))


class _ConfigTimeout(Exception):
    pass


def _with_timeout(fn, seconds: int):
    """Run one config under a SIGALRM deadline so a wedged accelerator or
    pathological compile can't eat the whole suite run."""
    import signal

    def on_alarm(signum, frame):
        raise _ConfigTimeout(f"config exceeded {seconds}s")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    try:
        return fn()
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _session(extra=None):
    from spark_tpu import TpuSession

    conf = {
        "spark.tpu.batch.capacity": 1 << 24,
        "spark.sql.shuffle.partitions": 1,
        # no per-operator profiling overhead in measured runs
        "spark.tpu.ui.operatorMetrics": "false",
        "spark.tpu.trace.enabled": "false",
    }
    if TRACE:
        # --trace is an observability run: spans + attributed metrics on
        # (collection is launch-free, so dispatch counts stay honest)
        conf["spark.tpu.ui.operatorMetrics"] = "true"
        conf["spark.tpu.trace.enabled"] = "true"
    if CLUSTER:
        # local process cluster; >1 shuffle partition so plans keep real
        # exchanges (= remote map stages shipped to workers)
        conf["spark.tpu.cluster.enabled"] = "true"
        conf["spark.tpu.cluster.workers"] = "2"
        conf["spark.sql.shuffle.partitions"] = 2
    if PROGRESS:
        conf["spark.tpu.progress.console"] = "true"
        conf["spark.tpu.progress.updateInterval"] = "0.2"
        conf["spark.tpu.heartbeat.interval"] = "0.25"
    if PROFILE:
        # flight recorder on: every executed query appends a
        # fingerprint-keyed profile (close-time host work only — the
        # measured dispatch counts stay honest)
        conf["spark.tpu.obs.profileDir"] = PROFILE_DIR
    conf.update(extra or {})
    if SMOKE:
        conf["spark.tpu.batch.capacity"] = min(
            int(conf["spark.tpu.batch.capacity"]), 1 << 18)
    session = TpuSession("bench", conf)
    if TRACE:
        # keep only the tracer (host span buffer): retaining the session
        # would pin every config's device-resident scan caches at once
        _TRACE_TRACERS.append(session.tracer)
    if CLUSTER:
        # cluster sessions ARE retained, then stopped at exit — worker
        # processes must not outlive the bench run
        _CLUSTER_SESSIONS.append(session)
    return session


def _df_from_table(session, table, name):
    """Device-cached single-partition DataFrame over an arrow table.
    --cluster splits the scan so aggregations keep a real exchange in
    the plan (a single-partition partial agg completes locally and never
    ships a map stage to the workers)."""
    from spark_tpu.api.dataframe import DataFrame
    from spark_tpu.expr.expressions import AttributeReference
    from spark_tpu.io.sources import InMemorySource
    from spark_tpu.plan.logical import LogicalRelation
    from spark_tpu.types import from_arrow_type

    source = InMemorySource(table, num_partitions=2 if CLUSTER else 1)
    source.cache_device_batches = True
    attrs = [AttributeReference(f.name, from_arrow_type(f.type), True)
             for f in table.schema]
    return DataFrame(session, LogicalRelation(source, attrs, name))


def _run_blocked(df) -> float:
    """Execute a DataFrame and block until all device output is ready.

    Blocks via block_until_ready AND an 8-byte host read of each output
    buffer: a host read cannot complete before the producing computation
    has, so the timing stays honest even if a remote backend's
    block_until_ready resolves on dispatch rather than completion."""
    t0 = time.perf_counter()
    parts = df.query_execution.execute()

    def _block(x):
        if isinstance(x, list):
            for y in x:
                _block(y)
        else:
            for c in x.columns:
                try:
                    c.data.block_until_ready()
                    np.asarray(c.data[:1])
                except (AttributeError, TypeError):
                    pass

    _block(parts)
    return time.perf_counter() - t0


# resource evidence of the best timed run: XLA "bytes accessed" of every
# kernel dispatched in it (per-launch captured cost × launches — see
# physical/compile._capture_kernel_cost) and the device ledger's HBM
# watermark across the measured window
_LAST_RUN = {"bytes": 0.0, "hbm_peak": 0}


def _best_of(fn, n=5):
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    fn()  # warm-up: upload + compile
    if SMOKE:
        n = 1
    GLOBAL_LEDGER.begin_window()
    best, best_bytes = None, 0.0
    for _ in range(n):
        b0 = KC.bytes_total
        t = fn()
        if best is None or t < best:
            best, best_bytes = t, KC.bytes_total - b0
    _LAST_RUN["bytes"] = best_bytes
    _LAST_RUN["hbm_peak"] = GLOBAL_LEDGER.window_peak()
    return best


def _hbm_fields(name: str, best: float, est_bytes: float) -> dict:
    """Per-config HBM evidence: `hbm_gbps` is MEASURED — the best run's
    captured kernel bytes over its wall time — with the historical
    row-count estimate only as a tagged fallback when cost capture found
    nothing (kernelCost off / lowering unavailable). Under --analyze the
    record also carries the plan analyzer's predicted peak HBM next to
    the ledger's measured watermark."""
    by = _LAST_RUN["bytes"]
    # under --cluster the map stages run in worker processes whose
    # KernelCache/ledger are per-process — the driver-side capture only
    # covers its own dispatches, so the tag says so instead of claiming
    # a full measurement
    src = ("measured-driver" if CLUSTER else "measured") if by \
        else "estimated"
    out = {"hbm_gbps": round((by or est_bytes) / best / 1e9, 1),
           "hbm_gbps_source": src}
    if ANALYZE:
        out["hbm_peak_predicted"] = _PREDICTED_PEAKS.get(name)
        out["hbm_peak_measured"] = _LAST_RUN["hbm_peak"]
    return out


def _kernel_counters():
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE

    return GLOBAL_KERNEL_CACHE.counters()


def _attach_kernel_delta(rec, before):
    """Per-config kernel dispatch/compile evidence: a fusion regression
    shows up as a launch-count jump before it shows up as wall-clock."""
    after = _kernel_counters()
    rec["kernel_launches"] = after["kernel_cache.launches"] \
        - before["kernel_cache.launches"]
    rec["kernel_compiles"] = after["kernel_cache.misses"] \
        - before["kernel_cache.misses"]
    return rec


# --------------------------------------------------------------------------
# #1 groupBy-sum
# --------------------------------------------------------------------------

def bench_groupby():
    import pyarrow as pa

    import spark_tpu.api.functions as F

    n_rows = int(10_000_000 * SCALE)
    n_keys = 1 << 20
    baseline = 75.5e6

    session = _session()
    rng = np.random.default_rng(42)
    table = pa.table({
        "k": rng.integers(0, n_keys, n_rows).astype(np.int64),
        "v": rng.integers(0, 1000, n_rows).astype(np.int64),
    })
    df = _df_from_table(session, table, "agg_bench")
    q = df.groupBy("k").agg(F.sum("v").alias("s"))
    _maybe_analyze(q, "groupby")
    best = _best_of(lambda: _run_blocked(q))
    rate = n_rows / best
    return {
        "metric": "groupBy-sum 1e7 rows (randomized int keys, 1M groups)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(rate / baseline, 3),
        **_hbm_fields("groupby", best, n_rows * 16),
    }


# --------------------------------------------------------------------------
# #2 global sort
# --------------------------------------------------------------------------

def bench_sort():
    import pyarrow as pa

    n_rows = int(100_000_000 * SCALE)
    baseline = 27.5e6  # reference radix sort, long keys

    session = _session({"spark.tpu.batch.capacity": 1 << 27})
    rng = np.random.default_rng(7)
    table = pa.table({"k": rng.integers(np.iinfo(np.int64).min,
                                        np.iinfo(np.int64).max,
                                        n_rows, dtype=np.int64)})
    df = _df_from_table(session, table, "sort_bench")
    q = df.orderBy("k")
    _maybe_analyze(q, "sort")
    best = _best_of(lambda: _run_blocked(q))
    rate = n_rows / best
    return {
        "metric": "global sort 1e8 random int64",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(rate / baseline, 3),
        **_hbm_fields("sort", best, n_rows * 8),
    }


# --------------------------------------------------------------------------
# #3 shuffled join (store_sales ⋈ date_dim shape)
# --------------------------------------------------------------------------

def bench_join():
    import pyarrow as pa

    import spark_tpu.api.functions as F

    n_fact = int(20_000_000 * SCALE)
    baseline = 10.1e6  # reference shuffled hash join, codegen on

    # 4M-row probe tiles: one moderate-size jitted join program reused
    # across tiles beats one giant 2^25 compile
    session = _session({"spark.tpu.batch.capacity": 1 << 22})
    rng = np.random.default_rng(3)
    # date_dim shape: 73049 consecutive date surrogate keys over 1998-2002
    d_date_sk = np.arange(2_450_816, 2_450_816 + 73_049, dtype=np.int64)
    d_year = 1998 + ((d_date_sk - 2_450_816) // 365).astype(np.int64)
    dim = pa.table({"d_date_sk": d_date_sk, "d_year": d_year})
    fact = pa.table({
        "ss_sold_date_sk": rng.integers(
            2_450_816, 2_450_816 + 73_049, n_fact).astype(np.int64),
        "ss_ext_sales_price": rng.random(n_fact),
    })
    f = _df_from_table(session, fact, "fact")
    d = _df_from_table(session, dim, "dim")
    q = (f.join(d, f["ss_sold_date_sk"] == d["d_date_sk"])
          .groupBy("d_year")
          .agg(F.sum("ss_ext_sales_price").alias("rev")))
    _maybe_analyze(q, "join")
    best = _best_of(lambda: _run_blocked(q))
    rate = n_fact / best
    return {
        "metric": "join store_sales-shape ⋈ date_dim (2e7 ⋈ 73k) + agg",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(rate / baseline, 3),
        **_hbm_fields("join", best, n_fact * 16),
    }


# --------------------------------------------------------------------------
# #3b shuffle-heavy map stage: exchange map-side fusion on/off
# --------------------------------------------------------------------------

_MAP_SIDE_KINDS = ("fused_shuffle", "pipeline", "shuffle_pids",
                   "shuffle_hash", "shuffle_rr", "shuffle_range")


def bench_shuffle():
    """Filter→project→hash-repartition→agg: the map side is the product
    under test. With spark.tpu.fusion.exchange on (default) the stage
    runs ONE fused dispatch per map batch; off pays pipeline + partition
    kernels plus an intermediate batch. Reports map-side kernel launches
    per batch both ways; vs_baseline is the speedup over our own unfused
    oracle. Partition count 5 (non-power-of-two) keeps the exchange on
    the host shuffle path rather than a mesh all-to-all."""
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE

    n_rows = int(20_000_000 * SCALE)
    session = _session({"spark.tpu.batch.capacity": 1 << 22,
                        # the bench measures the fused path at every scale
                        "spark.tpu.fusion.minRows": "0"})
    cap = int(session.conf.get("spark.tpu.batch.capacity"))
    n_batches = max(1, -(-n_rows // cap))
    rng = np.random.default_rng(23)
    table = pa.table({
        "k": rng.integers(0, 1 << 16, n_rows).astype(np.int64),
        "v": rng.integers(0, 1000, n_rows).astype(np.int64),
    })
    df = _df_from_table(session, table, "shuffle_bench")

    def q():
        # repartition terminal: every launch in the query IS map-side
        # work (a downstream agg would add its own pipeline launches and
        # muddy the per-batch metric)
        return (df.filter(F.col("v") > 25)
                .withColumn("v2", F.col("v") * 3)
                .repartition(5, "k"))

    _maybe_analyze(q, "shuffle")
    results = {}
    hbm = {}
    for mode, flag in (("fused", "true"), ("unfused", "false")):
        session.conf.set("spark.tpu.fusion.exchange", flag)
        best = _best_of(lambda: _run_blocked(q()))
        if mode == "fused":
            hbm = _hbm_fields("shuffle", best, n_rows * 16)
        before = dict(GLOBAL_KERNEL_CACHE.launches_by_kind)
        _run_blocked(q())
        after = GLOBAL_KERNEL_CACHE.launches_by_kind
        map_launches = sum(after.get(k, 0) - before.get(k, 0)
                           for k in _MAP_SIDE_KINDS)
        results[mode] = (best, map_launches)
    session.conf.unset("spark.tpu.fusion.exchange")
    best_fused, map_fused = results["fused"]
    best_unfused, map_unfused = results["unfused"]
    rate = n_rows / best_fused
    return {
        "metric": "shuffle map stage filter+project+repartition(5,k) 2e7 "
                  "rows (exchange map-side fusion; vs_baseline = speedup "
                  "over the unfused oracle)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(best_unfused / best_fused, 3),
        **hbm,
        "map_launches_per_batch_fused": round(map_fused / n_batches, 2),
        "map_launches_per_batch_unfused": round(map_unfused / n_batches, 2),
    }


# --------------------------------------------------------------------------
# #3b2 runtime-adaptive join filter: build-side domain pushed into the
# not-yet-run probe shuffle (physical/adaptive.install_runtime_filters)
# --------------------------------------------------------------------------

def bench_adaptive():
    """Selective shuffled hash join (2e7-row probe ⋈ 300-key contiguous
    dim) run twice: spark.tpu.adaptive.runtimeFilter off (oracle) and on.
    With the filter on, the materialized build side's dense key range is
    harvested host-side at the stage boundary and pushed into the probe
    shuffle, which prunes ~98.5% of probe rows BEFORE they are shuffled.
    Reports probe rows shuffled and kernel launches per run both ways;
    vs_baseline is the speedup over our own filter-off oracle. Partition
    count 5 (non-power-of-two) keeps the exchanges on the host shuffle
    path so byte/row accounting is exact."""
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE

    n_fact = int(20_000_000 * SCALE)
    n_keys = 100_000
    session = _session({"spark.tpu.batch.capacity": 1 << 22,
                        "spark.sql.shuffle.partitions": 5,
                        "spark.sql.autoBroadcastJoinThreshold": -1})
    rng = np.random.default_rng(41)
    fact = pa.table({
        "k": rng.integers(0, n_keys, n_fact).astype(np.int64),
        "v": rng.integers(0, 1000, n_fact).astype(np.int64),
    })
    dim = pa.table({"k": np.arange(40_000, 40_300, dtype=np.int64),
                    "w": np.arange(300, dtype=np.int64)})
    # multi-partition inputs keep real hash exchanges in the join plan
    # (single-partition sources co-locate and the probe never shuffles)
    f = _df_from_table(session, fact, "rf_fact").repartition(5)
    d = _df_from_table(session, dim, "rf_dim").repartition(2)

    def q():
        return (f.join(d, on="k").groupBy("k")
                .agg(F.sum("v").alias("sv")))

    _maybe_analyze(q, "adaptive")
    results, hbm = {}, {}
    for mode, flag in (("on", "true"), ("off", "false")):
        session.conf.set("spark.tpu.adaptive.runtimeFilter", flag)
        best = _best_of(lambda: _run_blocked(q()))
        if mode == "on":
            hbm = _hbm_fields("adaptive", best, n_fact * 16)
        c0 = session._metrics.snapshot()["counters"]
        l0 = GLOBAL_KERNEL_CACHE.counters()["kernel_cache.launches"]
        _run_blocked(q())
        c1 = session._metrics.snapshot()["counters"]
        launches = GLOBAL_KERNEL_CACHE.counters()["kernel_cache.launches"] \
            - l0
        pruned = c1.get("adaptive.filter_rows_pruned", 0) \
            - c0.get("adaptive.filter_rows_pruned", 0)
        installed = c1.get("adaptive.runtime_filters_installed", 0) \
            - c0.get("adaptive.runtime_filters_installed", 0)
        results[mode] = (best, launches, pruned, installed)
    session.conf.unset("spark.tpu.adaptive.runtimeFilter")
    best_on, launches_on, pruned_on, installed_on = results["on"]
    best_off, launches_off, pruned_off, _ = results["off"]
    rate = n_fact / best_on
    return {
        "metric": "adaptive runtime join filter 2e7 probe ⋈ 300-key dim "
                  "+ agg (vs_baseline = speedup over the filter-off "
                  "oracle)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(best_off / best_on, 3),
        **hbm,
        "filters_installed": installed_on,
        "probe_rows_shuffled_off": n_fact,
        "probe_rows_shuffled_on": n_fact - pruned_on,
        "probe_rows_pruned": pruned_on,
        "launches_per_run_on": launches_on,
        "launches_per_run_off": launches_off,
    }


# --------------------------------------------------------------------------
# #3c mesh SPMD shuffle stage: one sharded dispatch per stage per step
# --------------------------------------------------------------------------

def bench_mesh():
    """Filter→project→hash-repartition over the device mesh: the whole
    map stage (traced pipeline + partition ids + all-to-all) is ONE
    shard_map dispatch per step with donated send buffers. vs_baseline is
    the speedup over our own legacy composition (spark.tpu.fusion.mesh=
    false: per-batch pipeline materialization before the collective);
    the record also carries dispatches_per_stage measured from the
    KernelCache and the donated vs undonated staged-buffer HBM peaks
    from the DeviceLedger window watermark."""
    import gc

    import jax
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.parallel import mesh_fusion as MF
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE

    ndev = len(jax.devices())
    if ndev < 2:
        return {"metric": "mesh shuffle stage SKIPPED (needs >=2 devices)",
                "value": 0, "unit": "status", "vs_baseline": 1.0}
    num_out = 8 if ndev >= 8 else (4 if ndev >= 4 else 2)
    n_rows = int(20_000_000 * SCALE)
    session = _session({"spark.tpu.batch.capacity": 1 << 22,
                        "spark.tpu.fusion.minRows": "0"})
    rng = np.random.default_rng(29)
    table = pa.table({
        "k": rng.integers(0, 1 << 16, n_rows).astype(np.int64),
        "v": rng.integers(0, 1000, n_rows).astype(np.int64),
    })
    df = _df_from_table(session, table, "mesh_bench")

    def q():
        return (df.filter(F.col("v") > 25)
                .withColumn("v2", F.col("v") * 3)
                .repartition(num_out, "k"))

    _maybe_analyze(q, "mesh")
    results = {}
    for mode, flag in (("fused", "true"), ("legacy", "false")):
        session.conf.set("spark.tpu.fusion.mesh", flag)
        best = _best_of(lambda: _run_blocked(q()))
        before = dict(GLOBAL_KERNEL_CACHE.launches_by_kind)
        _run_blocked(q())
        after = GLOBAL_KERNEL_CACHE.launches_by_kind
        dispatches = after.get("mesh_stage", 0) - before.get("mesh_stage", 0)
        results[mode] = (best, dispatches)
    session.conf.unset("spark.tpu.fusion.mesh")

    def hbm_window():
        gc.collect()
        GLOBAL_LEDGER.begin_window()
        _run_blocked(q())
        return GLOBAL_LEDGER.window_peak()

    donate_was = MF.DONATE_DEFAULT
    try:
        MF.DONATE_DEFAULT = False
        _run_blocked(q())  # compile the undonated oracle program
        peak_undonated = hbm_window()
        MF.DONATE_DEFAULT = True
        peak_donated = hbm_window()
    finally:
        MF.DONATE_DEFAULT = donate_was

    best_fused, disp_fused = results["fused"]
    best_legacy, _disp_legacy = results["legacy"]
    rate = n_rows / best_fused
    return {
        "metric": f"mesh SPMD shuffle stage filter+project+repartition"
                  f"({num_out},k) {n_rows:.0e} rows over {num_out} devices "
                  "(one sharded dispatch per stage per step; vs_baseline "
                  "= speedup over the materialize-then-collective legacy "
                  "path)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(best_legacy / best_fused, 3),
        **_hbm_fields("mesh", best_fused, n_rows * 16),
        "dispatches_per_stage": disp_fused,
        "hbm_peak_donated": peak_donated,
        "hbm_peak_undonated": peak_undonated,
        "donated_hbm_saving": peak_undonated - peak_donated,
    }


# --------------------------------------------------------------------------
# #3d compressed execution: dictionary/RLE-native kernels + code shuffle
# --------------------------------------------------------------------------

def bench_encoded():
    """Dictionary-heavy filter→hash-repartition(string key)→group-by
    (string key)→sum: the compressed-execution scoreboard. Encoded
    (spark.tpu.encoding.enabled, default on): the aggregate groups
    directly on dictionary codes (dense-on-codes, no sort, no range
    probe), the fused map dispatch computes string pids from the padded
    dict-hash lut inside the stage kernel, and the shuffle ships int32
    codes + shared dictionary references. Decoded oracle (off): hashed
    eq-key staging, sorted-segment grouping. vs_baseline is the speedup
    over the oracle; the record carries shuffle bytes moved and hbm_gbps
    both ways. Partition count 5 keeps the exchange on the host path."""
    import pyarrow as pa

    import spark_tpu.api.functions as F  # noqa: F401

    n_rows = int(20_000_000 * SCALE)
    session = _session({"spark.tpu.batch.capacity": 1 << 22,
                        "spark.tpu.fusion.minRows": "0"})
    rng = np.random.default_rng(31)
    # long repeated strings: the decoded wire format pays them per row
    cats = [f"category-{i:04d}-with-a-long-repeated-name" for i in
            range(4096)]
    codes = rng.integers(0, len(cats), n_rows)
    table = pa.table({
        "s": pa.DictionaryArray.from_arrays(
            pa.array(codes, type=pa.int32()), pa.array(cats)),
        "v": rng.integers(0, 1000, n_rows).astype(np.int64),
    })
    df = _df_from_table(session, table, "encoded_bench")

    def q():
        return (df.filter(F.col("v") > 25)
                .repartition(5, "s")
                .groupBy("s").agg(F.sum("v").alias("sv")))

    _maybe_analyze(q, "encoded")
    results = {}
    for mode, flag in (("encoded", "true"), ("decoded", "false")):
        session.conf.set("spark.tpu.encoding.enabled", flag)
        best = _best_of(lambda: _run_blocked(q()))
        results[mode] = (best,
                         _hbm_fields(f"encoded[{mode}]", best, n_rows * 12))
    session.conf.unset("spark.tpu.encoding.enabled")

    # wire bytes: the CLUSTER block format is where codes + one dict per
    # map task beat decoded row values (the local path shares host
    # buffers either way) — a 2-worker process cluster at bounded scale
    # measures the pickled block sizes (MapStatus bytes) both ways
    wire = {}
    wn = min(n_rows, 500_000)
    wtable = table.slice(0, wn)
    for mode, flag in (("encoded", "true"), ("decoded", "false")):
        from spark_tpu.api.session import TpuSession
        from spark_tpu.exec.cluster import LocalCluster

        s2 = TpuSession(f"bench-encoded-wire-{mode}", {
            "spark.sql.shuffle.partitions": "3",
            "spark.tpu.batch.capacity": 1 << 18,
            "spark.sql.adaptive.enabled": "false",
            "spark.tpu.fusion.minRows": "0",
            "spark.tpu.encoding.enabled": flag,
        })
        s2.attachSqlCluster(LocalCluster(num_workers=2))
        try:
            wdf = s2.createDataFrame(wtable)
            (wdf.filter(F.col("v") > 25).repartition(3, "s")
             .groupBy("s").agg(F.sum("v").alias("sv")).toArrow())
            wire[mode] = s2._metrics.snapshot()["counters"].get(
                "shuffle.bytes_written", 0)
        finally:
            s2.stop()

    best_enc, hbm_enc = results["encoded"]
    best_dec, hbm_dec = results["decoded"]
    rate = n_rows / best_enc
    return {
        "metric": "compressed execution filter+repartition(5,s)+groupBy(s) "
                  f"{n_rows:.0e} rows, 4096-entry dictionary (dense-on-"
                  "codes agg + fused dict-hash pids + code-shipping "
                  "shuffle; vs_baseline = speedup over the decoded oracle)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(best_dec / best_enc, 3),
        **{k: v for k, v in hbm_enc.items()},
        "hbm_gbps_decoded": hbm_dec.get("hbm_gbps"),
        "shuffle_wire_bytes_encoded": int(wire["encoded"]),
        "shuffle_wire_bytes_decoded": int(wire["decoded"]),
        "shuffle_wire_bytes_ratio": round(
            wire["encoded"] / wire["decoded"], 3)
        if wire["decoded"] else None,
    }


def bench_whole_query():
    """Whole-query compilation scoreboard: a q3-shaped star join
    (fact scan -> filter -> two broadcast dim joins -> group-by sum)
    executed under the whole tier (ONE jitted program per step, exchanges
    lowered to in-program gathers, zero host shuffle round-trips) vs the
    per-stage tier (PR 1/5 fusion). vs_baseline is the tier speedup;
    the record carries measured dispatches-per-query for both tiers."""
    import pyarrow as pa

    import spark_tpu.api.functions as F  # noqa: F401
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    n_rows = int(10_000_000 * SCALE)
    session = _session({"spark.tpu.batch.capacity": 1 << 22,
                        "spark.tpu.fusion.minRows": "0"})
    rng = np.random.default_rng(23)
    n_dim = 2048
    fact = pa.table({
        "date_sk": rng.integers(0, n_dim, n_rows).astype(np.int64),
        "item_sk": rng.integers(0, n_dim, n_rows).astype(np.int64),
        "price": rng.integers(0, 10_000, n_rows).astype(np.int64),
    })
    dates = pa.table({
        "d_date_sk": np.arange(n_dim, dtype=np.int64),
        "d_year": (1998 + (np.arange(n_dim) // 366)).astype(np.int64),
        "d_moy": (1 + np.arange(n_dim) % 12).astype(np.int64),
    })
    items = pa.table({
        "i_item_sk": np.arange(n_dim, dtype=np.int64),
        "i_brand_id": (np.arange(n_dim) % 37).astype(np.int64),
        "i_manufact_id": (np.arange(n_dim) % 100).astype(np.int64),
    })
    fdf = _df_from_table(session, fact, "wq_fact")
    ddf = _df_from_table(session, dates, "wq_dates")
    idf = _df_from_table(session, items, "wq_items")
    fdf.createOrReplaceTempView("wq_fact")
    ddf.createOrReplaceTempView("wq_dates")
    idf.createOrReplaceTempView("wq_items")
    sql = ("select d_year, i_brand_id, sum(price) s from wq_fact "
           "join wq_dates on date_sk = d_date_sk "
           "join wq_items on item_sk = i_item_sk "
           "where d_moy = 11 and i_manufact_id = 28 "
           "group by d_year, i_brand_id")

    def q():
        return session.sql(sql)

    session.conf.set("spark.tpu.compile.tier", "whole")
    _maybe_analyze(q, "whole_query")  # the whole-tier launch model
    results = {}
    dispatches = {}
    for tier in ("whole", "stage"):
        session.conf.set("spark.tpu.compile.tier", tier)
        q().toArrow()  # warm: compile the tier's programs
        before = KC.launches
        q().toArrow()
        dispatches[tier] = KC.launches - before
        best = _best_of(lambda: _run_blocked(q()))
        results[tier] = (best, _hbm_fields(f"whole_query[{tier}]", best,
                                           n_rows * 24))
    session.conf.unset("spark.tpu.compile.tier")
    best_w, hbm_w = results["whole"]
    best_s, _hbm_s = results["stage"]
    rate = n_rows / best_w
    return {
        "metric": "whole-query compilation: q3-shaped star join+agg "
                  f"{n_rows:.0e} fact rows as ONE jitted dispatch per "
                  "step (spark.tpu.compile.tier=whole; vs_baseline = "
                  "speedup over the per-stage tier)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(best_s / best_w, 3),
        **{k: v for k, v in hbm_w.items()},
        "dispatches_per_query_whole": int(dispatches["whole"]),
        "dispatches_per_query_stage": int(dispatches["stage"]),
        "wall_ms_whole": round(best_w * 1e3, 1),
        "wall_ms_stage": round(best_s * 1e3, 1),
    }


def bench_mesh_whole():
    """Mesh whole-query compilation scoreboard: the q3-shaped star join
    (fact scan -> filter -> two dim joins -> hash repartition -> group-by
    sum) executed as ONE shard_map program over the device mesh per step
    (spark.tpu.compile.tier=mesh-whole: leaves staged sharded, exchanges
    lowered to in-program all-to-alls, join and aggregate folded in
    behind the collectives) vs the single-device whole tier and the
    per-stage tier. vs_baseline is the speedup over the stage tier; the
    record carries measured dispatches-per-query for all three tiers and
    the donated vs undonated leaf-plane HBM watermark."""
    import gc

    import jax
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.parallel import mesh_fusion as MF
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    ndev = len(jax.devices())
    if ndev < 4:
        return {"metric": "mesh whole-query SKIPPED (needs >=4 devices)",
                "value": 0, "unit": "status", "vs_baseline": 1.0}
    P = 8 if ndev >= 8 else 4
    n_rows = int(10_000_000 * SCALE)
    session = _session({"spark.tpu.batch.capacity": 1 << 22,
                        "spark.tpu.fusion.minRows": "0",
                        "spark.sql.shuffle.partitions": P})
    rng = np.random.default_rng(23)
    n_dim = 2048
    fact = pa.table({
        "date_sk": rng.integers(0, n_dim, n_rows).astype(np.int64),
        "item_sk": rng.integers(0, n_dim, n_rows).astype(np.int64),
        "price": rng.integers(0, 10_000, n_rows).astype(np.int64),
    })
    dates = pa.table({
        "d_date_sk": np.arange(n_dim, dtype=np.int64),
        "d_year": (1998 + (np.arange(n_dim) // 366)).astype(np.int64),
        "d_moy": (1 + np.arange(n_dim) % 12).astype(np.int64),
    })
    items = pa.table({
        "i_item_sk": np.arange(n_dim, dtype=np.int64),
        "i_brand_id": (np.arange(n_dim) % 37).astype(np.int64),
        "i_manufact_id": (np.arange(n_dim) % 100).astype(np.int64),
    })
    _df_from_table(session, fact, "mwq_fact") \
        .createOrReplaceTempView("mwq_fact")
    _df_from_table(session, dates, "mwq_dates") \
        .createOrReplaceTempView("mwq_dates")
    _df_from_table(session, items, "mwq_items") \
        .createOrReplaceTempView("mwq_items")
    sql = ("select d_year, i_brand_id, price from mwq_fact "
           "join mwq_dates on date_sk = d_date_sk "
           "join mwq_items on item_sk = i_item_sk "
           "where d_moy = 11 and i_manufact_id = 28")

    def q():
        return (session.sql(sql).repartition(P, "i_brand_id")
                .groupBy("d_year", "i_brand_id")
                .agg(F.sum("price").alias("s")))

    session.conf.set("spark.tpu.compile.tier", "mesh-whole")
    _maybe_analyze(q, "mesh_whole")  # the mesh launch + retry model
    results = {}
    dispatches = {}
    for tier in ("mesh-whole", "whole", "stage"):
        session.conf.set("spark.tpu.compile.tier", tier)
        q().toArrow()  # warm: compile the tier's programs
        before = KC.launches
        q().toArrow()
        dispatches[tier] = KC.launches - before
        results[tier] = _best_of(lambda: _run_blocked(q()))

    session.conf.set("spark.tpu.compile.tier", "mesh-whole")

    def hbm_window():
        gc.collect()
        GLOBAL_LEDGER.begin_window()
        _run_blocked(q())
        return GLOBAL_LEDGER.window_peak()

    donate_was = MF.DONATE_DEFAULT
    try:
        MF.DONATE_DEFAULT = False
        _run_blocked(q())  # compile the undonated oracle program
        peak_undonated = hbm_window()
        MF.DONATE_DEFAULT = True
        _run_blocked(q())
        peak_donated = hbm_window()
    finally:
        MF.DONATE_DEFAULT = donate_was
    session.conf.unset("spark.tpu.compile.tier")

    best_m = results["mesh-whole"]
    rate = n_rows / best_m
    return {
        "metric": "mesh whole-query compilation: q3-shaped star join+agg "
                  f"{n_rows:.0e} fact rows as ONE shard_map dispatch per "
                  f"step over {P} devices (spark.tpu.compile.tier="
                  "mesh-whole; vs_baseline = speedup over the per-stage "
                  "tier)",
        "value": round(rate / 1e6, 2),
        "unit": "M rows/s",
        "vs_baseline": round(results["stage"] / best_m, 3),
        **_hbm_fields("mesh_whole", best_m, n_rows * 24),
        "dispatches_per_query_mesh_whole": int(dispatches["mesh-whole"]),
        "dispatches_per_query_whole": int(dispatches["whole"]),
        "dispatches_per_query_stage": int(dispatches["stage"]),
        "speedup_vs_whole": round(results["whole"] / best_m, 3),
        "hbm_peak_donated": peak_donated,
        "hbm_peak_undonated": peak_undonated,
        "donated_hbm_saving": peak_undonated - peak_donated,
        "wall_ms_mesh_whole": round(best_m * 1e3, 1),
        "wall_ms_whole": round(results["whole"] * 1e3, 1),
        "wall_ms_stage": round(results["stage"] * 1e3, 1),
    }


# --------------------------------------------------------------------------
# #4/#5 TPC-DS q3 / q7 / q19 wall-clock at SF1-equivalent volume
# --------------------------------------------------------------------------

TPCDS_REF_MS = {"q3": 252.0, "q7": 595.0, "q19": 361.0}
# tests/tpcds/datagen.py scale=1.0 ≈ 30k store_sales rows; real SF1 is
# 2 880 404 rows (reference GenTPCDSData) → scale 96 ≈ SF1 fact volume.
TPCDS_GEN_SCALE = 96.0


def _gen_tpcds_subset(scale):
    """Generate only the tables q3/q7/q19 touch (dims + store_sales).
    Cached as parquet under /tmp — datagen at SF1 volume is ~2 min of
    host work and deterministic (seed 17), so regeneration is waste."""
    import pyarrow.parquet as pq

    cache = f"/tmp/sparktpu_bench_tpcds_{scale:g}"
    names = ["date_dim", "time_dim", "item", "customer_address",
             "customer_demographics", "household_demographics",
             "income_band", "customer", "store", "warehouse", "ship_mode",
             "reason", "call_center", "catalog_page", "web_site",
             "web_page", "promotion", "store_sales"]
    if os.path.isdir(cache):
        try:
            return {n: pq.read_table(os.path.join(cache, f"{n}.parquet"))
                    for n in names}
        except Exception:
            pass
    _here = os.path.dirname(os.path.abspath(__file__))
    if _here not in sys.path:
        sys.path.insert(0, _here)
    from tests.tpcds.datagen import _Gen

    g = _Gen(scale, 17)
    g.date_dim()
    g.time_dim()
    g.item()
    g.customer_address()
    g.customer_demographics()
    g.household_demographics()
    g.income_band()
    g.customer()
    g.store()
    g.warehouse()
    g.ship_mode()
    g.reason()
    g.call_center()
    g.catalog_page()
    g.web_site()
    g.web_page()
    g.promotion()
    g.store_sales()
    try:
        os.makedirs(cache, exist_ok=True)
        for n in names:
            pq.write_table(g.tables[n], os.path.join(cache, f"{n}.parquet"))
    except Exception:
        pass
    return g.tables


def bench_tpcds():
    here = os.path.dirname(os.path.abspath(__file__))
    qdir = os.path.join(here, "tests", "tpcds", "queries")
    tables = _gen_tpcds_subset(TPCDS_GEN_SCALE * SCALE)
    n_ss = tables["store_sales"].num_rows

    session = _session({"spark.tpu.batch.capacity": 1 << 22})
    for name, tab in tables.items():
        session.createDataFrame(tab).createOrReplaceTempView(name)

    from tests.tpcds.oracle import strip_trailing_limit

    out = []
    for qname, ref_ms in TPCDS_REF_MS.items():
        sql = strip_trailing_limit(
            open(os.path.join(qdir, f"{qname}.sql")).read())
        _maybe_analyze(lambda: session.sql(sql), f"tpcds-{qname}")

        def run():
            t0 = time.perf_counter()
            session.sql(sql).toArrow()
            return time.perf_counter() - t0

        best = _best_of(run, n=5)
        out.append({
            "metric": f"TPC-DS {qname} wall-clock "
                      f"(SF1-equivalent, {n_ss} fact rows)",
            "value": round(best * 1e3, 1),
            "unit": "ms",
            "vs_baseline": round(ref_ms / (best * 1e3), 3),
        })
    return out


# --------------------------------------------------------------------------
# serve-restart: persistent-cache warm restarts (exec/persist_cache.py)
# --------------------------------------------------------------------------

def _serve_leg() -> int:
    """One serve-restart child leg: run the query set against the
    persistent caches rooted at SPARK_TPU_CACHE_DIR and print one
    SERVE-LEG json line. Phase 1 runs with the result cache DISABLED so
    queries actually execute (that is what proves the XLA disk cache:
    engine compiles happen, backend compiles hit disk on the warm leg);
    phase 2 enables the result cache and measures the repeated-query
    path (zero-launch Arrow-payload answer)."""
    import pyarrow as pa

    import spark_tpu.api.functions as F
    import spark_tpu.exec.persist_cache as pc
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    cache_dir = os.environ["SPARK_TPU_CACHE_DIR"]
    session = _session({
        "spark.tpu.cache.dir": cache_dir,
        "spark.tpu.cache.result.enabled": "false",
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.batch.capacity": 1 << 14,
        "spark.tpu.fusion.minRows": "0",
    })
    rng = np.random.default_rng(11)
    n = max(4000, int(100_000 * SCALE))
    table = pa.table({"k": rng.integers(0, 64, n).astype(np.int64),
                      "v": rng.integers(0, 1000, n).astype(np.int64)})
    df = _df_from_table(session, table, "serve_t")
    queries = {
        "groupby": lambda: df.groupBy("k").agg(F.sum("v").alias("s")),
        "filter_sort": lambda: df.where(F.col("v") > 500).orderBy("k"),
    }
    exec_ms = {}
    for name, q in queries.items():
        t0 = time.perf_counter()
        q().toArrow()
        exec_ms[name] = round((time.perf_counter() - t0) * 1000, 2)
    # phase 2: repeated identical query through the result cache (the
    # cold leg populates the entry; the warm leg's first lookup already
    # hits it CROSS-PROCESS)
    session.conf.set("spark.tpu.cache.result.enabled", "true")
    queries["groupby"]().toArrow()
    l0 = KC.launches
    t0 = time.perf_counter()
    queries["groupby"]().toArrow()
    repeat_ms = round((time.perf_counter() - t0) * 1000, 2)
    counters = session._metrics.snapshot()["counters"]
    print("SERVE-LEG " + json.dumps({
        "compiles": KC.misses,
        "disk_hit_compiles": KC.disk_hit_compiles,
        "disk": pc.disk_counters(),
        "exec_ms": exec_ms,
        "repeat_ms": repeat_ms,
        "repeat_launches": KC.launches - l0,
        "result_cache_hits": int(counters.get("result_cache.hit", 0)),
    }), flush=True)
    return 0


def bench_serve_restart():
    """Cold→warm restart differential: the SAME query set in two real
    processes sharing one cache dir. The warm process must show zero
    XLA disk misses (every backend compile served from the cold run's
    disk cache) and answer the repeated query from the result cache
    with zero kernel launches."""
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="sparktpu_cache_")
    env = dict(os.environ)
    env["SPARK_TPU_CACHE_DIR"] = cache_dir
    env["SPARK_TPU_BENCH_SCALE"] = str(SCALE)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if SMOKE:
        env["JAX_PLATFORMS"] = "cpu"
    legs = []
    for leg in ("cold", "warm"):
        cmd = [sys.executable, os.path.abspath(__file__), "--serve-leg"]
        if SMOKE:
            cmd.append("--smoke")
        proc = subprocess.run(
            cmd, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, text=True,
            timeout=min(_CONFIG_TIMEOUT_S, 600))
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("SERVE-LEG ")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"serve-restart {leg} leg failed rc={proc.returncode}: "
                f"{proc.stdout[-400:]}")
        legs.append(json.loads(lines[-1][len("SERVE-LEG "):]))
    cold, warm = legs
    return [{
        "metric": "serve-restart warm XLA disk misses "
                  "(0 = restart pays no cold compiles)",
        "value": warm["disk"]["compile.disk_miss"],
        "unit": "cold XLA compiles in a fresh process",
        "vs_baseline": 1.0,
        "cold_disk_misses": cold["disk"]["compile.disk_miss"],
        "warm_disk_hits": warm["disk"]["compile.disk_hit"],
        "cold_engine_compiles": cold["compiles"],
        "warm_engine_compiles": warm["compiles"],
        "warm_disk_hit_compiles": warm["disk_hit_compiles"],
    }, {
        "metric": "serve-restart repeated-query latency "
                  "(cross-process result-cache hit)",
        "value": warm["repeat_ms"],
        "unit": "ms",
        "vs_baseline": 1.0,
        "first_execution_ms": warm["exec_ms"].get("groupby"),
        "cold_repeat_ms": cold["repeat_ms"],
        "repeat_kernel_launches": warm["repeat_launches"],
        "result_cache_hits_warm_leg": warm["result_cache_hits"],
    }]


# --------------------------------------------------------------------------
# serve: multi-tenant serving load (spark_tpu/serve/)
# --------------------------------------------------------------------------

_SERVE_QUERIES = [
    "select k, sum(v) as s from serve_load_t group by k",
    "select k, v from serve_load_t where v > 500 order by v limit 32",
    "select count(*) c from serve_load_t where k < 32",
]


def _serve_load_leg() -> int:
    """One serve-load child leg: start a serving session with 2 pools
    (dash:2, batch:1), drive 8 concurrent cloned sessions through the
    mixed query set (phase 1: result cache DISABLED so queries really
    execute and contend), then replay through the result cache
    (phase 2), and print one SERVE-LOAD json line with fairness,
    latency, attribution, and cache evidence."""
    import pyarrow as pa

    import spark_tpu.exec.persist_cache as pc
    from spark_tpu.obs.history import ProfileStore
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
    from spark_tpu.serve import QueryService
    from spark_tpu.serve.loadgen import run_serve_load

    cache_dir = os.environ["SPARK_TPU_CACHE_DIR"]
    profile_dir = os.environ["SPARK_TPU_SERVE_PROFILES"]
    session = _session({
        "spark.tpu.cache.dir": cache_dir,
        "spark.tpu.cache.result.enabled": "false",
        "spark.tpu.obs.profileDir": profile_dir,
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.batch.capacity": 1 << 14,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.scheduler.pools": "dash:2,batch:1",
        "spark.tpu.serve.maxConcurrent": "2",
        # metrics plane on for the whole leg: the scrape at end-of-load
        # and the drain-time series snapshot are part of the report
        "spark.tpu.metrics.export": "true",
        "spark.tpu.metrics.tickInterval": "0.25",
    })
    rng = np.random.default_rng(7)
    n = max(4000, int(100_000 * SCALE))
    session.createDataFrame(pa.table({
        "k": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })).createOrReplaceTempView("serve_load_t")
    service = QueryService(session)
    # serial warmup: compile every kernel once BEFORE the concurrent
    # phase — concurrent FIRST invocations race the XLA disk-cache
    # write (two threads compile, one persists), which made the warm
    # leg's disk_miss flap 0/1. Warm kernels take the cache-hit path,
    # so the contended phase measures admission, not compile races.
    warmup = service.open_session()
    for q in _SERVE_QUERIES:
        service.execute_sql(warmup, q)
    # phase 1: real execution under contention (8 sessions, 2 pools)
    load = run_serve_load(service, _SERVE_QUERIES, sessions=8, reps=2,
                          pools=("dash", "batch"))
    # phase 2: repeated dashboard queries through the result cache
    session.conf.set("spark.tpu.cache.result.enabled", "true")
    l0 = KC.launches
    t0 = time.perf_counter()
    repeat = run_serve_load(service, _SERVE_QUERIES, sessions=4, reps=1,
                            pools=("dash", "batch"))
    repeat_ms = round((time.perf_counter() - t0) * 1000, 2)
    repeat_launches = KC.launches - l0
    rc_hits = int(repeat["counters"].get("result_cache.hit", 0))
    # end-of-load Prometheus scrape: parse it back and reconcile the
    # per-pool e2e histogram counts against the queries the load
    # actually completed (the metrics-plane acceptance identity)
    from spark_tpu.obs import export as mx

    scrape = mx.render_prometheus()
    parsed = mx.parse_prometheus(scrape)
    e2e_count = sum(
        v for (name, _labels), v in parsed["samples"].items()
        if name == "spark_tpu_serve_pool_e2e_ms_count")
    service.drain()
    drain_ts = service.drain_snapshot or {}
    # attribution: per-query scope-exact launch totals (stored profiles)
    # must sum to the process-global KernelCache delta
    store = ProfileStore(profile_dir)
    attributed = 0
    overlapped = 0
    profiles = 0
    for qk in store.query_keys():
        for p in store.profiles(qk):
            profiles += 1
            attributed += int(p.get("launch_total", 0))
            if p.get("overlapped"):
                overlapped += 1
    print("SERVE-LOAD " + json.dumps({
        "load": load,
        "repeat": {"wall_ms": repeat_ms, "launches": repeat_launches,
                   "errors": repeat["errors"],
                   "result_cache_hits": rc_hits},
        "profiles": profiles,
        "attributed_launches": attributed,
        "global_launches": KC.launches,
        "overlapped_profiles": overlapped,
        "disk": pc.disk_counters(),
        "compiles": KC.misses,
        "disk_hit_compiles": KC.disk_hit_compiles,
        "metrics": {
            "scrape_bytes": len(scrape),
            "scrape_samples": len(parsed["samples"]),
            "e2e_hist_count": int(e2e_count),
            "drain_series": len(drain_ts.get("series", {})),
        },
    }), flush=True)
    return 0


def bench_serve():
    """Serving load test, cold process then warm restart: 8 concurrent
    sessions on 2 weighted pools; the warm leg must pay zero XLA disk
    misses and answer the repeated query set from the result cache
    with zero launches."""
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="sparktpu_serve_cache_")
    env = dict(os.environ)
    env["SPARK_TPU_CACHE_DIR"] = cache_dir
    env["SPARK_TPU_BENCH_SCALE"] = str(SCALE)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if SMOKE:
        env["JAX_PLATFORMS"] = "cpu"
    legs = []
    for leg in ("cold", "warm"):
        env["SPARK_TPU_SERVE_PROFILES"] = tempfile.mkdtemp(
            prefix=f"sparktpu_serve_prof_{leg}_")
        cmd = [sys.executable, os.path.abspath(__file__),
               "--serve-load-leg"]
        if SMOKE:
            cmd.append("--smoke")
        proc = subprocess.run(
            cmd, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, text=True,
            timeout=min(_CONFIG_TIMEOUT_S, 600))
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("SERVE-LOAD ")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"serve {leg} leg failed rc={proc.returncode}: "
                f"{proc.stdout[-400:]}")
        legs.append(json.loads(lines[-1][len("SERVE-LOAD "):]))
    cold, warm = legs
    pools = cold["load"]["pools"]
    out = [{
        "metric": "serve p99 latency (8 sessions, pools dash:2/batch:1, "
                  "maxConcurrent=2)",
        "value": max(p["p99_ms"] or 0 for p in pools.values()),
        "unit": "ms",
        "vs_baseline": 1.0,
        "per_pool": {name: {"p50_ms": p["p50_ms"], "p95_ms": p["p95_ms"],
                            "p99_ms": p["p99_ms"],
                            "completed": p["completed"]}
                     for name, p in pools.items()},
        "queue_depth_peak": cold["load"]["queue_depth_peak"],
        "errors": (cold["load"]["errors"] + warm["load"]["errors"])[:4],
        "metrics_scrape": cold["metrics"],
    }, {
        "metric": "serve weighted fairness (contended-grant ratio "
                  "normalized by 2:1 weights; 1.0 = proportional)",
        "value": cold["load"]["fairness_ratio"] or 0.0,
        "unit": "x proportional share",
        "vs_baseline": 1.0,
        "contended_grants": cold["load"]["contended_grants"],
    }, {
        "metric": "serve attribution drift (sum of per-query attributed "
                  "launches - global counter delta; 0 = scope-exact)",
        "value": abs(cold["attributed_launches"]
                     - cold["global_launches"]),
        "unit": "launches",
        "vs_baseline": 1.0,
        "attributed": cold["attributed_launches"],
        "global": cold["global_launches"],
        "profiles": cold["profiles"],
        "overlapped_profiles": cold["overlapped_profiles"]
        + warm["overlapped_profiles"],
    }, {
        "metric": "serve warm-restart XLA disk misses (0 = replayed "
                  "load pays no cold compiles)",
        "value": warm["disk"]["compile.disk_miss"],
        "unit": "cold XLA compiles",
        "vs_baseline": 1.0,
        "cold_disk_misses": cold["disk"]["compile.disk_miss"],
        "warm_disk_hits": warm["disk"]["compile.disk_hit"],
        "warm_disk_hit_compiles": warm["disk_hit_compiles"],
    }, {
        "metric": "serve warm repeated-load kernel launches (0 = every "
                  "dashboard query answered by the result cache)",
        "value": warm["repeat"]["launches"],
        "unit": "launches",
        "vs_baseline": 1.0,
        "repeat_wall_ms": warm["repeat"]["wall_ms"],
        "result_cache_hits_warm": warm["repeat"]["result_cache_hits"],
    }]
    return out


# --------------------------------------------------------------------------

CONFIGS = {
    "groupby": bench_groupby,
    "sort": bench_sort,
    "join": bench_join,
    "shuffle": bench_shuffle,
    "adaptive": bench_adaptive,
    "mesh": bench_mesh,
    "encoded": bench_encoded,
    "whole_query": bench_whole_query,
    "mesh_whole": bench_mesh_whole,
    "serve_restart": bench_serve_restart,
    "serve": bench_serve,
    "tpcds": bench_tpcds,
}


def _emit(rec):
    """Flush each record as it's produced: a timed-out suite must still
    leave a valid evidence trail (r03 lost 3 of 6 metrics to rc=124)."""
    print(json.dumps(rec), flush=True)


def _fallback_to_cpu_child() -> int:
    """Accelerator is unhealthy: re-exec the suite in a provably-CPU child
    at smoke scale. The child env is scrubbed of every tunnel trigger
    (sitecustomize shadow + JAX_PLATFORMS=cpu) so neither the session nor
    any worker subprocess it spawns can dial the wedged tunnel."""
    import subprocess

    from __graft_entry__ import cpu_subprocess_env

    _emit({"metric": ("ACCELERATOR UNAVAILABLE — suite re-run on CPU at "
                      f"{min(SCALE, 0.01):g} scale; vs_baseline values "
                      "below are NOT TPU numbers"),
           "value": 0, "unit": "status", "vs_baseline": 0.0})
    env = cpu_subprocess_env()
    env["SPARK_TPU_BENCH_CHILD"] = "1"
    env["SPARK_TPU_BENCH_SCALE"] = str(min(SCALE, 0.01))
    env["SPARK_TPU_BENCH_TIMEOUT"] = str(min(_CONFIG_TIMEOUT_S, 300))
    env["SPARK_TPU_BENCH_BUDGET"] = str(min(_SUITE_BUDGET_S, 1500))
    # mode flags were stripped from sys.argv at import — re-append them
    # so the child keeps the requested trace/analyze/cluster behavior
    flags = [f for f, on in (("--analyze", ANALYZE), ("--trace", TRACE),
                             ("--cluster", CLUSTER),
                             ("--progress", PROGRESS),
                             ("--mesh", MESH),
                             ("--encoded", ENCODED),
                             ("--adaptive", ADAPTIVE),
                             ("--whole-query", WHOLE_QUERY),
                             ("--mesh-whole", MESH_WHOLE),
                             ("--serve-restart", SERVE_RESTART),
                             ("--serve", SERVE)) if on]
    try:  # stdout inherited: child lines flush straight to the driver
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)]
            + sys.argv[1:] + flags,
            env=env, timeout=min(_SUITE_BUDGET_S, 1800))
        return r.returncode
    except subprocess.TimeoutExpired:
        _emit({"metric": "bench suite CPU-fallback child timed out",
               "value": 0.001, "unit": "x baseline", "vs_baseline": 0.001})
        return 0


def main() -> int:
    t_start = time.monotonic()
    is_child = os.environ.get("SPARK_TPU_BENCH_CHILD") == "1"
    if SMOKE:
        is_child = True  # functional gate: forced-CPU, no device probe
    elif SERVE_LEG or SERVE_LOAD_LEG:
        pass  # restart child: platform decided by the parent's env
    elif not is_child and not _device_init_alive(30):
        return _fallback_to_cpu_child()

    import jax

    if is_child:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    if SERVE_LEG:
        # internal serve-restart child: one query-set run against the
        # shared cache dir, one SERVE-LEG json line, exit
        return _serve_leg()
    if SERVE_LOAD_LEG:
        # internal serve-load child: one concurrent serving run against
        # the shared cache dir, one SERVE-LOAD json line, exit
        return _serve_load_leg()

    default = [c for c in CONFIGS
               if not (SMOKE and c == "tpcds")
               and (MESH or c != "mesh")       # mesh config is opt-in
               and (ENCODED or c != "encoded")  # encoded too
               and (ADAPTIVE or c != "adaptive")  # and adaptive
               and (WHOLE_QUERY or c != "whole_query")  # and whole-query
               and (MESH_WHOLE or c != "mesh_whole")   # and mesh-whole
               and (SERVE_RESTART or c != "serve_restart")  # and restart
               and (SERVE or c != "serve")]  # and the serving load test
    only = sys.argv[1:] or default
    records, failed = [], []
    for name in only:
        remaining = _SUITE_BUDGET_S - (time.monotonic() - t_start)
        if remaining < 30:
            failed.append(name)
            _emit({"metric": f"{name} SKIPPED (suite budget exhausted)",
                   "value": 0, "unit": "error", "vs_baseline": 0.0})
            continue
        kc_before = _kernel_counters()
        try:
            r = _with_timeout(CONFIGS[name],
                              int(min(_CONFIG_TIMEOUT_S, remaining)))
        except Exception as e:  # keep the suite alive; record the failure
            failed.append(name)
            _emit({"metric": f"{name} FAILED",
                   "value": 0, "unit": "error",
                   "vs_baseline": 0.0,
                   "error": f"{type(e).__name__}: {e}"[:400]})
            continue
        recs = r if isinstance(r, list) else [r]
        if recs:
            _attach_kernel_delta(recs[0], kc_before)
        for rec in recs:
            if SCALE != 1.0:
                # scaled smoke runs compare against full-scale reference
                # numbers — flag the ratio as not meaningful
                rec["scale"] = SCALE
                rec["metric"] += f" [SCALED {SCALE:g}x — vs_baseline invalid]"
            records.append(rec)
            _emit(rec)
    if TRACE:
        try:
            from spark_tpu.obs.tracing import to_chrome_trace

            spans = []
            for t in _TRACE_TRACERS:
                spans.extend(t.spans())
            with open(TRACE_PATH, "w") as f:
                json.dump(to_chrome_trace(spans, process_name="bench"), f)
            _emit({"metric": "trace written", "value": len(spans),
                   "unit": "spans", "vs_baseline": 1.0,
                   "path": os.path.abspath(TRACE_PATH)})
        except Exception as e:  # tracing must never sink a bench run
            _emit({"metric": "trace FAILED", "value": 0, "unit": "error",
                   "vs_baseline": 0.0,
                   "error": f"{type(e).__name__}: {e}"[:200]})
    for s in _CLUSTER_SESSIONS:
        try:
            s.stop()
        except Exception:
            pass
    # floor at 0.001 so a catastrophically slow config drags the geomean
    # instead of vanishing from it (round() can produce exact 0.0)
    ok = [max(r["vs_baseline"], 0.001) for r in records]
    # failed configs drag the geomean honestly: each counts as 0.01x
    ok += [0.01] * len(failed)
    geo = math.exp(sum(math.log(v) for v in ok) / len(ok)) if ok else 0.0
    label = (f"bench suite geomean vs reference CPU baseline "
             f"({len(records)} metrics over {len(only)} configs")
    if is_child:
        label += "; CPU-FALLBACK, scaled, not TPU numbers"
    label += f"; FAILED: {','.join(failed)})" if failed else ")"
    _emit({
        "metric": label,
        "value": round(geo, 2),
        "unit": "x baseline",
        "vs_baseline": round(geo, 3),
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
