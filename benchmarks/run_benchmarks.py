#!/usr/bin/env python
"""Micro-benchmark harness.

Role of the reference's Benchmark harness + committed results
(sql/core/benchmarks/*-results.txt, SURVEY.md §4 'Benchmarks as tests'):
each case reports best/avg wall time and rows/s; results are written to
benchmarks/results/<name>-results.txt with the environment header so runs
are comparable across machines/backends.

Run: python benchmarks/run_benchmarks.py [--rows N] [--only case..]
"""

from __future__ import annotations

import argparse
import os
import platform
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _env_header():
    import jax

    return (f"backend={jax.default_backend()} devices={len(jax.devices())} "
            f"python={platform.python_version()} "
            f"machine={platform.machine()} {platform.system()}")


class Bench:
    def __init__(self, name: str, out_dir: str):
        self.name = name
        self.rows: list[str] = []
        self.out_dir = out_dir

    def case(self, label: str, n_rows: int, fn, iters: int = 5):
        fn()  # warm-up (compile)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        best = min(times)
        avg = sum(times) / len(times)
        rate = n_rows / best / 1e6
        line = (f"{label:<44} best {best * 1000:9.1f} ms   "
                f"avg {avg * 1000:9.1f} ms   {rate:9.1f} M rows/s")
        print(line)
        self.rows.append(line)

    def write(self):
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"{self.name}-results.txt")
        with open(path, "w") as f:
            f.write(f"# {self.name}\n# {_env_header()}\n")
            f.write("\n".join(self.rows) + "\n")
        print(f"→ {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=5_000_000)
    ap.add_argument("--only", nargs="*", default=None)
    args = ap.parse_args()
    n = args.rows

    import pyarrow as pa

    from spark_tpu import TpuSession
    import spark_tpu.api.functions as F
    from spark_tpu.api.dataframe import DataFrame
    from spark_tpu.io.sources import InMemorySource
    from spark_tpu.plan.logical import LogicalRelation
    from spark_tpu.expr.expressions import AttributeReference
    from spark_tpu.types import float64, int64

    session = TpuSession("microbench", {
        "spark.tpu.batch.capacity": 1 << 24,
        "spark.sql.shuffle.partitions": 1,
    })
    rng = np.random.default_rng(7)

    def device_df(table):
        src = InMemorySource(table, num_partitions=1)
        src.cache_device_batches = True
        types = {pa.int64(): int64, pa.float64(): float64}
        attrs = [AttributeReference(f.name, types[f.type], False)
                 for f in table.schema]
        df = DataFrame(session, LogicalRelation(src, attrs, "bench"))
        df.count()  # populate the device cache
        return df

    def run(df_query):
        parts = df_query.query_execution.execute()
        for p in parts:
            for b in p:
                for c in b.columns:
                    c.data.block_until_ready()

    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    only = set(args.only or [])

    # ---- aggregation -----------------------------------------------------
    if not only or "aggregate" in only:
        b = Bench("aggregate", out_dir)
        t = pa.table({
            "k_dense": rng.integers(0, 1 << 20, n).astype(np.int64),
            "k_sparse": (rng.integers(0, 1 << 20, n).astype(np.int64)
                         * 1_000_003),
            "v": rng.integers(0, 1000, n).astype(np.int64),
            "f": rng.random(n),
        })
        df = device_df(t)
        b.case("ungrouped sum+count", n,
               lambda: run(df.agg(F.sum("v").alias("s"),
                                  F.count("*").alias("c"))))
        b.case("groupBy dense keys (scatter path)", n,
               lambda: run(df.groupBy("k_dense").agg(F.sum("v").alias("s"))))
        b.case("groupBy sparse keys (sort path)", n,
               lambda: run(df.groupBy("k_sparse").agg(F.sum("v").alias("s"))))
        b.case("groupBy 2 aggs + avg", n,
               lambda: run(df.groupBy("k_dense").agg(
                   F.sum("v").alias("s"), F.avg("f").alias("a"))))
        b.write()

    # ---- filter/project --------------------------------------------------
    if not only or "compute" in only:
        b = Bench("compute", out_dir)
        t = pa.table({"x": rng.integers(0, 1000, n).astype(np.int64),
                      "y": rng.random(n)})
        df = device_df(t)
        b.case("filter x>500 + project x*2+y", n,
               lambda: run(df.filter(F.col("x") > 500)
                           .select((F.col("x") * 2).alias("a"),
                                   (F.col("y") + 1.0).alias("b"))))
        b.case("5-way fused arithmetic", n,
               lambda: run(df.select(
                   ((F.col("x") * 2 + 1) % 97).alias("a"),
                   (F.col("y") * F.col("y") + F.col("x")).alias("c"))))
        b.write()

    # ---- join ------------------------------------------------------------
    if not only or "join" in only:
        b = Bench("join", out_dir)
        nb = 1 << 16
        probe = device_df(pa.table({
            "k": rng.integers(0, nb, n).astype(np.int64),
            "v": rng.integers(0, 100, n).astype(np.int64)}))
        build = device_df(pa.table({
            "k": np.arange(nb, dtype=np.int64),
            "w": rng.integers(0, 100, nb).astype(np.int64)}))
        b.case("broadcast join dense 64k build", n,
               lambda: run(probe.join(build, on="k")))
        sparse_build = device_df(pa.table({
            "k": np.arange(nb, dtype=np.int64) * 1_000_003,
            "w": rng.integers(0, 100, nb).astype(np.int64)}))
        sparse_probe = device_df(pa.table({
            "k": (rng.integers(0, nb, n).astype(np.int64) * 1_000_003),
            "v": rng.integers(0, 100, n).astype(np.int64)}))
        b.case("broadcast join sparse keys (sorted probe)", n,
               lambda: run(sparse_probe.join(sparse_build, on="k")))
        b.write()

    # ---- sort ------------------------------------------------------------
    if not only or "sort" in only:
        b = Bench("sort", out_dir)
        t = pa.table({"x": rng.integers(0, 1 << 40, n).astype(np.int64),
                      "y": rng.random(n)})
        df = device_df(t)
        b.case("sort by int64", n, lambda: run(df.orderBy("x")))
        b.case("sort desc + secondary key", n,
               lambda: run(df.orderBy(F.col("x").desc(), F.col("y"))))
        b.case("topK 100", n, lambda: run(df.orderBy("x").limit(100)))
        b.write()


    # ---- shuffle ---------------------------------------------------------
    if not only or "shuffle" in only:
        b = Bench("shuffle", out_dir)
        session.conf.set("spark.sql.shuffle.partitions", 8)
        t = pa.table({"k": rng.integers(0, 1 << 20, n).astype(np.int64),
                      "v": rng.integers(0, 100, n).astype(np.int64)})
        src8 = InMemorySource(t, num_partitions=8)
        src8.cache_device_batches = True
        attrs = [AttributeReference(f.name, int64, False)
                 for f in t.schema]
        df8 = DataFrame(session, LogicalRelation(src8, attrs, "sh"))
        df8.count()
        b.case("hash shuffle 8->8 + final agg", n,
               lambda: run(df8.groupBy("k").agg(F.sum("v").alias("s"))))
        b.case("repartition round-robin 8->8", n,
               lambda: run(df8.repartition(8)))
        session.conf.set("spark.sql.shuffle.partitions", 1)
        b.write()

    # ---- TPC-DS q3 steady state -----------------------------------------
    if not only or "tpcds" in only:
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tests"))
        from tpcds_mini import gen_tpcds, register_tpcds

        b = Bench("tpcds", out_dir)
        n_sales = max(n // 2, 100_000)
        tables = gen_tpcds(n_sales=n_sales)
        register_tpcds(session, tables)
        q3 = """SELECT dt.d_year, item.i_brand_id AS brand_id,
                       SUM(ss_ext_sales_price) AS sum_agg
                FROM date_dim dt, store_sales, item
                WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
                  AND store_sales.ss_item_sk = item.i_item_sk
                  AND item.i_manufact_id = 28 AND dt.d_moy = 11
                GROUP BY dt.d_year, item.i_brand_id
                ORDER BY dt.d_year, sum_agg DESC LIMIT 100"""
        b.case(f"q3 shape over {n_sales} sales rows", n_sales,
               lambda: session.sql(q3).toArrow())
        b.write()

    session.stop()


if __name__ == "__main__":
    main()
