#!/usr/bin/env python
"""Profile grouped-sum kernel variants on the live chip.

Finds where bench.py's 6.1s/run goes: raw segment_sum (scatter) vs
sort-based vs the end-to-end query path.
"""

import sys
import time

import numpy as np

N = 10_000_000
G = 1 << 20
CAP = 1 << 24


def timeit(fn, *args, reps=3):
    out = fn(*args)
    import jax
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    jax.config.update("jax_enable_x64", True)
    print("backend:", jax.default_backend(), flush=True)

    rng = np.random.default_rng(42)
    k = np.zeros(CAP, np.int64)
    k[:N] = rng.integers(0, G, N)
    v = np.zeros(CAP, np.int64)
    v[:N] = rng.integers(0, 1000, N)
    m = np.zeros(CAP, bool)
    m[:N] = True
    kd, vd, md = jnp.asarray(k), jnp.asarray(v), jnp.asarray(m)
    out_cap = 1 << 21

    @jax.jit
    def dense_scatter(k, v, m):
        seg = jnp.where(m, k, out_cap - 1).astype(jnp.int32)
        tot = jax.ops.segment_sum(jnp.where(m, v, 0), seg,
                                  num_segments=out_cap)
        cnt = jax.ops.segment_sum(m.astype(jnp.int64), seg,
                                  num_segments=out_cap)
        return tot, cnt

    t = timeit(dense_scatter, kd, vd, md)
    print(f"dense segment_sum scatter: {t*1e3:.1f} ms = {N/t/1e6:.1f} M rows/s",
          flush=True)

    @jax.jit
    def sort_based(k, v, m):
        key = jnp.where(m, k, jnp.iinfo(jnp.int64).max)
        sk, sv = lax.sort((key, v), num_keys=1, is_stable=False)
        # segment starts where key changes
        prev = jnp.concatenate([sk[:1] - 1, sk[:-1]])
        starts = sk != prev
        gid = jnp.cumsum(starts.astype(jnp.int32)) - 1
        tot = jax.ops.segment_sum(sv, gid, num_segments=out_cap)
        return sk, tot

    t = timeit(sort_based, kd, vd, md)
    print(f"sort + seg-sum:            {t*1e3:.1f} ms = {N/t/1e6:.1f} M rows/s",
          flush=True)

    @jax.jit
    def just_sort(k, v):
        return lax.sort((k, v), num_keys=1, is_stable=False)

    t = timeit(just_sort, kd, vd)
    print(f"lax.sort only:             {t*1e3:.1f} ms", flush=True)

    @jax.jit
    def sorted_scan_diff(k, v, m):
        # sort, then segment sums via cumsum-diff at boundaries (no scatter)
        key = jnp.where(m, k, jnp.iinfo(jnp.int64).max)
        sk, sv = lax.sort((key, v), num_keys=1, is_stable=False)
        cs = jnp.cumsum(sv)
        is_last = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones(1, bool)])
        # per-row: cumsum at last row of each run; subtract previous run's end
        run_end_cs = jnp.where(is_last, cs, 0)
        return sk, run_end_cs

    t = timeit(sorted_scan_diff, kd, vd, md)
    print(f"sort + cumsum-diff:        {t*1e3:.1f} ms", flush=True)

    @jax.jit
    def pure_cumsum(v):
        return jnp.cumsum(v)

    t = timeit(pure_cumsum, vd)
    print(f"cumsum only 16M:           {t*1e3:.1f} ms", flush=True)

    # scatter with int32 data instead of int64
    @jax.jit
    def dense_scatter32(k, v, m):
        seg = jnp.where(m, k, out_cap - 1).astype(jnp.int32)
        tot = jax.ops.segment_sum(jnp.where(m, v, 0).astype(jnp.float32), seg,
                                  num_segments=out_cap)
        return tot

    t = timeit(dense_scatter32, kd, vd, md)
    print(f"scatter f32:               {t*1e3:.1f} ms", flush=True)

    # end-to-end query path
    sys.path.insert(0, ".")
    import pyarrow as pa
    from spark_tpu import TpuSession
    import spark_tpu.api.functions as F
    from spark_tpu.api.dataframe import DataFrame
    from spark_tpu.io.sources import InMemorySource
    from spark_tpu.plan.logical import LogicalRelation
    from spark_tpu.expr.expressions import AttributeReference
    from spark_tpu.types import int64 as i64t

    session = TpuSession("bench", {
        "spark.tpu.batch.capacity": 1 << 24,
        "spark.sql.shuffle.partitions": 1,
    })
    table = pa.table({"k": k[:N], "v": v[:N]})
    source = InMemorySource(table, num_partitions=1)
    source.cache_device_batches = True
    attrs = [AttributeReference(f.name, i64t, False) for f in table.schema]
    df = DataFrame(session, LogicalRelation(source, attrs, "bench"))

    def run_query():
        q = df.groupBy("k").agg(F.sum("v").alias("s"))
        t0 = time.perf_counter()
        parts = q.query_execution.execute()
        for part in parts:
            for b in part:
                for c in b.columns:
                    c.data.block_until_ready()
        return time.perf_counter() - t0

    run_query()
    ts = [run_query() for _ in range(3)]
    t = min(ts)
    print(f"end-to-end query:          {t*1e3:.1f} ms = {N/t/1e6:.1f} M rows/s",
          flush=True)

    # phase timing inside one run
    import spark_tpu.exec.query_execution as qe
    q = df.groupBy("k").agg(F.sum("v").alias("s"))
    t0 = time.perf_counter()
    plan = q.query_execution.executed_plan
    t1 = time.perf_counter()
    print(f"  planning: {(t1-t0)*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
